"""custom-storage: a user-defined persistence backend.

Parity with the reference's custom-storage example
(``/root/reference/examples/custom-storage/src/ping_state.rs``): the
framework's ``StateLoader``/``StateSaver`` boundary is a plugin seam — an
application can persist actor state in its *own* table/schema instead of the
framework's ``state_provider_object_state`` table.

Here ``PingStateStorage`` keeps ``PingState`` rows in a bespoke
``ping_state(object_id, pings, last_ping_at)`` sqlite table, and the
``PingService`` actor declares ``state = managed_state(PingState,
PingStateStorage)`` to route its persistence through it. A second cluster
boot proves state survives full process "restarts"::

    python examples/custom_storage.py
"""

import asyncio
import sqlite3
import sys
import time
from typing import Any

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.errors import StateNotFound
from rio_tpu.state import StateProvider


@message
class Ping:
    pass


@message
class PingState:
    pings: int = 0
    last_ping_at: float = 0.0


class PingStateStorage(StateProvider):
    """Custom backend: its own table, its own schema — not the framework's.

    Implements the same ``load/save/delete`` surface as the built-in
    providers, which is all ``managed_state`` needs.
    """

    def __init__(self, path: str) -> None:
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS ping_state ("
            "object_id TEXT PRIMARY KEY, pings INTEGER NOT NULL, "
            "last_ping_at REAL NOT NULL)"
        )
        self._db.commit()

    async def load(self, object_kind: str, object_id: str, state_type: str, ty: Any) -> Any:
        row = self._db.execute(
            "SELECT pings, last_ping_at FROM ping_state WHERE object_id=?",
            (object_id,),
        ).fetchone()
        if row is None:
            raise StateNotFound(object_id)
        return PingState(pings=row[0], last_ping_at=row[1])

    async def save(self, object_kind: str, object_id: str, state_type: str, value: Any) -> None:
        self._db.execute(
            "INSERT INTO ping_state (object_id, pings, last_ping_at) VALUES (?,?,?) "
            "ON CONFLICT(object_id) DO UPDATE SET "
            "pings=excluded.pings, last_ping_at=excluded.last_ping_at",
            (object_id, value.pings, value.last_ping_at),
        )
        self._db.commit()

    async def delete(self, object_kind: str, object_id: str, state_type: str) -> None:
        self._db.execute("DELETE FROM ping_state WHERE object_id=?", (object_id,))
        self._db.commit()


from rio_tpu.state import managed_state  # noqa: E402 (after PingStateStorage exists)


class PingService(ServiceObject):
    state = managed_state(PingState, PingStateStorage)

    @handler
    async def ping(self, msg: Ping, ctx: AppData) -> PingState:
        self.state.pings += 1
        self.state.last_ping_at = time.time()
        await self.save_state(ctx)
        return self.state


async def boot_and_ping(db_path: str, n_pings: int) -> PingState:
    """Boot a fresh 1-node cluster, ping, tear down (a 'process restart')."""
    members = LocalStorage()
    placement = LocalObjectPlacement()
    server = Server(
        address="127.0.0.1:0",
        registry=Registry().add_type(PingService),
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=placement,
    )
    server.app_data.set(PingStateStorage(db_path), as_type=PingStateStorage)
    await server.prepare()
    await server.bind()
    task = asyncio.create_task(server.run())
    await asyncio.sleep(0.1)
    client = Client(members)
    state = None
    for _ in range(n_pings):
        state = await client.send(PingService, "pingu", Ping(), returns=PingState)
    client.close()
    task.cancel()
    await asyncio.gather(task, return_exceptions=True)
    return state


async def main() -> None:
    db = "custom_storage_demo.db"
    import os

    if os.path.exists(db):
        os.remove(db)
    s1 = await boot_and_ping(db, 3)
    print(f"[run 1] pings={s1.pings}")
    s2 = await boot_and_ping(db, 2)  # brand-new cluster, same table
    print(f"[run 2] pings={s2.pings} (state survived the restart)")
    assert s2.pings == 5
    row = sqlite3.connect(db).execute(
        "SELECT object_id, pings FROM ping_state"
    ).fetchall()
    print(f"[demo] custom table contents: {row}")
    os.remove(db)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
