"""ping-pong: the minimal rio-tpu application.

Parity with the reference's ping-pong example
(``/root/reference/examples/ping-pong``): a ``PingService`` actor that
answers ``Ping`` with ``Pong`` and shuts itself down after 3 requests.

Runs a 2-node cluster (real TCP on loopback, shared in-memory membership)
and a cluster-transparent client in one process::

    python examples/ping_pong.py
"""

import asyncio
import sys

sys.path.insert(0, ".")  # run from repo root without installing

from rio_tpu import (
    AppData,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider


@message
class Ping:
    ping_id: int = 0


@message
class Pong:
    ping_id: int = 0
    served: int = 0
    server: str = ""


class PingService(ServiceObject):
    """Answers pings; self-destructs after 3 (reference services.rs:10-37)."""

    def __init__(self):
        self.served = 0

    @handler
    async def ping(self, msg: Ping, ctx: AppData) -> Pong:
        from rio_tpu import ServerInfo

        self.served += 1
        if self.served >= 3:
            await self.shutdown(ctx)  # deallocate after this response
        return Pong(ping_id=msg.ping_id, served=self.served, server=ctx.get(ServerInfo).address)


def build_registry() -> Registry:
    return Registry().add_type(PingService)


async def main() -> None:
    members = LocalStorage()
    placement = LocalObjectPlacement()

    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
        )
        await s.prepare()
        addr = await s.bind()
        print(f"[server] listening on {addr}")
        servers.append(s)

    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    from rio_tpu import ClientBuilder

    client = ClientBuilder().members_storage(members).build()
    for i in range(7):
        pong = await client.send(PingService, "pingu", Ping(ping_id=i), returns=Pong)
        print(f"[client] ping {i} -> pong served={pong.served} by {pong.server}")

    client.close()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
