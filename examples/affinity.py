"""affinity: communication-aware placement that cuts bytes over TCP.

The edge sampler (``rio_tpu/affinity``, on by default in every server)
watches *who talks to whom* at the dispatch path: each served request
records a ``(source actor | "client", target actor)`` edge with EMA
byte/call rates. This demo closes the full feedback loop on a live
2-node cluster:

1. **workload** — 8 ``Front`` actors each forward every request to a
   partner ``Back`` actor, local-first (the cursor/saga delivery idiom:
   try the in-server dispatch queue; only a REDIRECT falls back to a
   cluster client and stamps the edge sender-side).
2. **adversarial seating** — the directory is pre-seated load-BALANCED
   but pair-SPLIT: every Front on one node, its Back on the other, so a
   load-only solver has no reason to move anything while every forward
   crosses TCP.
3. **scrape → merge → solve** — per-node graphs come back over the wire
   via the admin ``DumpEdges`` command (``cluster_edges`` merges them;
   the ``python -m rio_tpu.admin edges`` CLI renders the same view),
   ``set_edge_graph`` installs the merged graph, and a full rebalance
   runs the alternating linearized-OT refine on top of the unchanged
   Sinkhorn core.
4. **payoff** — identical traffic again: every pair is now co-seated,
   forwards resolve in-process, and the TCP byte counters collapse. The
   demo asserts co-location and a >= 2x bytes-over-TCP drop.

Run::

    python examples/affinity.py
"""

import asyncio
import sys

sys.path.insert(0, ".")  # run from repo root without installing

from rio_tpu import (
    AppData,
    Client,
    LocalStorage,
    ObjectId,
    ObjectPlacementItem,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.affinity import EdgeSampler
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.errors import HandlerError
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
from rio_tpu.registry import type_id

N_PAIRS = 8
ROUNDS = 40
PAD = 2048


@message
class Work:
    seq: int = 0
    pad: bytes = b""


@message
class Ack:
    seq: int = 0


class Back(ServiceObject):
    """The chatty partner: receives the padded forwards."""

    @handler
    async def work(self, msg: Work, ctx: AppData) -> Ack:
        return Ack(seq=msg.seq)


class Front(ServiceObject):
    """Forwards every request to its partner Back, local-first.

    Inside a dispatched handler the affinity source is already bound to
    this actor's identity, so the in-process leg needs no extra code: the
    partner's dispatch records the ``Front.i -> Back.i`` edge by itself.
    Only the remote fallback leg stamps the edge explicitly — the wire
    carries no source identity, so the receiving node would otherwise
    attribute it to ``"client"``.
    """

    def __init__(self) -> None:
        self._remote = False

    @handler
    async def work(self, msg: Work, ctx: AppData) -> Ack:
        # The client's trigger frame is small; the Front fattens the
        # payload it pushes to its partner — so the Front->Back leg is
        # the traffic that matters, exactly the shape co-location fixes.
        fat = Work(seq=msg.seq, pad=b"\x00" * PAD)
        if not self._remote:
            try:
                return await self.send(ctx, Back, self.id, fat, returns=Ack)
            except HandlerError as e:
                if not str(e).startswith("REDIRECT"):
                    raise
                self._remote = True  # seated elsewhere; go remote
        ack = await ctx.get(Client).send(Back, self.id, fat, returns=Ack)
        sampler = ctx.try_get(EdgeSampler)
        if sampler is not None:
            sampler.observe(
                f"{type_id(Front)}.{self.id}",
                f"{type_id(Back)}.{self.id}",
                len(fat.pad),
                False,
            )
        return ack


async def main() -> dict:
    members = LocalStorage()
    # The graph term is priced per edge against the stay-put move_cost;
    # host_factor is ~zeroed because both "nodes" share this host yet the
    # loopback sockets between them still carry every byte (the shipping
    # 0.5 default is for real multi-host topologies).
    placement = JaxObjectPlacement(
        node_axis_size=4,
        mode="greedy",
        affinity_weight=2.0,
        affinity_host_factor=0.05,
    )
    servers: list[Server] = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(Front).add_type(Back),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            # Demo-speed fidelity: sample every dispatch instead of the
            # shipping 1-in-8 stride, so a short run sees every edge.
            affinity_stride=1,
        )
        await s.prepare()
        print(f"[server] node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    node0, node1 = (s.local_address for s in servers)
    for addr in (node0, node1):
        placement.register_node(addr)
    # Load-balanced but pair-split: the worst seating for bytes-over-TCP
    # that a load-only solver would still call perfect.
    for i in range(N_PAIRS):
        await placement.update(
            ObjectPlacementItem(ObjectId(type_id(Front), str(i)), node0 if i % 2 else node1)
        )
        await placement.update(
            ObjectPlacementItem(ObjectId(type_id(Back), str(i)), node1 if i % 2 else node0)
        )

    client = Client(members)
    for s in servers:  # the Fronts' remote-fallback leg
        s.app_data.set(Client(members))

    async def drive(rounds: int) -> None:
        for r in range(rounds):
            for i in range(N_PAIRS):
                await client.send(Front, str(i), Work(seq=r), returns=Ack)

    def tcp_total() -> int:
        return sum(
            s.affinity.tcp_in_bytes + s.affinity.tcp_out_bytes for s in servers
        )

    await drive(4)  # warm: activate every pair on its adversarial seat

    t0 = tcp_total()
    await drive(ROUNDS)
    blind = tcp_total() - t0
    print(f"[blind]    {blind} bytes over TCP ({ROUNDS * N_PAIRS} requests)")

    # Scrape every node's edge graph over the wire and merge — exactly
    # what `python -m rio_tpu.admin edges` renders for an operator.
    from rio_tpu.admin import cluster_edges

    rows = await cluster_edges(client, members)
    actor_rows = [r for r in rows if r[0] != "client"]
    print(f"[edges]    {len(rows)} merged edges; hottest actor-to-actor:")
    for src, dst, bps, cps, lf in actor_rows[:4]:
        print(f"           {src} -> {dst}  {bps:,.0f} B/s  {cps:.1f} call/s  local={lf:.2f}")

    installed = placement.set_edge_graph(rows)
    moves = await placement.rebalance(delta=False)
    print(f"[solve]    {installed} edges installed, {moves} moves, mode={placement.stats.mode}")
    for h in placement._affinity_history:
        print(
            f"           pass {h['pass']}: cut={h['cut']:.4f} "
            f"total={h['total']:.4f} accepted={h['accepted']}"
        )

    pairs_local = 0
    for i in range(N_PAIRS):
        f = await placement.lookup(ObjectId(type_id(Front), str(i)))
        b = await placement.lookup(ObjectId(type_id(Back), str(i)))
        pairs_local += int(f == b)
    print(f"[place]    {pairs_local}/{N_PAIRS} pairs co-located")

    await drive(4)  # settle: activations follow the new directory

    t0 = tcp_total()
    await drive(ROUNDS)
    after = tcp_total() - t0
    ratio = blind / max(after, 1)
    print(f"[affinity] {after} bytes over TCP — {ratio:.1f}x fewer than blind")

    client.close()
    for s in servers:
        s.app_data.get(Client).close()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)

    assert pairs_local == N_PAIRS, f"only {pairs_local}/{N_PAIRS} pairs co-located"
    assert ratio >= 2.0, f"bytes-over-TCP ratio {ratio:.2f} < 2x"
    print("[demo] done")
    return {"blind": blind, "affinity": after, "ratio": ratio, "pairs": pairs_local}


if __name__ == "__main__":
    asyncio.run(main())
