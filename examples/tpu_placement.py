"""tpu-placement: the flagship JaxObjectPlacement provider, live.

No counterpart in the reference — its placement is a random pick plus
row-by-row SQL (``rio-rs/src/client/mod.rs:255-262``,
``object_placement/sqlite.rs:68-100``). This demo boots a real cluster on
the TPU-native directory and shows the three behaviors that replace it:

1. **Directory routing** — clients resolve the owner from the host-mirrored
   directory before dialing: 1 network hop, no redirect round trip.
2. **Server-owned churn response** — kill a node; the opt-in
   ``placement_daemon`` watches liveness and triggers a warm-started OT
   re-solve that moves ONLY the displaced objects (stay-put discount) —
   zero application solver calls.
3. **Affinity** — the provider carries an AffinityTracker; the server
   auto-observes every served request into it, pulling objects back to
   the nodes that served them (cache warmth) while capacity keeps load
   balanced.

Runs on CPU out of the box (JAX_PLATFORMS=cpu); the same code jit-compiles
the solve onto a TPU when one is attached::

    python examples/tpu_placement.py
"""

import asyncio
import sys

sys.path.insert(0, ".")  # run from repo root without installing

from rio_tpu import (
    AppData,
    Client,
    LocalStorage,
    ObjectId,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.commands import AdminCommand
from rio_tpu.object_placement.jax_placement import AffinityTracker, JaxObjectPlacement
from rio_tpu.placement_daemon import PlacementDaemonConfig

N_SERVERS = 5
N_OBJECTS = 200


@message
class Hit:
    n: int = 0


@message
class HitCount:
    n: int = 0
    server: str = ""


class CounterActor(ServiceObject):
    def __init__(self):
        self.hits = 0

    @handler
    async def hit(self, msg: Hit, ctx: AppData) -> HitCount:
        from rio_tpu import ServerInfo

        self.hits += msg.n
        return HitCount(n=self.hits, server=ctx.get(ServerInfo).address)


async def main() -> None:
    members = LocalStorage()
    tracker = AffinityTracker(dim=32)
    # Hierarchical mode consumes the tracker's feature hooks — the
    # observed-traffic affinity steers the 2-level OT solve. Carrying the
    # tracker on the provider makes the Server auto-wire observation into
    # its dispatch path (rio_tpu/commands.py DispatchObserver).
    placement = JaxObjectPlacement(
        mode="hierarchical",
        n_iters=20,
        affinity_tracker=tracker,
    )

    servers: list[Server] = []
    for _ in range(N_SERVERS):
        s = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(CounterActor),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            # Churn response with zero app code: watch liveness, re-solve.
            placement_daemon=True,
            placement_daemon_config=PlacementDaemonConfig(
                poll_interval=0.1, debounce=0.05, min_rebalance_interval=0.1
            ),
        )
        await s.prepare()
        await s.bind()
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.3)
    placement.sync_members(await members.active_members())

    # Directory-routing client: resolve the owner before dialing.
    client = Client(
        members,
        placement_resolver=lambda t, i: placement.lookup(ObjectId(t, i)),
    )

    print(f"[demo] driving {N_OBJECTS} actors over {N_SERVERS} servers")
    for i in range(N_OBJECTS):
        # NOTE: no tracker.observe here — the serving node records it.
        await client.send(CounterActor, f"c{i}", Hit(n=1), returns=HitCount)
    print(
        f"[demo] {client.stats.requests} requests took "
        f"{client.stats.roundtrips} hops ({client.stats.redirects} redirects)"
    )

    # Kill a node. A cleanly-exiting server deregisters itself from
    # membership (Server.run's finally); from there the PLACEMENT DAEMON
    # does everything: sees the liveness change, syncs the solver, and
    # triggers the warm-started re-solve. Zero application code.
    victim = servers[0]
    epoch0 = placement.stats.epoch  # snapshot BEFORE the churn event
    print(f"[demo] killing {victim.local_address}")
    victim.admin_sender().queue.put_nowait(AdminCommand.server_exit())
    for _ in range(600):  # the daemon's first real solve includes jit compile
        # A discarded attempt is a stats event too — wait for a COMPLETED
        # solve (the daemon retries after a discard).
        if (
            placement.stats.epoch != epoch0
            and placement.stats.n_objects
            and not placement.stats.discarded
        ):
            break
        await asyncio.sleep(0.05)
    else:
        raise SystemExit("[demo] FAILED: the placement daemon never re-solved")
    moved = placement.stats.moved
    print(
        f"[demo] daemon re-solve in {placement.stats.solve_ms:.1f} ms: moved "
        f"{moved} of {placement.stats.n_objects} objects (only the displaced "
        f"share) — zero app-level solver calls"
    )

    # Every actor still answers, state intact where the node survived.
    survivors = 0
    for i in range(N_OBJECTS):
        out = await client.send(CounterActor, f"c{i}", Hit(n=1), returns=HitCount)
        if out.n == 2:
            survivors += 1
    print(
        f"[demo] all {N_OBJECTS} actors reachable after churn; "
        f"{survivors} kept in-memory state (rest re-materialized)"
    )

    client.close()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
