"""Multi-host placement solve: the same program from 1 chip to a pod.

The SPMD bring-up recipe for the solver plane (see
``rio_tpu/parallel/multihost.py``). Run it three ways — the PROGRAM TEXT
is identical in all of them, which is the point:

1. Single process (laptop / one chip)::

       python examples/multihost_solve.py

2. Two processes on one machine (real multi-controller over loopback —
   what tests/test_multihost.py does)::

       python examples/multihost_solve.py --coordinator 127.0.0.1:9911 \
           --num-processes 2 --process-id 0 &
       python examples/multihost_solve.py --coordinator 127.0.0.1:9911 \
           --num-processes 2 --process-id 1

3. A TPU pod (one process per host; the pod runtime supplies the cluster
   env, so no arguments are needed)::

       python examples/multihost_solve.py   # on every host

Where the reference stack would initialize NCCL/MPI communicators and
hand-shard tensors, here :func:`multihost.initialize` joins the hosts into
one jax runtime and the SAME ``shard_map`` solve spans all of them — XLA
routes the collectives (ICI in-slice, DCN across).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None, help="host:port of process 0")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--objects-per-device", type=int, default=4096)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from rio_tpu.parallel import make_mesh, multihost
    from rio_tpu.parallel.hierarchical import sharded_hierarchical_assign

    multi = multihost.initialize(
        args.coordinator, args.num_processes, args.process_id
    )
    if not multi and args.coordinator is None:
        # Single-process demo (initialize() found no cluster and touched
        # no backend): this example is about the SPMD structure, so pin
        # the well-behaved CPU backend (8 virtual devices) rather than
        # whatever accelerator plugin the ambient env wires in — the
        # single-chip accelerator demos live in the other examples.
        from rio_tpu.utils.jaxenv import force_cpu

        force_cpu(n_devices=8)
    me = jax.process_index()
    print(
        f"[host {me}] processes={jax.process_count()} "
        f"global_devices={jax.device_count()} local={jax.local_device_count()} "
        f"(multihost={multi})"
    )

    mesh = make_mesh()  # spans every host's devices
    n_obj = args.objects_per_device * jax.device_count()
    d, m, g = 16, 64, 8
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    # Every host derives the same global inputs, then feeds ONLY its rows
    # (in production these rows come from the host's own directory shard).
    obj_all = np.asarray(jax.random.normal(k1, (n_obj, d), jnp.float32))
    node_feat = np.asarray(jax.random.normal(k2, (d, m), jnp.float32)) * 0.2
    rows = multihost.process_rows(n_obj, mesh)
    axes = tuple(mesh.axis_names)
    obj_feat = multihost.distributed_array(mesh, P(axes, None), obj_all[rows])
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32).at[5].set(0.0)  # one dead node

    res = sharded_hierarchical_assign(
        mesh, obj_feat, node_feat, cap, alive, n_groups=g
    )
    jax.block_until_ready(res.assignment)

    from jax.experimental import multihost_utils

    if jax.process_count() > 1:
        a = np.asarray(
            multihost_utils.process_allgather(res.assignment, tiled=True)
        )
    else:
        a = np.asarray(res.assignment)
    loads = np.bincount(a, minlength=m)
    print(
        f"[host {me}] placed {n_obj} objects on {m - 1} live nodes: "
        f"load min/max = {loads[loads > 0].min()}/{loads.max()}, "
        f"dead-node load = {loads[5]}, overflow = {int(res.overflow)}"
    )
    assert loads[5] == 0 and int(res.overflow) == 0


if __name__ == "__main__":
    main()
