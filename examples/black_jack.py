"""black-jack: a full game built on rio-tpu actors.

Parity with the reference's black-jack example
(``/root/reference/examples/black-jack``):

* a ``Cassino`` actor spawns per-game ``GameTable`` actors with uuid ids
  (``src/services/mod.rs``);
* each table runs its game engine on a **dedicated OS thread** bridged to
  the actor with thread-safe queues — the reference runs a bevy ECS loop
  on a spawned thread bridged with crossbeam channels
  (``src/services/table.rs:54-99``);
* every state transition is **published** to subscribers via the
  ``MessageRouter`` (``table.rs:72-86``);
* the thread's lifecycle is tied to the actor's ``after_load`` /
  ``before_shutdown`` hooks (``table.rs:104-131``);
* game *rules* are plain, framework-free code, unit-tested directly
  (``tests/game.rs``) — see ``tests/test_black_jack.py``.

Run a demo game::

    python examples/black_jack.py
"""

from __future__ import annotations

import asyncio
import dataclasses
import queue
import random
import sys
import threading
import uuid

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    MessageRouter,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
    type_id,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider

# ---------------------------------------------------------------------------
# Game rules — pure, framework-free (reference examples/black-jack/src/game.rs
# shape; unit-tested in tests/test_black_jack.py like tests/game.rs)
# ---------------------------------------------------------------------------

SUITS = "♠♥♦♣"
RANKS = ["A", "2", "3", "4", "5", "6", "7", "8", "9", "10", "J", "Q", "K"]


def card_value(rank: str) -> int:
    if rank == "A":
        return 11  # soft; hand_value demotes to 1 as needed
    if rank in ("J", "Q", "K"):
        return 10
    return int(rank)


def hand_value(cards: list[str]) -> int:
    """Best blackjack value ≤21 if possible (aces count 11 then demote)."""
    ranks = [c.rstrip("♠♥♦♣") for c in cards]
    total = sum(card_value(r) for r in ranks)
    aces = sum(1 for r in ranks if r == "A")
    while total > 21 and aces:
        total -= 10
        aces -= 1
    return total


def is_blackjack(cards: list[str]) -> bool:
    return len(cards) == 2 and hand_value(cards) == 21


class Deck:
    """Seeded 52-card deck; deterministic for tests."""

    def __init__(self, seed: int | None = None) -> None:
        self.cards = [f"{r}{s}" for s in SUITS for r in RANKS]
        random.Random(seed).shuffle(self.cards)

    def draw(self) -> str:
        return self.cards.pop()


def dealer_should_hit(cards: list[str]) -> bool:
    """House policy: draw to 17 (stand on all 17s)."""
    return hand_value(cards) < 17


def settle(player: list[str], dealer: list[str]) -> str:
    """Outcome from the player's perspective. A natural (two-card 21)
    beats any made 21; natural vs natural pushes."""
    pv, dv = hand_value(player), hand_value(dealer)
    if pv > 21:
        return "player_bust"
    if is_blackjack(player) and not is_blackjack(dealer):
        return "player_blackjack"
    if is_blackjack(dealer) and not is_blackjack(player):
        return "dealer_win"
    if dv > 21:
        return "dealer_bust"
    if pv > dv:
        return "player_win"
    if pv < dv:
        return "dealer_win"
    return "push"


@dataclasses.dataclass
class GameState:
    """One table's full state; snapshots of this are published to subscribers."""

    table_id: str = ""
    phase: str = "waiting"  # waiting -> player_turn -> settled
    player: str = ""
    player_cards: list[str] = dataclasses.field(default_factory=list)
    dealer_cards: list[str] = dataclasses.field(default_factory=list)
    outcome: str = ""

    def visible_dealer(self) -> list[str]:
        """Dealer shows one card until the hand settles."""
        if self.phase == "settled" or len(self.dealer_cards) < 2:
            return list(self.dealer_cards)
        return [self.dealer_cards[0], "??"]


class GameEngine:
    """The rules engine a table thread runs. Synchronous and deterministic."""

    def __init__(self, table_id: str, seed: int | None = None) -> None:
        self.deck = Deck(seed)
        self.state = GameState(table_id=table_id)

    def apply(self, cmd: str, arg: str = "") -> GameState:
        s = self.state
        if cmd == "join" and s.phase == "waiting":
            s.player = arg
            s.player_cards = [self.deck.draw(), self.deck.draw()]
            s.dealer_cards = [self.deck.draw(), self.deck.draw()]
            if is_blackjack(s.player_cards):
                self._dealer_play()
            else:
                s.phase = "player_turn"
        elif cmd == "hit" and s.phase == "player_turn":
            s.player_cards.append(self.deck.draw())
            if hand_value(s.player_cards) > 21:
                s.phase = "settled"
                s.outcome = "player_bust"
        elif cmd == "stand" and s.phase == "player_turn":
            self._dealer_play()
        elif cmd == "snapshot":
            pass
        else:
            raise ValueError(f"command {cmd!r} invalid in phase {s.phase!r}")
        return dataclasses.replace(
            s,
            player_cards=list(s.player_cards),
            dealer_cards=list(s.dealer_cards),
        )

    def _dealer_play(self) -> None:
        s = self.state
        while dealer_should_hit(s.dealer_cards):
            s.dealer_cards.append(self.deck.draw())
        s.phase = "settled"
        s.outcome = settle(s.player_cards, s.dealer_cards)


# ---------------------------------------------------------------------------
# Messages
# ---------------------------------------------------------------------------


@message
class OpenTable:
    seed: int = -1  # -1 → random


@message
class TableOpened:
    table_id: str = ""


@message
class Join:
    player: str = ""


@message
class Hit:
    pass


@message
class Stand:
    pass


@message
class TableView:
    table_id: str = ""
    phase: str = ""
    player: str = ""
    player_cards: list[str] = dataclasses.field(default_factory=list)
    dealer_cards: list[str] = dataclasses.field(default_factory=list)  # visible
    player_value: int = 0
    outcome: str = ""


def view_of(state: GameState) -> TableView:
    return TableView(
        table_id=state.table_id,
        phase=state.phase,
        player=state.player,
        player_cards=list(state.player_cards),
        dealer_cards=state.visible_dealer(),
        player_value=hand_value(state.player_cards),
        outcome=state.outcome,
    )


# ---------------------------------------------------------------------------
# Actors
# ---------------------------------------------------------------------------


class Cassino(ServiceObject):
    """Front desk: opens tables (reference Cassino spawning GameTables)."""

    def __init__(self) -> None:
        super().__init__()
        self.tables_opened = 0

    @handler
    async def open_table(self, msg: OpenTable, ctx: AppData) -> TableOpened:
        table_id = uuid.uuid4().hex
        self.tables_opened += 1
        # Activate the table actor (actor-to-actor send through the server's
        # internal client, reference service_object.rs:52-83) and seed it.
        await ServiceObject.send(
            ctx, GameTable, table_id, SetSeed(seed=msg.seed), returns=SeedAck,
        )
        return TableOpened(table_id=table_id)


@message
class SetSeed:
    seed: int = -1


@message
class SeedAck:
    pass


class _TableThread:
    """Dedicated OS thread driving a GameEngine; queue-bridged.

    Commands go in through a thread-safe queue and each carries its own
    reply slot; every resulting state snapshot is also pushed to an event
    queue that the actor pumps into the MessageRouter (the reference's
    crossbeam in/out channel pair, table.rs:54-99).
    """

    _STOP = object()

    def __init__(self, table_id: str, seed: int | None) -> None:
        self.commands: queue.Queue = queue.Queue()
        self.events: queue.Queue = queue.Queue()
        self.engine = GameEngine(table_id, seed)
        self.thread = threading.Thread(
            target=self._run, name=f"table-{table_id[:8]}", daemon=True
        )
        self.thread.start()

    def _run(self) -> None:
        while True:
            item = self.commands.get()
            if item is self._STOP:
                self.events.put(self._STOP)
                return
            cmd, arg, reply = item
            try:
                snapshot = self.engine.apply(cmd, arg)
                reply["state"] = snapshot
            except Exception as e:  # noqa: BLE001 — forwarded to the actor
                reply["error"] = e
            finally:
                reply["done"].set()
            if "state" in reply and cmd != "snapshot":
                self.events.put(reply["state"])

    async def ask(self, cmd: str, arg: str = "") -> GameState:
        reply: dict = {"done": threading.Event()}
        self.commands.put((cmd, arg, reply))
        await asyncio.to_thread(reply["done"].wait)
        if "error" in reply:
            raise reply["error"]
        return reply["state"]

    def stop(self) -> None:
        self.commands.put(self._STOP)
        self.thread.join(timeout=5)


class GameTable(ServiceObject):
    """One table == one actor == one engine thread (uuid-addressed)."""

    def __init__(self) -> None:
        super().__init__()
        self._table: _TableThread | None = None
        self._pump: asyncio.Task | None = None
        self._seed: int | None = None

    async def after_load(self, ctx: AppData) -> None:
        self._table = _TableThread(self.id, self._seed)
        self._pump = asyncio.create_task(self._pump_events(ctx))

    async def before_shutdown(self, ctx: AppData) -> None:
        # Reference table.rs:104-131: join the thread on actor shutdown.
        if self._pump is not None:
            self._pump.cancel()
        if self._table is not None:
            await asyncio.to_thread(self._table.stop)
            self._table = None

    async def _pump_events(self, ctx: AppData) -> None:
        """Engine thread → MessageRouter bridge (reference table.rs:72-86).

        Polls with a short timeout rather than blocking forever so that a
        cancelled pump never strands an executor thread in ``queue.get``.
        """
        router = ctx.get(MessageRouter)
        table = self._table
        assert table is not None
        while True:
            try:
                state = await asyncio.to_thread(table.events.get, True, 0.25)
            except queue.Empty:
                continue
            if state is _TableThread._STOP:
                return
            router.publish(type_id(GameTable), self.id, view_of(state))

    @handler
    async def set_seed(self, msg: SetSeed, ctx: AppData) -> SeedAck:
        if self._table is not None and msg.seed >= 0:
            # Re-arm the engine with the requested seed (table was activated
            # with a random deck before the seed arrived).
            await asyncio.to_thread(self._table.stop)
            self._seed = msg.seed
            self._table = _TableThread(self.id, self._seed)
            if self._pump is not None:
                self._pump.cancel()
            self._pump = asyncio.create_task(self._pump_events(ctx))
        return SeedAck()

    @handler
    async def join(self, msg: Join, ctx: AppData) -> TableView:
        assert self._table is not None
        return view_of(await self._table.ask("join", msg.player))

    @handler
    async def hit(self, msg: Hit, ctx: AppData) -> TableView:
        assert self._table is not None
        return view_of(await self._table.ask("hit"))

    @handler
    async def stand(self, msg: Stand, ctx: AppData) -> TableView:
        assert self._table is not None
        return view_of(await self._table.ask("stand"))


def build_registry() -> Registry:
    return Registry().add_type(Cassino).add_type(GameTable)


# ---------------------------------------------------------------------------
# Demo: open a table, subscribe to it, play a hand
# ---------------------------------------------------------------------------


async def main() -> None:
    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
        )
        await s.prepare()
        print(f"[server] cassino node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    client = Client(members)
    opened = await client.send(Cassino, "main", OpenTable(seed=7), returns=TableOpened)
    tid = opened.table_id
    print(f"[cassino] table {tid[:8]} opened")

    stream = await client.subscribe(GameTable, tid)

    async def watch() -> None:
        async for update in stream:
            print(
                f"[pubsub] phase={update.phase:<12} player={update.player_cards} "
                f"({update.player_value}) dealer={update.dealer_cards} "
                f"{update.outcome or ''}"
            )
            if update.phase == "settled":
                return

    watcher = asyncio.create_task(watch())
    await asyncio.sleep(0.2)

    view = await client.send(GameTable, tid, Join(player="ada"), returns=TableView)
    print(f"[player] dealt {view.player_cards} = {view.player_value}")
    while view.phase == "player_turn" and view.player_value < 17:
        view = await client.send(GameTable, tid, Hit(), returns=TableView)
        print(f"[player] hit -> {view.player_cards} = {view.player_value}")
    if view.phase == "player_turn":
        view = await client.send(GameTable, tid, Stand(), returns=TableView)
    print(f"[result] {view.outcome}: dealer had {view.dealer_cards}")

    try:
        await asyncio.wait_for(watcher, timeout=5)
    except asyncio.TimeoutError:
        watcher.cancel()

    client.close()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
