"""observability: exporting request-path trace spans.

Parity with the reference's observability example
(``/root/reference/examples/observability/src/bin/observability_server.rs:37-63``),
which wires ``tracing_subscriber`` + an OpenTelemetry OTLP layer into
Jaeger. rio-tpu's span taxonomy mirrors the reference's
(``frame_receive``, ``placement_lookup``, ``handler_handle``, …, see
``rio_tpu/tracing.py``); sinks are pluggable the same way the reference's
subscriber layers are. This demo registers two sinks:

* the built-in ``logging_sink`` (the reference's fmt layer), and
* an in-process aggregator standing where an OTLP exporter would go —
  any callable ``Span -> None`` can forward to a collector.

Spans cover the request path; the *counter* side of observability is
``rio_tpu.otel.server_gauges``: one flat snapshot of every wired
subsystem's stats (placement daemon, reminder daemon, migration manager,
solver, and — since servers run a ``LoadMonitor`` by default — the local
load sample and admission-control shed counter (``rio.load.*``) plus the
gossip-derived ``rio.cluster_load.<addr>.*`` view of every peer's
lag/inflight/staleness; no extra wiring needed). This demo runs a :func:`gauge_reader` task alongside the servers
— the in-process analogue of a Prometheus scrape loop — logging only the
gauges that CHANGED since the previous tick, so a quiet cluster logs
nothing and a busy one shows exactly which counters are moving.

Spans carry contextvar-propagated ``trace_id``/``span_id``/``parent_id``:
one request's ``request`` → ``placement_lookup`` → ``object_activate`` →
``handler_dispatch`` spans share a trace, exactly like the reference's
nested ``tracing`` spans. With the optional OpenTelemetry packages
installed, the real exporter is one line::

    from rio_tpu.otel import otlp_sink
    tracing.add_sink(otlp_sink("http://jaeger:4317"))

The OTLP metrics push is ImportError-gated the same way; this demo TRIES
it and, without the SDK, falls back to :class:`InMemoryMetricExporter` —
the same collect-cycle shape as ``tests/fake_otel.py``'s exporter, fed
from ``server_gauges`` directly — so the example runs end-to-end in a
bare environment (and tier-1 smoke-tests it doing so).

The third plane is the control-plane journal (``rio_tpu/journal.py``):
the demo drives one real migration, then scrapes every node's
``DumpEvents`` tail and prints the merged causal history plus
``explain`` for the migrated actor — "why is w0 on node 2" answered from
the cluster's own flight recorder.

The fourth plane is the gauge time-series ring (``rio_tpu/timeseries.py``)
plus the HealthWatch trend alarms (``rio_tpu/health.py``): servers here
sample at an aggressive cadence so the final ``DumpSeries`` scrape has a
real window, and the demo prints the same per-node trend table the
operator CLI renders live (``python -m rio_tpu.admin watch --demo``).

Run::

    python examples/observability.py
"""

import asyncio
import logging
import statistics
import sys
from collections import defaultdict

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu import tracing
from rio_tpu.admin import (
    ADMIN_TYPE,
    AdminAck,
    AdminRequest,
    DumpStats,
    StatsSnapshot,
    cluster_events,
    explain,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.journal import format_event
from rio_tpu.metrics import merge_rows
from rio_tpu.otel import server_gauges

gauge_log = logging.getLogger("rio_tpu.examples.gauges")


class InMemoryMetricExporter:
    """No-SDK stand-in for the OTLP metrics push (``fake_otel`` style).

    The real path (``otlp_metrics_exporter``) registers observable gauges
    whose callbacks read ``server_gauges`` on the SDK's timer; this
    fallback runs the same collect cycle explicitly — each
    :meth:`collect` reads every node's gauge snapshot and appends one
    ``{name: value}`` dict per node to ``exported``, exactly what the
    fake exporter in ``tests/fake_otel.py`` would have received over
    gRPC.
    """

    def __init__(self) -> None:
        self.exported: list[dict[str, float]] = []

    def collect(self, servers: list) -> None:
        for server in servers:
            self.exported.append(dict(server_gauges(server)))


def start_metrics_export(servers: list):
    """OTLP metrics push when the SDK is present, in-memory otherwise.

    Returns ``(mode, exporter_or_provider)``: ``("otlp", provider)`` with
    the real SDK (call ``provider.shutdown()``), or
    ``("in-memory", InMemoryMetricExporter)`` without it — the gated path
    the ROADMAP left open, now always runnable.
    """
    from rio_tpu.otel import otlp_metrics_exporter

    try:
        provider = otlp_metrics_exporter(
            lambda: server_gauges(servers[0]), interval=0.5
        )
        return "otlp", provider
    except ImportError:
        return "in-memory", InMemoryMetricExporter()


async def cluster_scrape(client: "Client", members) -> None:
    """Scrape every node over the wire and merge the RED histograms.

    The cluster-wide analogue of :func:`gauge_reader`: walk the membership
    view, ask each node's ``rio.Admin`` actor for its
    :class:`~rio_tpu.admin.StatsSnapshot` (one round trip per node), then
    :func:`~rio_tpu.metrics.merge_rows` the histogram rows so the printed
    p50/p99 are CLUSTER quantiles, not per-node ones. Exemplar trace ids
    ride each top bucket — paste one into the span table/Jaeger to jump
    from "p99 is slow" to the exact request that was.
    """
    snapshots: list[StatsSnapshot] = []
    for member in await members.active_members():
        snap = await client.send(
            ADMIN_TYPE, member.address, DumpStats(), returns=StatsSnapshot
        )
        snapshots.append(snap)
        print(
            f"[scrape] {snap.address}: {len(snap.gauges)} gauges, "
            f"{len(snap.histograms)} handler histograms"
        )
    merged = merge_rows([s.histograms for s in snapshots])
    print(f"\n[scrape] cluster-wide RED quantiles ({len(snapshots)} nodes):")
    print(f"{'handler.message':<34}{'count':>6}{'err':>5}{'p50 ms':>9}{'p99 ms':>9}")
    for (ht, mt), h in sorted(merged.items()):
        print(
            f"{ht + '.' + mt:<34}{h.count:>6}{h.error_count:>5}"
            f"{h.quantile(0.5) * 1e3:>9.3f}{h.quantile(0.99) * 1e3:>9.3f}"
        )
        if h.exemplar_trace:
            print(
                f"    exemplar: trace {h.exemplar_trace[:16]}… "
                f"({h.exemplar_s * 1e3:.3f} ms)"
            )


async def gauge_reader(servers: list, interval: float = 0.5) -> None:
    """Periodically log ``server_gauges`` DELTAS for every node.

    The in-process stand-in for a metrics scrape loop (the exporter version
    is ``rio_tpu.otel.otlp_metrics_exporter``): snapshot each node's flat
    gauge dict every ``interval`` seconds and log the counters that moved,
    as ``name +delta=now``. Runs until cancelled, like the server tasks.
    """
    previous: dict[int, dict[str, float]] = {}
    while True:
        await asyncio.sleep(interval)
        for i, server in enumerate(servers):
            now = server_gauges(server)
            before = previous.get(i, {})
            moved = {
                k: (v - before.get(k, 0.0), v)
                for k, v in now.items()
                if v != before.get(k, 0.0)
            }
            previous[i] = now
            if moved:
                gauge_log.info(
                    "node[%d] %s",
                    i,
                    " ".join(
                        f"{k} {d:+g}={v:g}" for k, (d, v) in sorted(moved.items())
                    ),
                )


@message
class Work:
    item: str = ""


@message
class Ack:
    item: str = ""


class Worker(ServiceObject):
    def __init__(self) -> None:
        super().__init__()
        self.handled = 0

    # Volatile state riding the migration/replication snapshot protocol —
    # gives the demo's migration a real payload, so the journal shows the
    # install phase on BOTH nodes instead of an empty snapshot.
    def __migrate_state__(self) -> int:
        return self.handled

    def __restore_state__(self, state: int) -> None:
        self.handled = int(state)

    @handler
    async def work(self, msg: Work, ctx: AppData) -> Ack:
        self.handled += 1
        await asyncio.sleep(0.002)  # pretend to do something
        return Ack(item=msg.item)


class SpanAggregator:
    """Collects spans like an OTLP exporter would; prints a summary table."""

    def __init__(self) -> None:
        self.durations: dict[str, list[float]] = defaultdict(list)
        self.traces: dict[str, list[tracing.Span]] = defaultdict(list)

    def __call__(self, span: tracing.Span) -> None:
        self.durations[span.name].append(span.duration * 1e3)
        self.traces[span.trace_id].append(span)

    def report(self) -> None:
        print(f"{'span':<28}{'count':>6}{'mean ms':>10}{'p99 ms':>10}")
        for name in sorted(self.durations):
            d = self.durations[name]
            p99 = statistics.quantiles(d, n=100)[98] if len(d) >= 2 else d[0]
            print(f"{name:<28}{len(d):>6}{statistics.fmean(d):>10.3f}{p99:>10.3f}")

    def show_one_trace(self) -> None:
        """Render one request's correlated span tree (what Jaeger shows)."""
        trace_id, spans = max(self.traces.items(), key=lambda kv: len(kv[1]))
        by_id = {s.span_id: s for s in spans}
        print(f"\n[trace] one correlated request (trace {trace_id[:16]}…):")

        def walk(span: tracing.Span, depth: int) -> None:
            print(f"  {'  ' * depth}{span.name:<26} {span.duration * 1e3:8.3f} ms")
            for child in sorted(spans, key=lambda s: s.start):
                if child.parent_id == span.span_id:
                    walk(child, depth + 1)

        for root in [s for s in spans if s.parent_id not in by_id]:
            walk(root, 0)


async def series_scrape(client: "Client", members) -> dict:
    """Scrape every node's gauge time-series ring and render the trend view.

    One ``DumpSeries`` round trip per live node (``scrape_series`` skips
    nodes predating the ring), then the same pure ``_watch_rows`` →
    ``_format_watch`` pipeline the ``watch`` CLI loops on: per-node
    request rate / worst-handler p99 / inflight / sheds, each with a
    trend arrow over the scraped window, plus the node's solver mode and
    any active HealthWatch alerts from the snapshot meta.
    """
    from rio_tpu.admin import _format_watch, _watch_rows, scrape_series
    from rio_tpu.timeseries import merge_series

    snapshots = await scrape_series(client, members, limit=64)
    merged = merge_series(s.samples() for s in snapshots)
    print(
        f"\n[series] {len(snapshots)} nodes, {len(merged)} samples in the "
        "merged window; live trend view (admin `watch` renders this):"
    )
    print(_format_watch(_watch_rows(snapshots)))
    alerts = sum(len(s.meta.get("alerts", ())) for s in snapshots)
    return {
        "series_nodes": len(snapshots),
        "series_samples": len(merged),
        "series_alerts": alerts,
    }


async def journal_scrape(client: "Client", members, subject: tuple) -> dict:
    """Scrape every node's control-plane journal and explain one actor.

    The journal-side twin of :func:`cluster_scrape`: one ``DumpEvents``
    round trip per live node, merged into a causally ordered cluster tail
    (``merge_events`` inside :func:`rio_tpu.admin.cluster_events`), then
    :func:`rio_tpu.admin.explain` narrows to the migrated actor — its
    activation seat, each migration phase on BOTH nodes, and the trace id
    linking those rows to the request spans above.
    """
    tail = await cluster_events(client, members, limit=256)
    print(f"\n[journal] merged cluster tail ({len(tail)} control events):")
    for ev in tail[-12:]:
        print(f"  {format_event(ev)}")
    tname, oid = subject
    history = await explain(client, members, tname, oid)
    traces = {e.trace_id for e in history if e.trace_id}
    print(f"\n[journal] explain {tname}/{oid} ({len(history)} events):")
    for ev in history:
        print(f"  {format_event(ev)}")
    print(f"[journal] {len(traces)} linked trace(s)")
    return {"tail": len(tail), "explain": len(history), "traces": len(traces)}


async def main(n_requests: int = 50) -> dict:
    logging.basicConfig(level=logging.INFO)  # DEBUG to see per-span log lines
    aggregator = SpanAggregator()
    tracing.add_sink(tracing.logging_sink)
    tracing.add_sink(aggregator)
    # Head-based sampling: every client request roots a trace_ctx that the
    # wire then propagates server-side (1.0 here so the demo traces all).
    tracing.set_sample_rate(1.0)

    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(Worker),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            # Demo-speed sampling so the one-shot DumpSeries scrape at the
            # end sees a real trend window (shipping default is 1 s).
            load_interval=0.05,
            timeseries_interval=0.05,
        )
        await s.prepare()
        print(f"[server] traced node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    tasks.append(asyncio.create_task(gauge_reader(servers, interval=0.05)))
    await asyncio.sleep(0.1)

    # Metrics push: real OTLP when the SDK is installed, the in-memory
    # collect-cycle fallback otherwise — always runnable.
    otlp_mode, exporter = start_metrics_export(servers)
    print(f"[metrics] export path: {otlp_mode}")

    client = Client(members)
    for i in range(n_requests):
        await client.send(Worker, f"w{i % 5}", Work(item=f"job-{i}"), returns=Ack)

    # Drive one real migration so the journal has a full phase chain to
    # show: pin → snapshot → install (both sides) → directory flip.
    from rio_tpu.registry import ObjectId, type_id

    tname = type_id(Worker)
    owner = await placement.lookup(ObjectId(tname, "w0"))
    target = next(s.local_address for s in servers if s.local_address != owner)
    await client.send(
        ADMIN_TYPE,
        owner,
        AdminRequest(
            kind="migrate_object", type_name=tname, object_id="w0", target=target
        ),
        returns=AdminAck,
    )
    await asyncio.sleep(0.3)  # the admin queue runs the migration async
    await client.send(Worker, "w0", Work(item="post-migration"), returns=Ack)
    await asyncio.sleep(0.1)  # let the gauge reader log the final deltas

    # Wire scrape: DUMP_STATS every node via its rio.Admin actor and merge
    # the per-handler histograms into cluster-wide quantiles + exemplars.
    await cluster_scrape(client, members)

    # Flight-recorder scrape: DUMP_EVENTS every node, merge, and explain
    # the actor the demo just migrated.
    journal_summary = await journal_scrape(client, members, (tname, "w0"))

    # Trend scrape: DUMP_SERIES every node and render the per-node trend
    # table the `watch` CLI shows live.
    series_summary = await series_scrape(client, members)
    client.close()

    if otlp_mode == "in-memory":
        exporter.collect(servers)  # one explicit collect cycle, per node
        names = set().union(*(snap.keys() for snap in exporter.exported))
        print(
            f"[metrics] in-memory exporter: {len(exporter.exported)} node "
            f"snapshots, {len(names)} distinct gauges "
            f"({sum(1 for n in names if n.startswith('rio.journal.'))} journal)"
        )
    else:  # pragma: no cover - requires the optional SDK
        exporter.shutdown()

    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)

    print("\n[trace] span summary (what an OTLP exporter would ship):")
    aggregator.report()
    aggregator.show_one_trace()
    tracing.clear_sinks()
    tracing.set_sample_rate(0.0)
    print("[demo] done")
    return {
        "otlp_mode": otlp_mode,
        "snapshots": len(exporter.exported) if otlp_mode == "in-memory" else 0,
        "spans": sum(len(d) for d in aggregator.durations.values()),
        **journal_summary,
        **series_summary,
    }


if __name__ == "__main__":
    asyncio.run(main())
