"""observability: exporting request-path trace spans.

Parity with the reference's observability example
(``/root/reference/examples/observability/src/bin/observability_server.rs:37-63``),
which wires ``tracing_subscriber`` + an OpenTelemetry OTLP layer into
Jaeger. rio-tpu's span taxonomy mirrors the reference's
(``frame_receive``, ``placement_lookup``, ``handler_handle``, …, see
``rio_tpu/tracing.py``); sinks are pluggable the same way the reference's
subscriber layers are. This demo registers two sinks:

* the built-in ``logging_sink`` (the reference's fmt layer), and
* an in-process aggregator standing where an OTLP exporter would go —
  any callable ``Span -> None`` can forward to a collector.

Spans cover the request path; the *counter* side of observability is
``rio_tpu.otel.server_gauges``: one flat snapshot of every wired
subsystem's stats (placement daemon, reminder daemon, migration manager,
solver, and — since servers run a ``LoadMonitor`` by default — the local
load sample and admission-control shed counter (``rio.load.*``) plus the
gossip-derived ``rio.cluster_load.<addr>.*`` view of every peer's
lag/inflight/staleness; no extra wiring needed). This demo runs a :func:`gauge_reader` task alongside the servers
— the in-process analogue of a Prometheus scrape loop — logging only the
gauges that CHANGED since the previous tick, so a quiet cluster logs
nothing and a busy one shows exactly which counters are moving.

Spans carry contextvar-propagated ``trace_id``/``span_id``/``parent_id``:
one request's ``request`` → ``placement_lookup`` → ``object_activate`` →
``handler_dispatch`` spans share a trace, exactly like the reference's
nested ``tracing`` spans. With the optional OpenTelemetry packages
installed, the real exporter is one line::

    from rio_tpu.otel import otlp_sink
    tracing.add_sink(otlp_sink("http://jaeger:4317"))

Run::

    python examples/observability.py
"""

import asyncio
import logging
import statistics
import sys
from collections import defaultdict

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu import tracing
from rio_tpu.admin import ADMIN_TYPE, DumpStats, StatsSnapshot
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.metrics import merge_rows
from rio_tpu.otel import server_gauges

gauge_log = logging.getLogger("rio_tpu.examples.gauges")


async def cluster_scrape(client: "Client", members) -> None:
    """Scrape every node over the wire and merge the RED histograms.

    The cluster-wide analogue of :func:`gauge_reader`: walk the membership
    view, ask each node's ``rio.Admin`` actor for its
    :class:`~rio_tpu.admin.StatsSnapshot` (one round trip per node), then
    :func:`~rio_tpu.metrics.merge_rows` the histogram rows so the printed
    p50/p99 are CLUSTER quantiles, not per-node ones. Exemplar trace ids
    ride each top bucket — paste one into the span table/Jaeger to jump
    from "p99 is slow" to the exact request that was.
    """
    snapshots: list[StatsSnapshot] = []
    for member in await members.active_members():
        snap = await client.send(
            ADMIN_TYPE, member.address, DumpStats(), returns=StatsSnapshot
        )
        snapshots.append(snap)
        print(
            f"[scrape] {snap.address}: {len(snap.gauges)} gauges, "
            f"{len(snap.histograms)} handler histograms"
        )
    merged = merge_rows([s.histograms for s in snapshots])
    print(f"\n[scrape] cluster-wide RED quantiles ({len(snapshots)} nodes):")
    print(f"{'handler.message':<34}{'count':>6}{'err':>5}{'p50 ms':>9}{'p99 ms':>9}")
    for (ht, mt), h in sorted(merged.items()):
        print(
            f"{ht + '.' + mt:<34}{h.count:>6}{h.error_count:>5}"
            f"{h.quantile(0.5) * 1e3:>9.3f}{h.quantile(0.99) * 1e3:>9.3f}"
        )
        if h.exemplar_trace:
            print(
                f"    exemplar: trace {h.exemplar_trace[:16]}… "
                f"({h.exemplar_s * 1e3:.3f} ms)"
            )


async def gauge_reader(servers: list, interval: float = 0.5) -> None:
    """Periodically log ``server_gauges`` DELTAS for every node.

    The in-process stand-in for a metrics scrape loop (the exporter version
    is ``rio_tpu.otel.otlp_metrics_exporter``): snapshot each node's flat
    gauge dict every ``interval`` seconds and log the counters that moved,
    as ``name +delta=now``. Runs until cancelled, like the server tasks.
    """
    previous: dict[int, dict[str, float]] = {}
    while True:
        await asyncio.sleep(interval)
        for i, server in enumerate(servers):
            now = server_gauges(server)
            before = previous.get(i, {})
            moved = {
                k: (v - before.get(k, 0.0), v)
                for k, v in now.items()
                if v != before.get(k, 0.0)
            }
            previous[i] = now
            if moved:
                gauge_log.info(
                    "node[%d] %s",
                    i,
                    " ".join(
                        f"{k} {d:+g}={v:g}" for k, (d, v) in sorted(moved.items())
                    ),
                )


@message
class Work:
    item: str = ""


@message
class Ack:
    item: str = ""


class Worker(ServiceObject):
    @handler
    async def work(self, msg: Work, ctx: AppData) -> Ack:
        await asyncio.sleep(0.002)  # pretend to do something
        return Ack(item=msg.item)


class SpanAggregator:
    """Collects spans like an OTLP exporter would; prints a summary table."""

    def __init__(self) -> None:
        self.durations: dict[str, list[float]] = defaultdict(list)
        self.traces: dict[str, list[tracing.Span]] = defaultdict(list)

    def __call__(self, span: tracing.Span) -> None:
        self.durations[span.name].append(span.duration * 1e3)
        self.traces[span.trace_id].append(span)

    def report(self) -> None:
        print(f"{'span':<28}{'count':>6}{'mean ms':>10}{'p99 ms':>10}")
        for name in sorted(self.durations):
            d = self.durations[name]
            p99 = statistics.quantiles(d, n=100)[98] if len(d) >= 2 else d[0]
            print(f"{name:<28}{len(d):>6}{statistics.fmean(d):>10.3f}{p99:>10.3f}")

    def show_one_trace(self) -> None:
        """Render one request's correlated span tree (what Jaeger shows)."""
        trace_id, spans = max(self.traces.items(), key=lambda kv: len(kv[1]))
        by_id = {s.span_id: s for s in spans}
        print(f"\n[trace] one correlated request (trace {trace_id[:16]}…):")

        def walk(span: tracing.Span, depth: int) -> None:
            print(f"  {'  ' * depth}{span.name:<26} {span.duration * 1e3:8.3f} ms")
            for child in sorted(spans, key=lambda s: s.start):
                if child.parent_id == span.span_id:
                    walk(child, depth + 1)

        for root in [s for s in spans if s.parent_id not in by_id]:
            walk(root, 0)


async def main() -> None:
    logging.basicConfig(level=logging.INFO)  # DEBUG to see per-span log lines
    aggregator = SpanAggregator()
    tracing.add_sink(tracing.logging_sink)
    tracing.add_sink(aggregator)
    # Head-based sampling: every client request roots a trace_ctx that the
    # wire then propagates server-side (1.0 here so the demo traces all).
    tracing.set_sample_rate(1.0)

    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(Worker),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
        )
        await s.prepare()
        print(f"[server] traced node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    tasks.append(asyncio.create_task(gauge_reader(servers, interval=0.05)))
    await asyncio.sleep(0.1)

    client = Client(members)
    for i in range(50):
        await client.send(Worker, f"w{i % 5}", Work(item=f"job-{i}"), returns=Ack)
    await asyncio.sleep(0.1)  # let the gauge reader log the final deltas

    # Wire scrape: DUMP_STATS every node via its rio.Admin actor and merge
    # the per-handler histograms into cluster-wide quantiles + exemplars.
    await cluster_scrape(client, members)
    client.close()

    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)

    print("\n[trace] span summary (what an OTLP exporter would ship):")
    aggregator.report()
    aggregator.show_one_trace()
    tracing.clear_sinks()
    tracing.set_sample_rate(0.0)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
