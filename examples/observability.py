"""observability: exporting request-path trace spans.

Parity with the reference's observability example
(``/root/reference/examples/observability/src/bin/observability_server.rs:37-63``),
which wires ``tracing_subscriber`` + an OpenTelemetry OTLP layer into
Jaeger. rio-tpu's span taxonomy mirrors the reference's
(``frame_receive``, ``placement_lookup``, ``handler_handle``, …, see
``rio_tpu/tracing.py``); sinks are pluggable the same way the reference's
subscriber layers are. This demo registers two sinks:

* the built-in ``logging_sink`` (the reference's fmt layer), and
* an in-process aggregator standing where an OTLP exporter would go —
  any callable ``Span -> None`` can forward to a collector.

Run::

    python examples/observability.py
"""

import asyncio
import logging
import statistics
import sys
from collections import defaultdict

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu import tracing
from rio_tpu.cluster.membership_protocol import LocalClusterProvider


@message
class Work:
    item: str = ""


@message
class Ack:
    item: str = ""


class Worker(ServiceObject):
    @handler
    async def work(self, msg: Work, ctx: AppData) -> Ack:
        await asyncio.sleep(0.002)  # pretend to do something
        return Ack(item=msg.item)


class SpanAggregator:
    """Collects spans like an OTLP exporter would; prints a summary table."""

    def __init__(self) -> None:
        self.durations: dict[str, list[float]] = defaultdict(list)

    def __call__(self, span: tracing.Span) -> None:
        self.durations[span.name].append(span.duration * 1e3)

    def report(self) -> None:
        print(f"{'span':<28}{'count':>6}{'mean ms':>10}{'p99 ms':>10}")
        for name in sorted(self.durations):
            d = self.durations[name]
            p99 = statistics.quantiles(d, n=100)[98] if len(d) >= 2 else d[0]
            print(f"{name:<28}{len(d):>6}{statistics.fmean(d):>10.3f}{p99:>10.3f}")


async def main() -> None:
    logging.basicConfig(level=logging.INFO)  # DEBUG to see per-span log lines
    aggregator = SpanAggregator()
    tracing.add_sink(tracing.logging_sink)
    tracing.add_sink(aggregator)

    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(Worker),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
        )
        await s.prepare()
        print(f"[server] traced node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    client = Client(members)
    for i in range(50):
        await client.send(Worker, f"w{i % 5}", Work(item=f"job-{i}"), returns=Ack)
    client.close()

    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)

    print("\n[trace] span summary (what an OTLP exporter would ship):")
    aggregator.report()
    tracing.clear_sinks()
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
