"""qos: tenants, priorities, deadlines, and weighted-fair dispatch.

The QoS scheduler (``rio_tpu/qos``, opt-in via ``Server(qos_config=...)``)
sits between frame decode and handler dispatch. This demo prices its
promise on a live 2-node cluster:

1. **the flood** — a ``bulk`` tenant hammers ONE hot actor from 24
   workers. Per-object serialized execution is the contention: each
   request holds the object's lock for its service time, so without QoS
   every arrival becomes a ready handler task parked FIFO at the lock.
2. **the probe** — a ``frontend`` tenant sends strict-priority
   (``priority=2``) requests at the same hot object. OFF, each probe
   joins the FIFO behind the whole flood; ON
   (``QosConfig(max_concurrent=4)``), the scheduler caps concurrent
   starts, parks the rest of the flood in the weighted-fair ring, and
   the probe's tier takes the next grant — it waits behind at most the
   in-flight few. The demo asserts a >= 2x interactive p99 win and ZERO
   interactive sheds (the flood never costs the protected tenant a
   request).
3. **deadlines** — a ``bulk``-tenant request with a 5 ms budget parks at
   the tail of its own tenant's deep queue (the weighted-fair ring would
   grant any OTHER tenant quickly — that's the point of the ring),
   expires, and is dropped WITHOUT running the handler; the client's
   retry loop sees the spent budget and raises :class:`DeadlineExceeded`
   instead of fanning out doomed work.
4. **the operator view** — the ``DumpQos`` admin round trip (what
   ``python -m rio_tpu.admin qos`` renders) scrapes per-(tenant, class)
   RED rows, shed/deadline-drop counters, and live queue depths from
   every node over the wire.

Run::

    python examples/qos.py
"""

import asyncio
import sys
import time

sys.path.insert(0, ".")  # run from repo root without installing

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.admin import scrape_qos
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.errors import DeadlineExceeded
from rio_tpu.qos import QosConfig

BULK_WORKERS = 24
PROBES = 40
SPIN_S = 0.002  # per-request hold on the hot object's lock


@message
class Burn:
    spin_s: float = 0.0


class BurnActor(ServiceObject):
    """Each request holds this object's serialized-execution lock for
    ``spin_s`` — a flood at one id is a FIFO queue every later arrival
    waits through."""

    @handler
    async def burn(self, msg: Burn, ctx: AppData) -> Burn:
        if msg.spin_s > 0:
            await asyncio.sleep(msg.spin_s)
        return msg


async def boot(qos_config: QosConfig | None):
    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers: list[Server] = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=Registry().add_type(BurnActor),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            **({"qos_config": qos_config} if qos_config is not None else {}),
        )
        await s.prepare()
        await s.bind()
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    deadline = asyncio.get_event_loop().time() + 10.0
    while asyncio.get_event_loop().time() < deadline:
        if len(await members.active_members()) >= len(servers):
            break
        await asyncio.sleep(0.02)
    return members, tasks, servers


async def run_mode(name: str, qos_config: QosConfig | None) -> dict:
    """Flood the hot object, measure interactive probe latency."""
    members, tasks, servers = await boot(qos_config)
    bulk = Client(members, tenant="bulk")
    inter = Client(members, tenant="frontend", priority=2)
    stop = asyncio.Event()
    out: dict = {"name": name}
    try:
        # Seat the hot object first: placement is not the contention.
        await inter.send(BurnActor, "hot", Burn(spin_s=0.0), returns=Burn)

        async def flood(w: int) -> None:
            while not stop.is_set():
                try:
                    await bulk.send(
                        BurnActor, "hot", Burn(spin_s=SPIN_S), returns=Burn
                    )
                except Exception:
                    if stop.is_set():
                        return
                    await asyncio.sleep(SPIN_S)  # shed under flood is legal

        flood_tasks = [
            asyncio.create_task(flood(w)) for w in range(BULK_WORKERS)
        ]
        await asyncio.sleep(0.3)  # flood reaches steady state

        lat_ms: list[float] = []
        for _ in range(PROBES):
            t0 = time.perf_counter()
            await inter.send(BurnActor, "hot", Burn(spin_s=SPIN_S), returns=Burn)
            lat_ms.append((time.perf_counter() - t0) * 1000.0)
        lat_ms.sort()
        out["p50_ms"] = lat_ms[len(lat_ms) // 2]
        out["p99_ms"] = lat_ms[min(len(lat_ms) - 1, (len(lat_ms) * 99) // 100)]

        if qos_config is not None:
            # Deadline: 5 ms of budget can't clear the bulk tenant's own
            # ~50 ms queue backlog (any OTHER tenant would be granted
            # quickly by the fair ring — so the doomed request must ride
            # the flooding tenant). The server drops it parked, without
            # running the handler; the client refuses to retry on a
            # spent budget.
            try:
                await bulk.send(
                    BurnActor, "hot", Burn(spin_s=SPIN_S), returns=Burn,
                    deadline_ms=5,
                )
                out["deadline_raised"] = False
            except DeadlineExceeded:
                out["deadline_raised"] = True

            # The operator view: one DumpQos round trip per node — the
            # same table `python -m rio_tpu.admin qos --nodes ...` prints.
            snapshots = await scrape_qos(inter, members)
            out["interactive_sheds"] = sum(
                s.interactive_sheds for s in snapshots
            )
            print(f"[admin] qos table ({len(snapshots)} nodes):")
            header = (
                f"  {'tenant':<10} {'class':<6} {'reqs':>6} {'sheds':>6} "
                f"{'ddrops':>7} {'avg_ms':>8} {'queue_ms':>9}"
            )
            for snap in sorted(snapshots, key=lambda s: s.address):
                print(
                    f"  {snap.address}: admitted={snap.admitted} "
                    f"sheds={snap.sheds} deadline_drops={snap.deadline_drops} "
                    f"queued={snap.queued}"
                )
                print(header)
                for r in snap.tenants:
                    print(
                        f"  {(r[0] or 'default'):<10} {r[1]:<6} {r[2]:>6} "
                        f"{r[6]:>6} {r[7]:>7} {r[4]:>8.2f} {r[5]:>9.2f}"
                    )
        stop.set()
        await asyncio.gather(*flood_tasks, return_exceptions=True)
    finally:
        stop.set()
        for c in (bulk, inter):
            c.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
    return out


async def main() -> dict:
    off = await run_mode("off", None)
    print(
        f"[off]  interactive p50 {off['p50_ms']:.1f} ms, "
        f"p99 {off['p99_ms']:.1f} ms (probe parks behind the whole flood)"
    )
    on = await run_mode("on", QosConfig(max_concurrent=4))
    print(
        f"[on]   interactive p50 {on['p50_ms']:.1f} ms, "
        f"p99 {on['p99_ms']:.1f} ms (strict-priority tier overtakes the ring)"
    )
    ratio = off["p99_ms"] / max(on["p99_ms"], 1e-9)
    print(
        f"[qos]  p99 {ratio:.1f}x better with QoS on; "
        f"{on['interactive_sheds']} interactive sheds; "
        f"deadline raised={on['deadline_raised']}"
    )

    assert ratio >= 2.0, f"interactive p99 ratio {ratio:.2f} < 2x"
    assert on["interactive_sheds"] == 0, (
        f"{on['interactive_sheds']} interactive sheds under flood"
    )
    assert on["deadline_raised"], "5 ms deadline survived a ~48 ms queue"
    print("[demo] done")
    return {"off": off, "on": on, "ratio": ratio}


if __name__ == "__main__":
    asyncio.run(main())
