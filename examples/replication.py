"""replication: kill the primary, the promoted standby keeps every write.

A migration is a *planned* handoff — source and target cooperate. This
example shows the unplanned case: the node holding a replicated actor dies
hard (no shutdown lifecycle, nothing flushed), and the actor's hot standby
takes over with every acknowledged write intact — including the volatile
``streak`` that only ever lived in the dead node's memory.

Three mechanisms, visible in order:

1. **Anti-affinity seats** — the directory stores ``k`` standby rows per
   replicated actor next to the primary row; the solver (or the hashed
   fallback) never co-locates a standby with its primary.
2. **Ship-on-ack** — after each handled request, before the response goes
   out, the primary ships the actor's ``__migrate_state__`` snapshot to
   every standby's ``MigrationInbox`` (byte-identical snapshots skipped).
3. **Epoch-fenced promotion** — on the first request after the death, a
   survivor promotes the standby through a directory CAS that bumps the
   row's epoch; the deposed primary's stale ships bounce off the fence.

Runs a 3-node cluster in one process::

    python examples/replication.py
"""

import asyncio
import sys

sys.path.insert(0, ".")

from rio_tpu import (
    AdminCommand,
    AppData,
    Client,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.object_placement import LocalObjectPlacement, ObjectId
from rio_tpu.replication import ReplicationConfig
from rio_tpu.state import LocalState, StateProvider, managed_state


@message
class Visit:
    pass


@message
class Report:
    total: int = 0      # persisted (managed state)
    streak: int = 0     # volatile: survives ONLY through the replica
    server: str = ""


@message
class VisitsState:
    total: int = 0


class Visits(ServiceObject):
    __replicated__ = True  # opt in: seats + ship-on-ack + failover

    state = managed_state(VisitsState)

    def __init__(self):
        self.streak = 0

    def __migrate_state__(self):
        return {"streak": self.streak}

    def __restore_state__(self, value):
        self.streak = int(value["streak"])

    @handler
    async def visit(self, msg: Visit, ctx: AppData) -> Report:
        from rio_tpu.commands import ServerInfo

        self.state.total += 1
        self.streak += 1
        await self.save_state(ctx)
        return Report(
            total=self.state.total,
            streak=self.streak,
            server=ctx.get(ServerInfo).address,
        )


def build_registry() -> Registry:
    return Registry().add_type(Visits)


async def main() -> None:
    members = LocalStorage()
    placement = LocalObjectPlacement()
    state = LocalState()

    servers = []
    tasks = []
    for _ in range(3):
        server = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            app_data=AppData().set(state, as_type=StateProvider),
            replication_config=ReplicationConfig(
                k=1,                       # hot standbys per actor
                ship_on_ack=True,          # delta ships before each ack
                anti_entropy_interval=0.5, # repair loop period (seconds)
            ),
        )
        await server.prepare()
        await server.bind()
        servers.append(server)
        tasks.append(asyncio.create_task(server.run()))
    while len(await members.active_members()) < 3:
        await asyncio.sleep(0.05)

    client = Client(members)
    try:
        for _ in range(5):
            report = await client.send(Visits, "alice", Visit(), returns=Report)
        print(
            f"primary {report.server}: total={report.total} "
            f"streak={report.streak}"
        )

        # The directory now holds an anti-affinity standby row with an
        # epoch fence, and the standby already has the latest delta.
        held, epoch = await placement.standbys(ObjectId("Visits", "alice"))
        assert held and report.server not in held
        print(f"standby seats {held} (epoch {epoch}) — never the primary")

        # Kill the primary HARD: no shutdown lifecycle, no flush. The
        # volatile streak now exists only in the shipped replica.
        primary = next(s for s in servers if s.local_address == report.server)
        primary.admin_sender().send(AdminCommand.server_exit())
        while await members.is_active(primary.local_address):
            await asyncio.sleep(0.02)
        print(f"killed {primary.local_address}")

        # First request after the death: a survivor promotes the standby
        # through the epoch CAS and the client's redirect lands there.
        report = await client.send(Visits, "alice", Visit(), returns=Report)
        print(
            f"failover -> {report.server}: total={report.total} "
            f"streak={report.streak}  (no acknowledged write lost)"
        )
        assert report.server == held[0]
        assert (report.total, report.streak) == (6, 6)
        _, epoch2 = await placement.standbys(ObjectId("Visits", "alice"))
        assert epoch2 == epoch + 1  # the fence moved exactly once

        for s in servers:
            mgr = s.replication_manager
            if s is primary or mgr is None:
                continue
            st = mgr.stats
            print(
                f"{s.local_address}: shipped={st.shipped} appends={st.appends} "
                f"promotions={st.promotions} restores={st.replica_restores}"
            )
    finally:
        client.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


if __name__ == "__main__":
    asyncio.run(main())
