"""reminders: presence expiry without a polling watchdog.

``examples/presence.py`` keeps every actor alive with a hand-rolled
background task that wakes 4x/second to check an idle deadline — the
pattern every framework user reinvents when nothing can *wake* an actor.
This example is the same presence-expiry feature rebuilt on the timers &
reminders subsystem:

* a **volatile timer** (``register_timer``) replaces the watchdog task:
  the idle check is an ordinary message through the dispatch queue
  (serialized with real requests — no races against handlers), and the
  framework cancels it at deactivation;
* a **durable reminder** (``register_reminder``) drives a cleanup sweep
  that must survive the actor being deallocated — the whole point: a
  deactivated ``SessionLog`` is *woken* on schedule by whichever node owns
  its reminder shard, trims its persisted history, and deactivates again.

Runs a 2-node cluster in one process::

    python examples/reminders.py
"""

import asyncio
import sys
import time

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalReminderStorage,
    LocalStorage,
    Registry,
    ReminderDaemonConfig,
    ReminderFired,
    ReminderStorage,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider

IDLE_AFTER = 0.6   # seconds without a heartbeat before self-shutdown
IDLE_TICK = 0.15   # volatile-timer period for the idle check
SWEEP_EVERY = 0.5  # durable-reminder period for the cleanup sweep


@message
class Heartbeat:
    pass


@message
class IdleCheck:
    pass


@message
class Seen:
    online: bool = True
    server: str = ""


class PresenceService(ServiceObject):
    """One per user; alive exactly while the user is heartbeating.

    The idle watchdog is a volatile timer: registered on activation, fired
    through the normal dispatch queue, cancelled automatically when the
    actor shuts down. Compare ``examples/presence.py``, which hand-rolls
    the same loop with asyncio.create_task + manual cancellation.
    """

    def __init__(self) -> None:
        super().__init__()
        self.last_seen = 0.0

    async def after_load(self, ctx: AppData) -> None:
        self.last_seen = time.monotonic()
        self.register_timer(ctx, "idle-check", IDLE_TICK, IdleCheck())

    @handler
    async def beat(self, msg: Heartbeat, ctx: AppData) -> Seen:
        self.last_seen = time.monotonic()
        from rio_tpu import ServerInfo

        return Seen(server=ctx.get(ServerInfo).address)

    @handler
    async def idle(self, msg: IdleCheck, ctx: AppData) -> None:
        if time.monotonic() - self.last_seen > IDLE_AFTER:
            print(f"[{self.id}] idle -> deactivating (timer dies with me)")
            await self.shutdown(ctx)


class SessionLog(ServiceObject):
    """Cluster-wide session ledger, swept by a DURABLE reminder.

    The sweep keeps running even when this actor is deactivated: the
    reminder daemon on the shard-owning node sends ``rio.ReminderFired``,
    which re-activates the actor wherever placement wants it.
    """

    def __init__(self) -> None:
        super().__init__()
        self.entries: list[tuple[str, float]] = []
        self.sweeps = 0

    @handler
    async def record(self, msg: Heartbeat, ctx: AppData) -> None:
        self.entries.append((f"hb-{len(self.entries)}", time.time()))
        if len(self.entries) == 1:  # first write arms the sweep
            await self.register_reminder(ctx, "sweep", SWEEP_EVERY)

    async def receive_reminder(self, fired: ReminderFired, ctx: AppData) -> None:
        from rio_tpu import ServerInfo

        cutoff = time.time() - 2 * SWEEP_EVERY
        before = len(self.entries)
        self.entries = [e for e in self.entries if e[1] >= cutoff]
        self.sweeps += 1
        print(
            f"[{self.id}] sweep #{self.sweeps} on "
            f"{ctx.get(ServerInfo).address}: {before} -> {len(self.entries)} "
            f"entries (missed={fired.missed})"
        )


def build_registry() -> Registry:
    return Registry().add_type(PresenceService).add_type(SessionLog)


async def main() -> None:
    members = LocalStorage()
    placement = LocalObjectPlacement()
    reminders = LocalReminderStorage()
    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            app_data=AppData().set(reminders, as_type=ReminderStorage),
            reminder_daemon=True,
            reminder_daemon_config=ReminderDaemonConfig(
                poll_interval=0.1, lease_ttl=1.0
            ),
        )
        await s.prepare()
        print(f"[server] node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    client = Client(members)
    for user in ("ana", "bo"):
        r = await client.send(PresenceService, user, Heartbeat(), returns=Seen)
        print(f"[client] {user} online via {r.server}")
        await client.send(SessionLog, "global", Heartbeat())

    print("[demo] keeping 'ana' alive; 'bo' idles out via its timer…")
    for _ in range(6):
        await asyncio.sleep(0.3)
        await client.send(PresenceService, "ana", Heartbeat(), returns=Seen)
        await client.send(SessionLog, "global", Heartbeat())

    print("[demo] the durable sweep keeps firing regardless of activations…")
    await asyncio.sleep(1.2)

    client.close()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
