"""presence: background tasks, shared counters, and self-shutdown.

Parity with the reference's presence example
(``/root/reference/examples/presence/src/services.rs:25-55``): a per-user
``PresenceService`` actor that

* spawns a background watchdog task in ``after_load``;
* bumps a process-global counter living in ``AppData`` (the reference's
  ``AtomicU32``) while the user is online;
* shuts itself down via the admin channel (``AdminSender``) once the user
  goes idle — the watchdog, not a request, triggers deallocation.

Runs a 2-node cluster in one process::

    python examples/presence.py
"""

import asyncio
import itertools
import sys
import time

sys.path.insert(0, ".")

from rio_tpu import (
    AdminCommand,
    AdminSender,
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
    type_id,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider

IDLE_AFTER = 0.6   # seconds without a heartbeat before the watchdog evicts
WATCH_TICK = 0.15


@message
class Heartbeat:
    pass


@message
class OnlineCount:
    count: int = 0


class OnlineCounter:
    """Shared across every actor on a node via AppData (reference AtomicU32)."""

    def __init__(self) -> None:
        self.value = itertools.count()  # monotone ids for demo logging
        self.online = 0


class PresenceService(ServiceObject):
    """One per user; alive exactly while the user is heartbeating."""

    def __init__(self) -> None:
        super().__init__()
        self.last_seen = 0.0
        self._watchdog: asyncio.Task | None = None

    async def after_load(self, ctx: AppData) -> None:
        self.last_seen = time.monotonic()
        counter = ctx.get_or_default(OnlineCounter)
        counter.online += 1
        # Background task owned by the actor (reference spawns in after_load).
        self._watchdog = asyncio.create_task(self._watch(ctx))

    async def before_shutdown(self, ctx: AppData) -> None:
        ctx.get_or_default(OnlineCounter).online -= 1
        if self._watchdog is not None:
            self._watchdog.cancel()
            self._watchdog = None

    async def _watch(self, ctx: AppData) -> None:
        while True:
            await asyncio.sleep(WATCH_TICK)
            if time.monotonic() - self.last_seen > IDLE_AFTER:
                # Idle: deallocate ourselves through the admin queue —
                # the same path the reference's AdminSender uses.
                ctx.get(AdminSender).send(
                    AdminCommand.shutdown(type_id(type(self)), self.id)
                )
                return

    @handler
    async def beat(self, msg: Heartbeat, ctx: AppData) -> OnlineCount:
        self.last_seen = time.monotonic()
        return OnlineCount(count=ctx.get_or_default(OnlineCounter).online)


def build_registry() -> Registry:
    return Registry().add_type(PresenceService)


async def main() -> None:
    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers = []
    for _ in range(2):
        s = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
        )
        await s.prepare()
        print(f"[server] presence node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    client = Client(members)
    for user in ("ana", "bo", "cy"):
        r = await client.send(PresenceService, user, Heartbeat(), returns=OnlineCount)
        print(f"[client] {user} online (node sees {r.count} online)")

    print("[demo] keeping 'ana' alive, letting 'bo' and 'cy' idle out…")
    for _ in range(6):
        await asyncio.sleep(0.3)
        r = await client.send(PresenceService, "ana", Heartbeat(), returns=OnlineCount)
    print(f"[client] after idling: ana's node sees {r.count} online")

    allocated = [
        u for u in ("ana", "bo", "cy")
        if await placement.lookup(
            __import__("rio_tpu").ObjectId("PresenceService", u)
        ) is not None
    ]
    print(f"[demo] still allocated: {allocated}")

    client.close()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    print("[demo] done")


if __name__ == "__main__":
    asyncio.run(main())
