"""metric-aggregator: managed state + actor fan-out + a load driver.

Parity with the reference example (``/root/reference/examples/metric-aggregator``):
a ``MetricAggregator`` actor per metric name keeps running stats in a
``managed_state`` field (persisted via SQLite), fans each sample out to a
per-tag aggregator through the internal client, and a ``loadall`` driver
sends 20k sequential requests (the reference's de-facto load benchmark,
``metric_aggregator_loadall.rs:26-37``).

Cross-process: every process (server or client) shares the cluster through
the same SQLite file — membership, placement, and state.

    python examples/metric_aggregator.py server --db /tmp/ma.db --port 7701
    python examples/metric_aggregator.py server --db /tmp/ma.db --port 7702
    python examples/metric_aggregator.py loadall --db /tmp/ma.db -n 20000
    python examples/metric_aggregator.py show --db /tmp/ma.db --name requests
"""

import argparse
import asyncio
import sys
import time

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    Registry,
    Server,
    ServiceObject,
    handler,
    make_registry,
    message,
)
from rio_tpu.cluster.membership_protocol.peer_to_peer import (
    PeerToPeerClusterConfig,
    PeerToPeerClusterProvider,
)
from rio_tpu.cluster.storage.sqlite import SqliteMembershipStorage
from rio_tpu.object_placement.sqlite import SqliteObjectPlacement
from rio_tpu.state import StateProvider, managed_state
from rio_tpu.state.sqlite import SqliteState


@message
class Metric:
    tag: str = ""
    value: float = 0.0


@message
class Stats:
    count: int = 0
    total: float = 0.0
    vmin: float = 0.0
    vmax: float = 0.0


@message
class GetStats:
    pass


class MetricAggregator(ServiceObject):
    """One per metric name; fans out to one per (name, tag)."""

    stats = managed_state(Stats)

    @handler
    async def record(self, msg: Metric, ctx: AppData) -> Stats:
        s = self.stats
        s.vmin = msg.value if s.count == 0 else min(s.vmin, msg.value)
        s.vmax = msg.value if s.count == 0 else max(s.vmax, msg.value)
        s.count += 1
        s.total += msg.value
        await self.save_state(ctx)
        # Fan out to the per-tag aggregator (reference services.rs:30-49).
        # The forwarded copy carries tag="" so the child never re-fans-out,
        # regardless of what characters the metric name contains.
        if msg.tag:
            await ServiceObject.send(
                ctx, MetricAggregator, f"{self.id}.{msg.tag}",
                Metric(tag="", value=msg.value), returns=Stats,
            )
        return s

    @handler
    async def get(self, msg: GetStats, ctx: AppData) -> Stats:
        return self.stats


# Declarative registry + typed client stubs — the reference builds this
# example with `make_registry!` (metric-aggregator/src/lib.rs); `decl.client`
# carries `metric_aggregator.send_metric/send_get_stats` typed wrappers.
decl = make_registry({
    MetricAggregator: [
        (Metric, Stats),
        (GetStats, Stats),
    ],
})


def build_registry() -> Registry:
    return decl.registry()


def sqlite_cluster(db: str):
    members = SqliteMembershipStorage(db)
    placement = SqliteObjectPlacement(db)
    state = SqliteState(db)
    return members, placement, state


async def run_server(db: str, port: int) -> None:
    members, placement, state = sqlite_cluster(db)
    await state.prepare()
    app_data = AppData()
    app_data.set(state, as_type=StateProvider)
    server = Server(
        address=f"0.0.0.0:{port}",
        registry=build_registry(),
        cluster_provider=PeerToPeerClusterProvider(
            members, PeerToPeerClusterConfig(interval_secs=2.0, num_failures_threshold=2,
                                             interval_secs_threshold=10.0)
        ),
        object_placement_provider=placement,
        app_data=app_data,
    )
    await server.prepare()
    addr = await server.bind()
    print(f"[server] metric-aggregator node on {addr}", flush=True)
    await server.run()


async def run_loadall(db: str, n: int, name: str) -> None:
    members, _, _ = sqlite_cluster(db)
    client = Client(members)
    send_metric = decl.client.metric_aggregator.send_metric
    t0 = time.perf_counter()
    for i in range(n):
        await send_metric(
            client, name, Metric(tag=f"tag{i % 10}", value=float(i % 100))
        )
    dt = time.perf_counter() - t0
    print(f"[loadall] {n} requests in {dt:.2f}s = {n / dt:.0f} req/s", flush=True)
    client.close()


async def run_show(db: str, name: str) -> None:
    members, _, _ = sqlite_cluster(db)
    client = Client(members)
    stats = await client.send(MetricAggregator, name, GetStats(), returns=Stats)
    print(f"[show] {name}: {stats}", flush=True)
    for tag in range(10):
        s = await client.send(MetricAggregator, f"{name}.tag{tag}", GetStats(), returns=Stats)
        print(f"[show] {name}.tag{tag}: count={s.count} total={s.total}", flush=True)
    client.close()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    s = sub.add_parser("server")
    s.add_argument("--db", required=True)
    s.add_argument("--port", type=int, default=0)
    l = sub.add_parser("loadall")
    l.add_argument("--db", required=True)
    l.add_argument("-n", type=int, default=20000)
    l.add_argument("--name", default="requests")
    g = sub.add_parser("show")
    g.add_argument("--db", required=True)
    g.add_argument("--name", default="requests")
    args = p.parse_args()
    if args.cmd == "server":
        asyncio.run(run_server(args.db, args.port))
    elif args.cmd == "loadall":
        asyncio.run(run_loadall(args.db, args.n, args.name))
    else:
        asyncio.run(run_show(args.db, args.name))


if __name__ == "__main__":
    main()
