"""streams: durable partitioned streams + saga workflows, end to end.

The platform layer on top of the actor mesh (``rio_tpu/streams/``):

* a **producer** publishes orders into the durable ``orders`` stream —
  every publish is acked with its ``(partition, offset)`` only after the
  append hit storage (sqlite here; postgres/redis are the same trait);
* **two consumer groups** (``billing`` and ``audit``) each get every
  record exactly-once-per-group via placement-seated cursor actors, with
  the reminder subsystem as the at-least-once redelivery backstop;
* a **saga** coordinates a multi-actor workflow with typed
  step/compensation chains — the demo runs one saga to completion, then
  forces a veto mid-chain and watches the compensations run in reverse;
* the whole saga is **one trace tree**: the same waterfall the operator
  CLI renders (``python -m rio_tpu.admin trace``) is assembled here
  in-process from every node's span ring + journal, so the
  step/compensation story reads as causal hops, not scattered logs.

Run::

    python examples/streams.py
"""

import asyncio
import sys
import tempfile
from collections import defaultdict

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalReminderStorage,
    LocalStorage,
    Registry,
    ReminderDaemonConfig,
    ReminderStorage,
    Server,
    ServiceObject,
    handler,
    message,
    tracing,
)
from rio_tpu.admin import assemble_waterfall, cluster_events, format_waterfall, scrape_spans
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.journal import SAGA, STREAM, format_event
from rio_tpu.registry import wire_error
from rio_tpu.state import LocalState, StateProvider
from rio_tpu.streams import StreamDelivery, StreamStorage
from rio_tpu.streams.sqlite import SqliteStreamStorage

RECEIVED: dict[str, list[str]] = defaultdict(list)  # "group/sink-id" -> items
LEDGER: dict[str, list[str]] = defaultdict(list)  # account id -> effects


@message
class Order:
    sku: str = ""
    qty: int = 0


@message
class Reserve:
    amount: int = 0


@message
class Release:
    amount: int = 0


@wire_error
class OutOfStock(Exception):
    pass


class Billing(ServiceObject):
    """Consumer group ``billing``: one cursor actor per partition feeds
    these; the id encodes stream/group/partition."""

    async def receive_stream(self, delivery: StreamDelivery, ctx) -> None:
        order = delivery.decode(Order)
        RECEIVED[f"billing/{self.id}"].append(order.sku)


class Audit(ServiceObject):
    async def receive_stream(self, delivery: StreamDelivery, ctx) -> None:
        order = delivery.decode(Order)
        RECEIVED[f"audit/{self.id}"].append(order.sku)


class Inventory(ServiceObject):
    """Saga participant: reserve/release with a persisted dedup ledger
    (the framework's blanket ``rio.SagaStep`` handler wraps these)."""

    @handler
    async def reserve(self, msg: Reserve, ctx) -> int:
        LEDGER[self.id].append(f"reserve:{msg.amount}")
        return msg.amount

    @handler
    async def release(self, msg: Release, ctx) -> int:
        LEDGER[self.id].append(f"release:{msg.amount}")
        return msg.amount


class StrictWarehouse(ServiceObject):
    """Participant that vetoes every reservation — the forced-compensation
    leg of the demo."""

    @handler
    async def reserve(self, msg: Reserve, ctx) -> int:
        LEDGER[self.id].append("veto")
        raise OutOfStock(f"{self.id} cannot reserve {msg.amount}")


async def main() -> dict:
    tracing.set_sample_rate(1.0)  # trace everything: the demo shows waterfalls
    tmp = tempfile.TemporaryDirectory(prefix="rio-streams-")
    storage = SqliteStreamStorage(f"{tmp.name}/streams.db")
    state = LocalState()
    reminders = LocalReminderStorage()

    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers: list[Server] = []
    for _ in range(2):
        app_data = (
            AppData()
            .set(storage, as_type=StreamStorage)
            .set(state, as_type=StateProvider)
            .set(reminders, as_type=ReminderStorage)
        )
        s = Server(
            address="127.0.0.1:0",
            registry=(
                Registry()
                .add_type(Billing)
                .add_type(Audit)
                .add_type(Inventory)
                .add_type(StrictWarehouse)
            ),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            app_data=app_data,
            # The redelivery/resume backstop, at demo cadence.
            reminder_daemon=True,
            reminder_daemon_config=ReminderDaemonConfig(
                poll_interval=0.05, lease_ttl=2.0
            ),
        )
        await s.prepare()
        print(f"[server] streams node on {await s.bind()}")
        servers.append(s)
    tasks = [asyncio.create_task(s.run()) for s in servers]
    await asyncio.sleep(0.1)

    client = Client(members)
    summary: dict = {}
    try:
        # -- producer → two consumer groups over the wire -----------------
        await client.subscribe_stream("orders", "billing", Billing)
        await client.subscribe_stream("orders", "audit", Audit)
        skus = [f"sku-{i}" for i in range(8)]
        acks = []
        for i, sku in enumerate(skus):
            ack = await client.publish_stream(
                "orders", Order(sku=sku, qty=1 + i), key=sku
            )
            acks.append(ack)
        print(f"[produce] {len(acks)} publishes acked, e.g. sku-0 -> {acks[0]}")

        def group_total(group: str) -> int:
            return sum(
                len(v) for k, v in RECEIVED.items() if k.startswith(group + "/")
            )

        deadline = asyncio.get_event_loop().time() + 20.0
        while group_total("billing") < len(skus) or group_total("audit") < len(skus):
            if asyncio.get_event_loop().time() > deadline:
                raise RuntimeError("consumer groups never caught up")
            await asyncio.sleep(0.05)
        for group in ("billing", "audit"):
            cursors = await client.stream_cursors("orders", group)
            lag = 0
            for p, off in cursors.items():
                lag += await storage.latest("orders", p) - off
            print(
                f"[consume] group {group}: {group_total(group)} deliveries "
                f"across {len(cursors)} partition cursor(s), lag={lag}"
            )
        summary["published"] = len(acks)
        summary["billing"] = group_total("billing")
        summary["audit"] = group_total("audit")

        # -- saga one: happy path ------------------------------------------
        from rio_tpu.streams.saga import step

        done = await client.start_saga(
            "order-1000",
            [
                step(Inventory, "east", Reserve(amount=3), Release(amount=3)),
                step(Inventory, "west", Reserve(amount=5), Release(amount=5)),
            ],
        )
        print(f"[saga] order-1000 -> {done.status} ({done.total} steps)")
        assert done.status == "completed", done

        # -- saga two: forced compensation ---------------------------------
        undone = await client.start_saga(
            "order-1001",
            [
                step(Inventory, "east", Reserve(amount=2), Release(amount=2)),
                step(StrictWarehouse, "strict", Reserve(amount=9), Release(amount=9)),
            ],
        )
        print(
            f"[saga] order-1001 -> {undone.status} "
            f"(error: {undone.error.splitlines()[0] if undone.error else ''})"
        )
        assert undone.status == "compensated", undone
        assert LEDGER["east"] == ["reserve:3", "reserve:2", "release:2"]
        print(f"[saga] ledger east={LEDGER['east']} strict={LEDGER['strict']}")
        summary["saga_completed"] = done.status
        summary["saga_compensated"] = undone.status

        # -- the waterfall: one saga = one trace tree ----------------------
        trace_id = undone.trace_id
        snapshots = await scrape_spans(client, members, trace_id=trace_id)
        events = await cluster_events(client, members, kinds=[SAGA, STREAM])
        trees = assemble_waterfall(
            [r for s in snapshots for r in s.spans()],
            [e for e in events if e.trace_id == trace_id],
        )
        print(
            f"\n[trace] compensated saga as one waterfall "
            f"(admin `trace {trace_id[:16]}…` renders the same):"
        )
        if trace_id in trees:
            print(format_waterfall(trace_id, trees[trace_id]))
        saga_story = [e for e in events if e.kind == SAGA]
        print(f"\n[journal] saga story ({len(saga_story)} SAGA events):")
        for ev in saga_story[-12:]:
            print(f"  {format_event(ev)}")
        summary["waterfall_hops"] = trees.get(trace_id, {}).get("hops", 0)
        summary["saga_events"] = len(saga_story)
        assert summary["saga_events"] > 0
    finally:
        client.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        tracing.set_sample_rate(0.0)
        tmp.cleanup()
    print("[demo] done")
    return summary


if __name__ == "__main__":
    out = asyncio.run(main())
    print(out)
