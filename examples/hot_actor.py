"""hot_actor: scale a celebrity actor's reads across its replicas.

A virtual actor executes serially — one object, one queue — so a single
hot key tops out at ``1/handler_time`` requests per second no matter how
many nodes the cluster has. This example turns the replication standbys
into bounded-staleness read replicas and walks the whole read-scale path:

1. **`@readonly` serving** — a standby answers marked read messages from
   its shipped replica while the replica is inside the staleness bound
   (`max_staleness_s` / `max_lag_seq`); outside the bound it transparently
   proxies to the primary — never an error, never a stale answer beyond
   the contract.
2. **Shed + divert** — when the primary is overloaded it refuses marked
   reads with a ``SERVER_BUSY`` that *names the standby seats*; the client
   caches the hint and fans reads across the seats with no backoff.
3. **Dynamic replication factor** — the hotness detector watches the
   per-object request-rate EMAs and raises the celebrity's replica count
   toward ``k_max`` while it is hot, then decays it one seat at a time
   (with hysteresis) as it cools — every transition through the normal
   epoch-preserving seat path.

Runs a 3-node cluster in one process::

    python examples/hot_actor.py
"""

import asyncio
import sys

sys.path.insert(0, ".")

from rio_tpu import (
    AppData,
    Client,
    LocalStorage,
    ReadScaleConfig,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
    readonly,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.commands import ServerInfo
from rio_tpu.load import LoadThresholds
from rio_tpu.object_placement import LocalObjectPlacement, ObjectId
from rio_tpu.replication import ReplicationConfig


@message
class Post:
    text: str = ""


@message
class ReadTimeline:
    pass


@message
class Timeline:
    posts: int = 0
    served_by: str = ""


class Celebrity(ServiceObject):
    __replicated__ = True  # standbys double as read replicas

    def __init__(self):
        self.posts = 0

    def __migrate_state__(self):
        return {"posts": self.posts}

    def __restore_state__(self, value):
        self.posts = int(value["posts"])

    @handler
    async def post(self, msg: Post, ctx: AppData) -> Timeline:
        self.posts += 1
        return Timeline(posts=self.posts, served_by=ctx.get(ServerInfo).address)

    @readonly
    @handler
    async def timeline(self, msg: ReadTimeline, ctx: AppData) -> Timeline:
        return Timeline(posts=self.posts, served_by=ctx.get(ServerInfo).address)


def build_registry() -> Registry:
    return Registry().add_type(Celebrity)


async def main() -> None:
    members = LocalStorage()
    placement = LocalObjectPlacement()

    read_cfg = ReadScaleConfig(
        max_staleness_s=2.0,  # replica age bound for serving reads
        max_lag_seq=2,        # acked-sequence lag bound
        k_min=1,
        k_max=2,              # 3 nodes: primary + up to 2 read replicas
        hot_rate=50.0,        # req/s that earns each extra replica
    )
    servers, tasks = [], []
    for _ in range(3):
        server = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            replication_config=ReplicationConfig(
                k=1, anti_entropy_interval=0.3
            ),
            read_scale_config=read_cfg,
        )
        await server.prepare()
        await server.bind()
        servers.append(server)
        tasks.append(asyncio.create_task(server.run()))
    while len(await members.active_members()) < 3:
        await asyncio.sleep(0.05)

    client = Client(members, read_scale=read_cfg)
    try:
        # One write activates the actor, seats its standby, and ships the
        # first replica before the ack (ship-on-ack).
        tl = await client.send(Celebrity, "star", Post(text="hi"), returns=Timeline)
        primary = tl.served_by
        held, epoch = await placement.standbys(ObjectId("Celebrity", "star"))
        print(f"primary {primary}; standby seats {held} (epoch {epoch})")

        # 1) A standby serves the read from its replica — ask it directly
        # by making the primary shed: drop its admission ceiling so every
        # readonly request is refused with a seat hint.
        primary_srv = next(s for s in servers if s.local_address == primary)
        primary_srv.load_monitor.thresholds = LoadThresholds(max_inflight=-1)

        served_by: dict[str, int] = {}
        for _ in range(40):
            tl = await client.send(
                Celebrity, "star", ReadTimeline(), returns=Timeline
            )
            assert tl.posts == 1  # inside the staleness bound, never behind
            served_by[tl.served_by] = served_by.get(tl.served_by, 0) + 1
        print(f"hot primary: 40 reads served by {served_by}")
        mgr = next(
            s.read_scale_manager for s in servers if s.local_address == held[0]
        )
        print(
            f"standby counters: reads={mgr.stats.standby_reads} "
            f"forwards={mgr.stats.standby_forwards}"
        )

        # Writes are never diverted: the primary still owns them.
        primary_srv.load_monitor.thresholds = LoadThresholds()
        tl = await client.send(Celebrity, "star", Post(text="again"), returns=Timeline)
        assert tl.served_by == primary and tl.posts == 2

        # 2) Dynamic k: feed the detector a hot rate and watch the replica
        # count climb to k_max — then decay as the key cools. (In
        # production the LoadMonitor tick feeds real per-object EMAs.)
        rs = primary_srv.read_scale_manager
        await rs.hotness_tick({"Celebrity.star": 150.0})
        held, epoch2 = await placement.standbys(ObjectId("Celebrity", "star"))
        print(f"hot: replica_k -> {len(held)} seats {held} (epoch {epoch2})")
        assert len(held) == 2 and epoch2 == epoch  # fence never moved

        await rs.hotness_tick({"Celebrity.star": 5.0})
        held, _ = await placement.standbys(ObjectId("Celebrity", "star"))
        print(f"cooled: replica_k -> {len(held)} seats {held}")
    finally:
        client.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


if __name__ == "__main__":
    asyncio.run(main())
