"""migration: a forced rebalance with state surviving the move.

A directory re-seat alone would strand the old node's in-memory activation
and lose everything not yet persisted. This example shows the coordinated
handoff behind every solver move: a stateful ``Visits`` actor — persisted
total via ``managed_state``, in-memory streak via ``__migrate_state__`` —
is seated on node A, loaded with traffic, then migrated twice:

1. **Admin command** (``AdminCommand.migrate``): the ops/debug entry to the
   handoff — pin, deactivate, snapshot, inline volatile transfer, directory
   flip, fence.
2. **Solver rebalance** (``JaxObjectPlacement.rebalance(move_sink=...)``):
   node A is cordoned (a drain, in miniature) and the OT re-solve's planned
   moves are actuated through the same :class:`MigrationManager` path the
   placement daemon uses.

After each move the next request activates the actor on its new node with
BOTH kinds of state intact — the streak counter proves the volatile
snapshot traveled, because a cold activation would reset it to zero.

Runs a 2-node cluster in one process::

    python examples/migration.py
"""

import asyncio
import sys

sys.path.insert(0, ".")

from rio_tpu import (
    AdminCommand,
    AppData,
    Client,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from rio_tpu.cluster.membership_protocol import LocalClusterProvider
from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
from rio_tpu.state import LocalState, StateProvider, managed_state


@message
class Visit:
    pass


@message
class Report:
    total: int = 0      # persisted (managed state)
    streak: int = 0     # volatile (travels only via migration)
    server: str = ""


@message
class VisitsState:
    total: int = 0


class Visits(ServiceObject):
    state = managed_state(VisitsState)

    def __init__(self):
        self.streak = 0  # in-memory only: lost on a plain deactivation

    def __migrate_state__(self):
        return {"streak": self.streak}

    def __restore_state__(self, value):
        self.streak = int(value["streak"])

    @handler
    async def visit(self, msg: Visit, ctx: AppData) -> Report:
        from rio_tpu.commands import ServerInfo

        self.state.total += 1
        self.streak += 1
        await self.save_state(ctx)
        return Report(
            total=self.state.total,
            streak=self.streak,
            server=ctx.get(ServerInfo).address,
        )


def build_registry() -> Registry:
    return Registry().add_type(Visits)


async def main() -> None:
    members = LocalStorage()
    placement = JaxObjectPlacement(mode="greedy", move_cost=0.5)
    state = LocalState()

    servers = []
    tasks = []
    for _ in range(2):
        server = Server(
            address="127.0.0.1:0",
            registry=build_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            app_data=AppData().set(state, as_type=StateProvider),
        )
        await server.prepare()
        await server.bind()
        servers.append(server)
        tasks.append(asyncio.create_task(server.run()))
    while len(await members.active_members()) < 2:
        await asyncio.sleep(0.05)
    placement.sync_members(await members.members())

    client = Client(members)
    try:
        for _ in range(3):
            report = await client.send(Visits, "alice", Visit(), returns=Report)
        print(f"seated on {report.server}: total={report.total} streak={report.streak}")
        source = next(s for s in servers if s.local_address == report.server)
        target = next(s for s in servers if s.local_address != report.server)

        # --- Move 1: explicit admin command --------------------------------
        source.admin_sender().send(
            AdminCommand.migrate("Visits", "alice", target.local_address)
        )
        while not source.migration_manager.stats.completed:
            await asyncio.sleep(0.02)
        report = await client.send(Visits, "alice", Visit(), returns=Report)
        print(
            f"after admin migrate -> {report.server}: "
            f"total={report.total} streak={report.streak}  (nothing lost)"
        )
        assert report.server == target.local_address
        assert (report.total, report.streak) == (4, 4)

        # --- Move 2: the solver decides ------------------------------------
        # Cordon the current host and re-solve with the migration manager as
        # the move sink — exactly what the placement daemon does on churn,
        # and what a DRAIN_SERVER does before exiting.
        placement.cordon(target.local_address)
        moved = await placement.rebalance(
            move_sink=target.migration_manager.apply_moves
        )
        report = await client.send(Visits, "alice", Visit(), returns=Report)
        print(
            f"after cordon+rebalance ({moved} move) -> {report.server}: "
            f"total={report.total} streak={report.streak}"
        )
        assert report.server == source.local_address
        assert (report.total, report.streak) == (5, 5)

        stats = target.migration_manager.stats
        print(
            f"coordinator stats: started={stats.started} "
            f"completed={stats.completed} state_bytes={stats.state_bytes}"
        )
    finally:
        client.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


if __name__ == "__main__":
    asyncio.run(main())
