"""rio-tpu headline benchmark: placements/sec @ up to 1M objects x 1k nodes.

Compares the TPU placement solve (entropic OT + capacity-aware rounding,
``rio_tpu/ops``) against the reference architecture's per-object SQL round
trip (one SELECT + one INSERT per placement, exactly the queries in
``rio-rs/src/object_placement/sqlite.rs:68-100``), measured here through
Python's C sqlite3 module on the same schema. Route hops are MEASURED on a
live 8-server loopback cluster (``rio_tpu/utils/routing_live.py``), not
simulated.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Robustness design (the round-1 artifact died in backend init, rc=124):

* every jax-touching tier runs in a CHILD process armed with a watchdog
  thread that ``os._exit``s at a hard deadline — a hung PJRT init through
  the axon tunnel cannot stall the orchestrator;
* the child probes ``jax.devices()`` exactly once (its own 120 s timer);
  an init failure aborts ALL remaining TPU tiers immediately — jax would
  otherwise re-attempt backend setup per tier, ~25 min each against a
  wedged relay;
* if no TPU tier survives, a CPU child (``JAX_PLATFORMS=cpu`` +
  ``PYTHONPATH=`` to bypass the axon sitecustomize) still produces a
  number, so the JSON line is printed in every outcome.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sqlite3
import subprocess
import sys
import threading
import time

N_NODES = 1024
CHUNK = 65536  # rows per rounding chunk (bounds rounding temps to ~256 MB)

EXIT_INIT_FAIL = 97  # jax backend never came up — do not try more TPU tiers
EXIT_SOLVE_FAIL = 98  # tier failed (e.g. OOM) — a smaller tier may fit
EXIT_WATCHDOG = 99  # deadline hit during backend init — treat as wedged
EXIT_TIER_TIMEOUT = 96  # deadline hit after a healthy probe — smaller tier may fit
EXIT_PREFLIGHT_HANG = 95  # hier pre-flight PULL hung — relay likely wedged, not slow

PROBE_DEADLINE_S = 120.0

# Relay-health ceiling for the hier ladder (the bench's only tier whose
# compile can outgrow its watchdog budget when the relay degrades). Healthy
# windows pull 4 MB in ~170-350 ms; wedge-preceding degradation measured
# 747 ms (r4) and 1119 ms (r5 session 2, where the 655k rung's ~45 s
# compile inflated past the 700 s child budget and the mid-compile watchdog
# exit re-wedged the relay). Above this, skip the ladder: its evidence is
# already banked (BENCH_DETAIL.tpu.json baseline_row5_hier) and a skipped
# rung is recoverable where a wedged relay is not.
HIER_PULL_MAX_MS = 700.0

# The only keys _write_detail carries forward from a prior tpu capture:
# scarce hardware evidence. Host-stage numbers (rpc, routing, live-cluster
# rows) deliberately never carry — they are only meaningful next to the
# SAME session's sqlite baseline (absolute throughput drifts ±30-40%).
_CARRYABLE_TIERS = (
    "collapsed_tier",
    "solve_tier",
    "baseline_row5_hier",
    "delta_tier",
)

# Field names whose values include the axon relay's per-call dispatch+sync
# overhead (~300 ms/cycle r4; the collapsed tier's "294 ms" was 0.6 ms of
# device compute + bench-loop sync). They are banked for relay forensics —
# never read them as device time. _relay_health enumerates every banked
# occurrence so a consumer of the sidecar can't miss the caveat.
_SYNC_CONTAMINATED_FIELDS = ("pull_ms", "single_shot_ms")


def sqlite_baseline_rate(n_samples: int = 5000) -> float:
    """Placements/sec for the reference's row-by-row SQL directory."""
    db = sqlite3.connect(":memory:")
    db.execute(
        "CREATE TABLE object_placement ("
        "struct_name TEXT NOT NULL, object_id TEXT NOT NULL,"
        "server_address TEXT, PRIMARY KEY (struct_name, object_id))"
    )
    db.execute("CREATE INDEX idx_addr ON object_placement (server_address)")
    t0 = time.perf_counter()
    for i in range(n_samples):
        # The allocate path: lookup miss then upsert (service.rs:193-254).
        db.execute(
            "SELECT server_address FROM object_placement "
            "WHERE struct_name=? AND object_id=?",
            ("Bench", str(i)),
        ).fetchone()
        db.execute(
            "INSERT INTO object_placement (struct_name, object_id, server_address) "
            "VALUES (?, ?, ?) ON CONFLICT (struct_name, object_id) "
            "DO UPDATE SET server_address=excluded.server_address",
            ("Bench", str(i), f"10.0.0.{i % 64}:5000"),
        )
        db.commit()
    return n_samples / (time.perf_counter() - t0)


def scaled_route_hops() -> dict:
    """64-server x 50k-object live routing + stale-directory degradation.

    Stderr evidence for BASELINE rows 1-2: the directory policy's hop win
    at scale, and graceful degradation (redirects + dial fallback, zero
    failures) when the directory serves a poisoned stale snapshot.
    """
    import asyncio

    from rio_tpu.utils.routing_live import measure_route_hops_scaled

    out = asyncio.run(measure_route_hops_scaled())
    print(
        f"# scaled routing ({out['n_servers']} servers, {out['n_objects']} objects, "
        f"{out['displaced']} displaced on {out['dead_servers']} killed nodes, {out['wrong']} wrong "
        f"pointers): reference mean={out['reference']['mean']} "
        f"p99={out['reference']['p99']:.0f} | directory mean={out['directory']['mean']} "
        f"p99={out['directory']['p99']:.0f} | STALE directory "
        f"mean={out['stale']['mean']} p99={out['stale']['p99']:.0f} "
        f"failures={out['stale_failures']}",
        file=sys.stderr,
    )
    return out


def row2_jax_provider_live() -> dict:
    """BASELINE row 2: 8 nodes x 100k objects on the REAL JaxObjectPlacement.

    The cluster's shared directory IS the provider under test (mode="auto"
    — greedy waterfill on this CPU host, OT on TPU); allocation flows
    through Server self-assign into the host-mirrored directory, and the
    directory-resolver policy then dials owners directly.
    """
    import asyncio

    from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
    from rio_tpu.utils.routing_live import measure_route_hops_live

    stats = asyncio.run(
        measure_route_hops_live(
            n_servers=8,
            n_objects=100_000,
            placement=JaxObjectPlacement(),
            sample_size=4_000,
        )
    )
    ref, ours = stats["reference"], stats["rio_tpu"]
    print(
        f"# row-2 live (8 servers, 100k objects on JaxObjectPlacement): "
        f"directory p99={ours.p99:.0f} mean={ours.mean:.2f} | "
        f"reference-policy p99={ref.p99:.0f} mean={ref.mean:.2f}",
        file=sys.stderr,
    )
    return {"ours": ours.as_dict(), "reference": ref.as_dict()}


def live_route_hops() -> dict:
    """p99 route hops measured across real TCP round trips (8 servers)."""
    import asyncio

    from rio_tpu.utils.routing_live import measure_route_hops_live

    stats = asyncio.run(measure_route_hops_live(n_servers=8, n_objects=2048))
    ref, ours = stats["reference"], stats["rio_tpu"]
    print(
        f"# measured route hops (live 8-server cluster, 2048 objects): "
        f"ours p99={ours.p99:.0f} mean={ours.mean:.2f} | "
        f"reference-policy p99={ref.p99:.0f} mean={ref.mean:.2f}",
        file=sys.stderr,
    )
    return {"ours": ours.as_dict(), "reference": ref.as_dict()}


# ---------------------------------------------------------------------------
# Child: one solve tier under a hard watchdog
# ---------------------------------------------------------------------------


def _arm_watchdog(
    seconds: float, code: int, note: str | None = None
) -> threading.Timer:
    """Hard in-process deadline: fires even if the main thread is stuck in C."""

    def fire():
        # One stderr line before dying so a silent rc in the parent's log
        # is attributable (r4: the hier child vanished with bare rc=99).
        # `note` lets a caller distinguish WHAT was hung (r5: a clean
        # "measured slow" skip and a hung-pull watchdog shared one rc).
        print(f"# watchdog fired after {seconds:.0f}s -> exit {code}"
              + (f" ({note})" if note else ""),
              file=sys.stderr, flush=True)
        os._exit(code)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _time_chained(chained_fn, args, k: int) -> tuple[float, float]:
    """Compile + best-of-2 timed runs of a k-step chained executable.

    ``chained_fn(*args, k)`` must return a jit-computed scalar; the plain
    float() pull is the sync (see _time_fn). Returns
    (per_step_seconds, compile_seconds). One copy of the protocol so the
    chained tiers cannot drift; the gate lives in _maybe_time_chain.
    """
    t0 = time.perf_counter()
    float(chained_fn(*args, k))
    compile_s = time.perf_counter() - t0
    ts = []
    for _ in range(2):
        t0 = time.perf_counter()
        float(chained_fn(*args, k))
        ts.append(time.perf_counter() - t0)
    return min(ts) / k, compile_s


def _maybe_time_chain(
    chained_fn,
    args,
    k: int,
    chain_budget_s: float | None,
    t_enter: float,
    compile_s: float,
    step_s: float,
) -> tuple[float | None, dict]:
    """The chain-gate + timing protocol, in ONE place for every tier.

    Projects one more compile of comparable cost (1.5x the tier's MEASURED
    single-shot compile) plus 3 chained executions scaled from the MEASURED
    single-shot step time, after subtracting the time the tier has already
    burned since ``t_enter`` — ``chain_budget_s`` arrives stale, computed
    at the child's call site before the tier's own compiles ran. Skipping
    is silent-but-safe: a watchdog must never fire mid-TPU-op (CLAUDE.md).
    Returns ``(per_step_seconds | None, extras_dict)``.
    """
    if chain_budget_s is None:
        return None, {}
    elapsed = time.perf_counter() - t_enter
    projected = 1.5 * compile_s + 3 * k * step_s
    if chain_budget_s - elapsed <= projected:
        return None, {}
    per_step_s, chain_compile_s = _time_chained(chained_fn, args, k)
    return per_step_s, {
        "chain_steps": k,
        "chain_compile_s": round(chain_compile_s, 2),
    }


def _solve_rate(
    n_obj: int,
    kernel_dtype,
    n_nodes: int = N_NODES,
    n_iters: int = 30,
    chain_budget_s: float | None = None,
) -> dict:
    """On-device OT solve throughput; returns a result dict.

    Uses the scaling-form core (``rio_tpu/ops/scaling.py``): K = exp(-C/eps)
    is built once, each iteration is two matrix-vector products, and the
    capacity-aware rounding pass REUSES K (bf16) instead of re-reading the
    fp32 cost — no per-iteration transcendentals anywhere, bandwidth-bound
    on K alone. Reports the sinkhorn-only rate too, so the rounding share
    stays visible.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from rio_tpu.ops import (
        exact_quota_repair,
        plan_rounded_assign_from_scaling,
        scaling_core_auto,
        scaling_impl_for,
    )
    from rio_tpu.ops.sinkhorn import normalize_marginals

    def _row_marginal_err(K, u, v, mass, cap):
        # Convergence proof: row-marginal L1 error against the SOLVER's own
        # normalized target (the column marginal is exact by construction
        # after the trailing v update). One extra matvec; included in BOTH
        # solve_only and step so full_ms - sinkhorn_ms still isolates the
        # rounding share.
        Kv = jnp.matmul(K, v.astype(K.dtype), preferred_element_type=jnp.float32)
        a, _ = normalize_marginals(mass, cap)
        return jnp.sum(jnp.abs(u * Kv - a))

    def solve_only(cost, mass, cap):
        u, v, K, _ = scaling_core_auto(
            cost, mass, cap, eps=0.05, n_iters=n_iters, kernel_dtype=kernel_dtype
        )
        return jnp.sum(u) + jnp.sum(v) + _row_marginal_err(K, u, v, mass, cap)

    def step(cost, mass, cap):
        u, v, K, _ = scaling_core_auto(
            cost, mass, cap, eps=0.05, n_iters=n_iters, kernel_dtype=kernel_dtype
        )
        marginal_err = _row_marginal_err(K, u, v, mass, cap)
        # Chunk the rounding pass so its cumsum temps stay bounded. NOTE:
        # quantile ranks are per-chunk, which is only equivalent to global
        # ranking because every row here is real with identical mass (each
        # chunk spreads over the same marginals); mixed masses or padding
        # split across chunks would need an explicit rank offset.
        chunk = min(CHUNK, n_obj)
        n_chunks = n_obj // chunk
        K_c = K.reshape(n_chunks, chunk, n_nodes)
        u_c = u.reshape(n_chunks, chunk)

        def round_chunk(args):
            k, uu = args
            return plan_rounded_assign_from_scaling(k, uu, v)

        assignment = lax.map(round_chunk, (K_c, u_c)).reshape(-1)
        # Exact-capacity repair: CDF rounding matches capacities only in
        # expectation (~3-sigma overshoot on the max-loaded node); the
        # repair re-slots just the excess (~3% of objects) so every node
        # lands exactly on its integer quota. Quotas come straight from
        # the capacity marginals — no extra pass over K.
        expected = cap / jnp.maximum(jnp.sum(cap), 1e-30) * n_obj
        assignment = exact_quota_repair(assignment, expected)
        # Scalar checksum: pulling it to host forces full completion (the
        # axon tunnel's block_until_ready returns before execution finishes).
        return (
            assignment,
            _mean_assigned_cost(cost, assignment),
            marginal_err,
            jnp.sum(assignment),
        )

    t_enter = time.perf_counter()
    cost, mass, cap = _tier_inputs(n_obj, n_nodes)
    solve_s, solve_compile, _ = _time_fn(jax.jit(solve_only), cost, mass, cap)
    full_s, full_compile, out = _time_fn(jax.jit(step), cost, mass, cap)

    # Sustained solve time: K solves chained in one executable, one pull at
    # the end — the relay's per-call dispatch+sync (~300 ms r4) divides
    # out; see _collapsed_rate. Two carried perturbations keep EVERY part
    # of the solve inside the loop against XLA's while-loop invariant code
    # motion: the cost is shifted by 1e-30*mass_c[0] (so the kernel build
    # K = exp(-C/eps) — a real per-solve cost — cannot hoist; it fuses into
    # the existing exp sweep, no extra HBM traffic) and the mass carries
    # 1e-20*u forward. Both are bit-exact identities on O(1) fp32 values,
    # so every step solves the same problem. Budgeted from MEASURED timings
    # of this very call (the budget arrives stale — the two compiles above
    # already burned into it): one more compile of comparable cost + 3
    # chained executions must clearly fit.
    @functools.partial(jax.jit, static_argnames=("k",))
    def chained_solve(cost, mass, cap, k):
        def body(_, mass_c):
            u, v, K, _sh = scaling_core_auto(
                cost + 1e-30 * mass_c[0], mass_c, cap,
                eps=0.05, n_iters=n_iters, kernel_dtype=kernel_dtype,
            )
            return mass_c + 1e-20 * u
        final = lax.fori_loop(0, k, body, mass)
        return jnp.sum(final)

    k_chain = int(min(8, max(2, round(6.0 / max(solve_s, 0.05)))))
    per_step_s, chain_extra = _maybe_time_chain(
        chained_solve, (cost, mass, cap), k_chain, chain_budget_s,
        t_enter, (solve_compile + full_compile) / 2, solve_s,
    )
    chained_res = None
    if per_step_s is not None:
        chained_res = {"solve_chain_ms": round(per_step_s * 1e3, 2), **chain_extra}
    # Quality evidence from the already-computed assignment: the speed
    # number only counts if it is actually capacity-balanced.
    import numpy as np

    loads = np.bincount(np.asarray(out[0]), minlength=n_nodes)
    # Cost quality: mean assigned cost on U[0,1) random costs — random
    # placement scores 0.50; lower is better (shows the solve optimizes
    # per-object cost, not just balance). Computed inside the jitted step.
    mean_cost = float(out[1])
    # With a chained solve time, the per-decision latency is the sustained
    # solve plus the rounding share. The rounding share is the DIFFERENCE
    # of two single-call times, so the relay's per-call overhead cancels.
    decision_s = full_s
    if chained_res is not None:
        decision_s = chained_res["solve_chain_ms"] / 1e3 + max(full_s - solve_s, 0.0)
    result = {
        "rate": n_obj / decision_s,
        "full_ms": round(decision_s * 1e3, 2),
        "single_shot_ms": round(full_s * 1e3, 2),
        "sinkhorn_ms": round(solve_s * 1e3, 2),
        "compile_s": round(solve_compile + full_compile, 2),
        "n_nodes": n_nodes,
        "n_iters": n_iters,
        "max_load": int(loads.max()),
        "fair_load": n_obj // n_nodes,
        "mean_cost": round(mean_cost, 4),
        "marginal_err": float(out[2]),
        "solver_impl": scaling_impl_for(n_obj, n_nodes),
    }
    if chained_res is not None:
        result.update(chained_res)
    return result


def _tier_inputs(n_obj: int, n_nodes: int):
    """The shared (cost, mass, cap) inputs every solve tier measures on."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    cost = jax.random.uniform(key, (n_obj, n_nodes), jnp.float32)
    mass = jnp.ones((n_obj,), jnp.float32)
    cap = jnp.ones((n_nodes,), jnp.float32)
    return cost, mass, cap


def _mean_assigned_cost(cost, assignment):
    """Mean of cost[i, assignment[i]] — computed INSIDE the jitted step so
    it is banked with the tier result (no post-measurement eager device
    work; an extra pass after timing once risked a watchdog exit mid-op)."""
    import jax.numpy as jnp

    return jnp.mean(jnp.take_along_axis(cost, assignment[:, None], axis=1))


def _time_fn(fn, cost, mass, cap) -> tuple[float, float, object]:
    """Warm (compile) + best-of-3; the host float() pull forces completion
    (the axon tunnel's block_until_ready returns early). Returns
    (best_seconds, compile_seconds, last_output) — callers reuse the
    output for quality checks instead of paying another on-device run.

    The pull is a PLAIN float() on the jit-computed scalar checksum —
    never an eager-op wrapper: mixing eager ops into the sync path hung
    indefinitely through the axon relay (r4 wedge)."""
    import jax

    def force(out):
        chk = out[-1] if isinstance(out, tuple) else out
        float(chk)

    t0 = time.perf_counter()
    out = fn(cost, mass, cap)
    jax.block_until_ready(out)
    force(out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(cost, mass, cap)
        force(out)
        times.append(time.perf_counter() - t0)
    return min(times), compile_s, out


def _collapsed_rate(
    n_obj: int,
    n_nodes: int = N_NODES,
    dead_frac: float = 0.03,
    n_iters: int = 30,
    move_cost: float = 0.5,
    chain_budget_s: float | None = None,
) -> dict:
    """The directory's COMMITTED fast path for a full rebalance, end to end.

    Measures exactly what ``JaxObjectPlacement.rebalance()`` runs for a
    flat (non-mesh) OT-mode re-solve (``jax_placement.py`` collapsed
    branch): per-seat counts -> class-collapsed (M x M) Sinkhorn
    (``ops/structured.class_quotas``) -> on-device quota expansion
    (``expand_class_quotas``) -> exact integer-quota repair — one XLA
    pipeline, N never materializes an (N x M) cost.  Scenario is BASELINE
    row 3/4: n_obj objects seated across n_nodes, ``dead_frac`` of nodes
    just died (churn), the solve must re-seat the displaced share and
    nothing else.  The headline time is the SUSTAINED per-decision latency
    over a chain of churn re-solves compiled into one executable (each
    step re-seats the previous step's assignment after a fresh node-death
    wave) — the relay's per-call dispatch+sync overhead, which dwarfs the
    device compute at this size, divides out.  The single-call time (incl.
    one relay sync), the bulk host pull, and the mover-only directory dict
    update (O(movers), matching rebalance()'s apply loop) are reported
    separately.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rio_tpu.ops import exact_quota_repair
    from rio_tpu.ops.assignment import build_cost_matrix
    from rio_tpu.ops.structured import class_quotas, expand_class_quotas

    t_enter = time.perf_counter()
    m = n_nodes
    n_dead = max(1, int(m * dead_frac))
    cur = jax.random.randint(jax.random.PRNGKey(2), (n_obj,), 0, m, jnp.int32)
    alive_np = np.ones(m, np.float32)
    alive_np[:n_dead] = 0.0  # the churn event: n_dead nodes just died
    alive = jnp.asarray(alive_np)
    cap = jnp.ones((m,), jnp.float32)
    # Same eps rule as the provider: off-diagonal leakage < 1e-8.
    class_eps = min(0.05, move_cost / 25.0)

    def decide(cur, cap, alive):
        """The committed rebalance decision, exactly as the provider runs it."""
        base_cost = build_cost_matrix(jnp.zeros((m,), jnp.float32), cap, alive)[0]
        counts = jnp.bincount(cur, length=m)
        quotas, g, _cls_err = class_quotas(
            base_cost, counts, cap * alive,
            move_cost=move_cost, eps=class_eps, n_iters=n_iters,
        )
        expanded = expand_class_quotas(quotas, cur)
        cap_alive = cap * alive
        expected = cap_alive / jnp.maximum(jnp.sum(cap_alive), 1e-30) * n_obj
        assignment = exact_quota_repair(
            expanded, expected, prefer_keep=expanded == cur
        )
        return assignment, g

    @jax.jit
    def step(cur, cap, alive):
        assignment, g = decide(cur, cap, alive)
        moved = jnp.sum(assignment != cur)
        return assignment, g, moved, jnp.sum(assignment)

    def force(out):
        # Plain pull of the jit-computed scalar checksum. NOT an eager
        # jnp.sum wrapper: mixing eager ops into the sync path hung
        # indefinitely through the axon relay (r4), and the pull alone
        # already forces completion (block_until_ready returns early
        # through the tunnel, so a value pull is the only reliable sync).
        float(out[-1])

    t0 = time.perf_counter()
    out = step(cur, cap, alive)
    jax.block_until_ready(out)
    force(out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = step(cur, cap, alive)
        force(out)
        times.append(time.perf_counter() - t0)
    best = min(times)

    # Sustained decision time: K churn re-solves CHAINED in one executable,
    # one host sync at the end. Through the axon relay a single call's wall
    # time is dominated by dispatch+sync (~300 ms measured r4, vs 0.6 ms of
    # device compute for this pipeline) and block_until_ready returns
    # early — total/K over a data-dependent chain is the only tunnel-proof
    # device timing. Each step kills an alternating set of n_dead nodes, so
    # every step re-seats a real displaced share (~dead_frac of objects)
    # from the PREVIOUS step's assignment: same shapes, fresh churn, no
    # loop-invariant hoisting.
    alive_b_np = np.ones(m, np.float32)
    alive_b_np[n_dead : 2 * n_dead] = 0.0
    alive_b = jnp.asarray(alive_b_np)

    @functools.partial(jax.jit, static_argnames=("k",))
    def chained(cur, cap, alive_a, alive_b, k):
        def body(i, c):
            alive = jnp.where(i % 2 == 0, alive_a, alive_b)
            assignment, _ = decide(c, cap, alive)
            return assignment
        final = jax.lax.fori_loop(0, k, body, cur)
        return jnp.sum(final)

    single_s = max(best, 1e-4)
    chain_steps = int(min(64, max(8, round(20.0 / single_s))))
    per_step_s, chain_extra = _maybe_time_chain(
        chained, (cur, cap, alive, alive_b), chain_steps, chain_budget_s,
        t_enter, compile_s, single_s,
    )
    chained_res = None
    if per_step_s is not None:
        chained_res = {"decision_ms": round(per_step_s * 1e3, 2), **chain_extra}

    # Host-side bookkeeping, timed separately: the 4 MB assignment pull and
    # the directory dict update as rebalance() actually applies it — one
    # vectorized mover extraction, then a Python loop over ONLY the movers
    # (the displaced few percent), not all N keys.
    t0 = time.perf_counter()
    a = np.asarray(out[0])
    pull_ms = (time.perf_counter() - t0) * 1e3
    cur_np = np.asarray(cur)
    keys = [str(i) for i in range(n_obj)]
    directory = {k: int(v) for k, v in zip(keys, cur_np.tolist())}
    t0 = time.perf_counter()
    mover_pos = np.nonzero(a != cur_np)[0]
    for p in mover_pos.tolist():
        directory[keys[p]] = int(a[p])
    host_apply_ms = (time.perf_counter() - t0) * 1e3

    displaced = int((cur_np < n_dead).sum())  # objects on dead nodes
    loads = np.bincount(a, minlength=m)
    # ``full_ms`` is the per-decision latency: the sustained (chained)
    # number when measured, else the single-shot one. ``single_shot_ms``
    # always records the relay-inclusive single call for transparency.
    decision_s = (
        chained_res["decision_ms"] / 1e3 if chained_res is not None else best
    )
    result = {
        "rate": n_obj / decision_s,
        "full_ms": round(decision_s * 1e3, 2),
        "single_shot_ms": round(best * 1e3, 2),
        "compile_s": round(compile_s, 2),
        "n_nodes": m,
        "n_iters": n_iters,
        "dead_nodes": n_dead,
        "displaced": displaced,
        "moved": int(out[2]),
        "max_load": int(loads.max()),
        "dead_load": int(loads[:n_dead].sum()),
        "fair_load": n_obj // (m - n_dead),
        "pull_ms": round(pull_ms, 2),
        "host_apply_ms": round(host_apply_ms, 2),
    }
    if chained_res is not None:
        result.update(chained_res)
    return result


def _warm_assign_rate(
    batch: int, n_nodes: int = N_NODES, chain_budget_s: float | None = None
) -> dict:
    """BASELINE row 4's single-chip half: warm incremental allocation.

    The ``assign_batch`` device path (``jax_placement._solve_chunk``): a
    batch of NEW objects lands via the cached node potentials from the
    last OT solve + greedy waterfill over remaining headroom — no Sinkhorn
    re-solve on the allocation path.
    """
    import jax
    import jax.numpy as jnp

    from rio_tpu.ops.assignment import build_cost_matrix, greedy_balanced_assign

    t_enter = time.perf_counter()
    m = n_nodes
    key = jax.random.PRNGKey(3)
    g = jax.random.normal(key, (m,), jnp.float32) * 0.1  # cached potentials
    load = jnp.ones((m,), jnp.float32) * (batch / m)
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)

    @jax.jit
    def step(g, load, cap, alive):
        cost = build_cost_matrix(load, cap, alive) - g[None, :]
        rows = jnp.broadcast_to(cost, (batch, m))
        mass = jnp.ones((batch,), jnp.float32)
        a = greedy_balanced_assign(rows, mass, cap * alive, load)
        return a, jnp.sum(a)

    def force(out):
        float(out[-1])  # plain pull; see _collapsed_rate.force

    t0 = time.perf_counter()
    out = step(g, load, cap, alive)
    jax.block_until_ready(out)
    force(out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = step(g, load, cap, alive)
        force(out)
        times.append(time.perf_counter() - t0)
    best = min(times)

    # Sustained per-batch time: K allocations chained in one executable
    # (each batch's assignment updates the load the next batch sees — the
    # real warm-allocation sequence), one pull at the end; see
    # _collapsed_rate for why single-call timing through the relay lies.
    @functools.partial(jax.jit, static_argnames=("k",))
    def chained(g, load, cap, alive, k):
        def body(_, ld):
            cost = build_cost_matrix(ld, cap, alive) - g[None, :]
            rows = jnp.broadcast_to(cost, (batch, m))
            mass = jnp.ones((batch,), jnp.float32)
            a = greedy_balanced_assign(rows, mass, cap * alive, ld)
            return ld + jnp.bincount(a, length=m).astype(ld.dtype)
        final_load = jax.lax.fori_loop(0, k, body, load)
        return jnp.sum(final_load)

    k_steps = 16
    per_step_s, chain_extra = _maybe_time_chain(
        chained, (g, load, cap, alive), k_steps, chain_budget_s,
        t_enter, compile_s, best,
    )
    decision_s = per_step_s if per_step_s is not None else best
    return {
        "rate": batch / decision_s,
        "full_ms": round(decision_s * 1e3, 2),
        "single_shot_ms": round(best * 1e3, 2),
        "batch": batch,
        "compile_s": round(compile_s, 2),
        **chain_extra,
    }


def _incremental_rate(
    n_obj: int,
    batch: int = 65_536,
    n_nodes: int = N_NODES,
    dead_frac: float = 0.03,
    n_iters: int = 30,
    move_cost: float = 0.5,
    chain_budget_s: float | None = None,
) -> dict:
    """BASELINE row 4 combined: the full churn CYCLE, chained (VERDICT r4 #5).

    One cycle = what a churny minute actually runs, in order: a warm
    allocation batch (new objects seated via cached potentials + greedy
    waterfill over current loads — ``jax_placement._solve_chunk``) followed
    by a full churn re-solve of the seated population after a node-death
    wave (the committed class-collapsed ``rebalance()`` pipeline). K cycles
    compile into ONE executable with one host pull, so the per-cycle time
    is tunnel-proof (single-call timings through the axon relay are ~99.8%
    dispatch+sync at this size). Allocation turnover is modeled
    steady-state: each cycle's batch replaces the previous cycle's (the
    seated population and all shapes stay static for XLA).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rio_tpu.ops import exact_quota_repair
    from rio_tpu.ops.assignment import build_cost_matrix, greedy_balanced_assign
    from rio_tpu.ops.structured import class_quotas, expand_class_quotas

    t_enter = time.perf_counter()
    m = n_nodes
    n_dead = max(1, int(m * dead_frac))
    cur = jax.random.randint(jax.random.PRNGKey(5), (n_obj,), 0, m, jnp.int32)
    g_warm = jax.random.normal(jax.random.PRNGKey(6), (m,), jnp.float32) * 0.1
    cap = jnp.ones((m,), jnp.float32)
    alive_a_np = np.ones(m, np.float32)
    alive_a_np[:n_dead] = 0.0
    alive_b_np = np.ones(m, np.float32)
    alive_b_np[n_dead : 2 * n_dead] = 0.0
    alive_a = jnp.asarray(alive_a_np)
    alive_b = jnp.asarray(alive_b_np)
    class_eps = min(0.05, move_cost / 25.0)

    def cycle(cur, extra_load, alive):
        # 1. warm allocation: batch new objects onto current loads.
        seated = jnp.bincount(cur, length=m).astype(jnp.float32)
        cost = (
            build_cost_matrix(seated + extra_load, cap, alive) - g_warm[None, :]
        )
        rows = jnp.broadcast_to(cost, (batch, m))
        mass = jnp.ones((batch,), jnp.float32)
        alloc = greedy_balanced_assign(rows, mass, cap * alive, seated + extra_load)
        extra_load = jnp.bincount(alloc, length=m).astype(jnp.float32)
        # 2. churn re-solve of the seated population (collapsed pipeline).
        base_cost = build_cost_matrix(jnp.zeros((m,), jnp.float32), cap, alive)[0]
        counts = jnp.bincount(cur, length=m)
        quotas, _, _ = class_quotas(
            base_cost, counts, cap * alive,
            move_cost=move_cost, eps=class_eps, n_iters=n_iters,
        )
        expanded = expand_class_quotas(quotas, cur)
        cap_alive = cap * alive
        expected = cap_alive / jnp.maximum(jnp.sum(cap_alive), 1e-30) * n_obj
        assignment = exact_quota_repair(
            expanded, expected, prefer_keep=expanded == cur
        )
        return assignment, extra_load

    @jax.jit
    def step(cur, extra_load, alive):
        assignment, extra = cycle(cur, extra_load, alive)
        return assignment, extra, jnp.sum(assignment) + jnp.sum(extra)

    def force(out):
        float(out[-1])  # plain pull; see _collapsed_rate.force

    zero_extra = jnp.zeros((m,), jnp.float32)
    t0 = time.perf_counter()
    out = step(cur, zero_extra, alive_a)
    jax.block_until_ready(out)
    force(out)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = step(cur, zero_extra, alive_a)
        force(out)
        times.append(time.perf_counter() - t0)
    best = min(times)

    @functools.partial(jax.jit, static_argnames=("k",))
    def chained(cur, extra_load, alive_a, alive_b, k):
        def body(i, state):
            c, e = state
            alive = jnp.where(i % 2 == 0, alive_a, alive_b)
            return cycle(c, e, alive)
        final_cur, final_extra = jax.lax.fori_loop(
            0, k, body, (cur, extra_load)
        )
        return jnp.sum(final_cur) + jnp.sum(final_extra)

    single_s = max(best, 1e-4)
    k_cycles = int(min(32, max(8, round(15.0 / single_s))))
    per_cycle_s, chain_extra = _maybe_time_chain(
        chained, (cur, zero_extra, alive_a, alive_b), k_cycles,
        chain_budget_s, t_enter, compile_s, single_s,
    )
    cycle_s = per_cycle_s if per_cycle_s is not None else best
    return {
        # One cycle serves one churn event plus `batch` allocations; the
        # 10%/min budget needs a re-solve well inside the ~seconds between
        # gossip-detected death waves — cycles/sec is the headroom number.
        "cycle_ms": round(cycle_s * 1e3, 2),
        "cycles_per_sec": round(1.0 / cycle_s, 1),
        "single_shot_ms": round(best * 1e3, 2),
        "n_obj": n_obj,
        "alloc_batch": batch,
        "dead_nodes": n_dead,
        "compile_s": round(compile_s, 2),
        **chain_extra,
    }


def _greedy_rate(n_obj: int, n_nodes: int = N_NODES) -> dict:
    """Greedy waterfill tier on the same inputs as the OT tier."""
    import jax
    import jax.numpy as jnp

    from rio_tpu.ops.assignment import greedy_balanced_assign

    @jax.jit
    def step(c, m, k):
        a = greedy_balanced_assign(c, m, k)
        return a, _mean_assigned_cost(c, a), jnp.sum(a)

    cost, mass, cap = _tier_inputs(n_obj, n_nodes)
    best, compile_s, out = _time_fn(step, cost, mass, cap)
    mean_cost = float(out[1])
    return {
        "rate": n_obj / best,
        "full_ms": round(best * 1e3, 2),
        "compile_s": round(compile_s, 2),
        "mean_cost": round(mean_cost, 4),
    }


def _hier_rate(
    n_obj: int,
    n_nodes: int = N_NODES,
    n_groups: int = 32,
    d: int = 16,
    chain_budget_s: float | None = None,
) -> dict:
    """BASELINE row-5 tier: hierarchical 2-level OT at the scale ceiling.

    10M x 1k cannot materialize a flat cost (40 GB fp32); the two-level
    solve runs in O(N*(G+S+d)) memory (~2.6 GB at 10M) — see
    ``rio_tpu/parallel/hierarchical.py``. When the budget allows, the
    per-solve time is also measured over a K-chain (see _collapsed_rate:
    single-call times through the relay are mostly dispatch+sync at the
    smaller rung sizes); a carried 1e-30-scale feature perturbation keeps
    every solve inside the loop against invariant hoisting.
    """
    import jax
    import jax.numpy as jnp

    from rio_tpu.parallel.hierarchical import (
        chunked_hierarchical_assign,
        hierarchical_assign,
    )

    # Above the 655k chunk shape, the TPU backend's compile is superlinear
    # (v5e: 50 s at 655k, 599 s flat at 2.6M) — run the sharded design
    # temporally instead: lax.map over fixed-shape chunks pins compile cost
    # to the chunk while execution scales linearly (CPU check: 8.5 s to
    # compile 16x655k vs 599 s the flat 2.6M cost on device).
    hier_chunk = 655_360
    n_chunks = n_obj // hier_chunk if n_obj > hier_chunk and n_obj % hier_chunk == 0 else 1

    t_enter = time.perf_counter()
    key = jax.random.PRNGKey(1)
    k1, k2 = jax.random.split(key)
    obj_feat = jax.random.normal(k1, (n_obj, d), jnp.float32)
    node_feat = jax.random.normal(k2, (d, n_nodes), jnp.float32)
    cap = jnp.ones((n_nodes,), jnp.float32)
    alive = jnp.ones((n_nodes,), jnp.float32)

    def run():
        if n_chunks > 1:
            res = chunked_hierarchical_assign(
                obj_feat, node_feat, cap, alive,
                n_groups=n_groups, n_chunks=n_chunks,
            )
        else:
            res = hierarchical_assign(
                obj_feat, node_feat, cap, alive, n_groups=n_groups
            )
        return res.assignment, res.overflow

    t0 = time.perf_counter()
    _, ovf = run()
    overflow = int(ovf)  # host pull forces completion
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        _, ovf = run()
        int(ovf)
        times.append(time.perf_counter() - t0)
    best = min(times)

    @functools.partial(jax.jit, static_argnames=("k",))
    def chained(obj_feat, node_feat, cap, alive, k):
        def body(_, carry):
            if n_chunks > 1:
                res = chunked_hierarchical_assign(
                    obj_feat + carry, node_feat, cap, alive,
                    n_groups=n_groups, n_chunks=n_chunks,
                )
            else:
                res = hierarchical_assign(
                    obj_feat + carry, node_feat, cap, alive, n_groups=n_groups
                )
            # 1e-30 * sum(assignment) is ~1e-22 against O(1) features:
            # bit-exact identity, structurally loop-carried.
            return 1e-30 * jnp.sum(res.assignment).astype(jnp.float32)
        final = jax.lax.fori_loop(0, k, body, jnp.float32(0.0))
        return final

    k_chain = int(min(8, max(2, round(4.0 / max(best, 0.05)))))
    per_step_s, chain_extra = _maybe_time_chain(
        chained, (obj_feat, node_feat, cap, alive), k_chain, chain_budget_s,
        t_enter, compile_s, best,
    )
    decision_s = per_step_s if per_step_s is not None else best
    return {
        "rate": n_obj / decision_s,
        "full_ms": round(decision_s * 1e3, 2),
        "single_shot_ms": round(best * 1e3, 2),
        "n_obj": n_obj,
        "n_nodes": n_nodes,
        "n_groups": n_groups,
        "overflow": overflow,
        "n_chunks": n_chunks,
        "compile_s": round(compile_s, 2),
        **chain_extra,
    }


def run_hier_tier(n_obj: int, deadline: float, platform: str = "tpu") -> None:
    """Child entry for the BASELINE row-5 (hierarchical) tier.

    Adaptive sizing against the relay-wedge hazard: measure a quarter-size
    tier first, project the full tier's cost (4x runtime + a fresh compile
    — shapes differ, nothing is cached), and only attempt the full size
    when it fits well inside the deadline. Whatever completed last is the
    reported tier.

    ``platform="cpu"`` is the REHEARSAL mode (pins the CPU backend before
    any jax init, like the pallas debug mode): the ladder's projection /
    banking / chain-gate logic has historically failed exactly when a
    healthy window finally opened (r4: the first rung's compile blew the
    deadline and the watchdog exit left no evidence), so it must be
    executable end-to-end without hardware.
    """
    start = time.monotonic()
    _arm_watchdog(deadline, EXIT_WATCHDOG)
    probe_timer = _arm_watchdog(min(PROBE_DEADLINE_S, deadline), EXIT_INIT_FAIL)
    if platform == "cpu":
        from rio_tpu.utils.jaxenv import force_cpu

        force_cpu()
    import jax

    try:
        devices = jax.devices()
    except Exception as e:
        print(f"# backend init failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    probe_timer.cancel()
    if platform == "tpu" and devices[0].platform != "tpu":
        sys.exit(EXIT_INIT_FAIL)
    fake_pull = os.environ.get("RIO_TPU_HIER_PREFLIGHT_MS")
    if fake_pull is not None and platform == "tpu":
        # Rehearsal-only hook: a stale export in the shell must not
        # silently disable the relay-health gate on a real TPU run.
        print(
            "# hier: ignoring RIO_TPU_HIER_PREFLIGHT_MS on tpu "
            "(rehearsal-only hook)",
            file=sys.stderr,
        )
        fake_pull = None
    preflight_ms = None
    if platform == "tpu" or fake_pull is not None:
        # Pull-latency pre-flight: the wedge vector is a watchdog os._exit
        # DURING a long compile, and rising pull latency is the proven
        # leading indicator (212 ms healthy -> 1119 ms in the run whose
        # ladder blew its budget). A 4 MB pull completes in bounded time,
        # so bailing here is a clean exit — never mid-compile.
        # RIO_TPU_HIER_PREFLIGHT_MS injects a fake measurement so the CPU
        # rehearsal can execute the skip/force branches end-to-end (this
        # gate must not be the one piece of ladder logic that first runs
        # inside a scarce live window — the r4 failure mode).
        if fake_pull is not None:
            try:
                pull_ms = float(fake_pull)
            except ValueError:
                print(
                    f"# hier: bad RIO_TPU_HIER_PREFLIGHT_MS={fake_pull!r}; "
                    "treating as healthy",
                    file=sys.stderr,
                )
                pull_ms = 0.0
        else:
            import numpy as _np

            # Warm one-way pulls, matching the ceiling's calibration data
            # (the collapsed tier's pull_ms and tpu_probe's pull4mb are
            # D2H-only; timing the cold H2D upload too would read ~2x
            # high). Min of 3 because a single tunnel sample is noisy
            # (healthy windows have pulled 170-970 ms); sustained >700 ms
            # across all three is the degradation signal. Each sample
            # needs a FRESH device array: jax.Array caches the host value
            # after the first device_get, so re-pulling the same array
            # measures a dict lookup, not the relay. A hung pull must not
            # burn the whole 700 s budget before its os._exit (a stall
            # here is still an execution-time exit — the documented-
            # harmless class — but exiting in seconds beats exiting after
            # the parent gave up): bound the pre-flight with its own
            # short watchdog.
            preflight_timer = _arm_watchdog(
                90.0,
                EXIT_PREFLIGHT_HANG,
                note="hier pre-flight pull hung; relay likely wedged",
            )
            pull_ms = float("inf")
            for _ in range(3):
                x = jax.device_put(_np.zeros(1 << 20, _np.float32))
                x.block_until_ready()
                t0 = time.monotonic()
                jax.device_get(x)
                pull_ms = min(pull_ms, (time.monotonic() - t0) * 1e3)
                del x
            preflight_timer.cancel()
        if pull_ms > HIER_PULL_MAX_MS:
            if os.environ.get("RIO_TPU_BENCH_HIER") == "1":
                print(
                    f"# hier: relay degraded (pull4mb {pull_ms:.0f} ms) but "
                    "RIO_TPU_BENCH_HIER=1 forces the ladder",
                    file=sys.stderr,
                )
            else:
                print(
                    f"# hier: relay degraded (pull4mb {pull_ms:.0f} ms > "
                    f"{HIER_PULL_MAX_MS:.0f} ms ceiling); skipping ladder",
                    file=sys.stderr,
                )
                sys.exit(EXIT_TIER_TIMEOUT)
        preflight_ms = pull_ms
    try:
        # Ladder of sizes, each banked before the next is attempted: the r4
        # run started straight at quarter size (2.6M), blew the deadline
        # inside the first compile, and the watchdog exit left NO evidence
        # at all. Small rungs are cheap insurance.
        if n_obj > 655_360 and n_obj % 655_360 == 0:
            # Chunked era: compile cost is pinned to the 655k chunk shape
            # (see _hier_rate), so the middle rung no longer buys risk
            # reduction — ladder straight from the chunk shape to the full
            # size and spend the budget on the headline rung.
            sizes = [655_360, n_obj]
        else:
            sizes = sorted(
                {
                    min(n_obj, max(65_536, n_obj // 16)),
                    min(n_obj, max(131_072, n_obj // 4)),
                    n_obj,
                }
            )
        result = {"ok": True, "kind": "hier", "rungs": {}}
        if preflight_ms is not None and preflight_ms != float("inf"):
            # Banked so _relay_health can pair it with the collapsed tier's
            # pull for the in-run degradation verdict.
            result["preflight_pull_ms"] = round(preflight_ms, 1)
        prev = prev_size = None
        for size in sizes:
            if prev is not None:
                ratio = size / prev_size
                # Project from the single-call time (the chained decision
                # time is smaller and would undercount) + compile cushion
                # covering both the plain and chained executables.
                prev_single = prev.get("single_shot_ms", prev["full_ms"])
                projected = (
                    ratio * (4 * prev_single / 1e3) + 2.5 * prev["compile_s"]
                )
                if time.monotonic() - start + projected > 0.7 * deadline:
                    print(
                        f"# hier: stopping before {size} "
                        f"(projected {projected:.0f}s over budget)",
                        file=sys.stderr,
                    )
                    break
            tier = _hier_rate(
                size,
                chain_budget_s=deadline - (time.monotonic() - start) - 30.0,
            )
            print(f"# hier rung {size}: {tier}", file=sys.stderr)
            result["rungs"][str(size)] = tier
            result["largest"] = tier
            print(json.dumps(result), flush=True)  # bank every rung
            prev, prev_size = tier, size
    except Exception as e:
        print(f"# hier tier failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_SOLVE_FAIL)


def run_hier_mesh_ab_tier(n_obj: int, deadline: float) -> None:
    """Child entry for the mesh x chunk vs chunked-only paired A/B.

    ISSUE 18 evidence: at MATCHED N, solve once through the composed
    ``mesh_chunked_hierarchical_assign_timed`` (8 virtual CPU devices x
    65,536-row cells — the shape whose compile the composition pins) and
    once through the single-chip ``chunked_hierarchical_assign_timed`` at
    the production 524,288-row chunk shape, and report both arms' chunk
    timings plus a sampled transport-cost ratio (mean best-minus-assigned
    affinity regret over a fixed 65,536-row sample; the full N x M
    affinity matrix would be tens of GB at the target scale).

    Always a CPU child: ``force_cpu(8)`` pins the virtual mesh before any
    backend touch, so this can run while the relay is wedged. TPU rungs
    stay ``tpu_round.py``-owned.
    """
    _arm_watchdog(deadline, EXIT_WATCHDOG)
    from rio_tpu.utils.jaxenv import force_cpu

    force_cpu(8)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rio_tpu.parallel import make_mesh
    from rio_tpu.parallel.hierarchical import (
        chunked_hierarchical_assign_timed,
        mesh_chunked_hierarchical_assign_timed,
    )

    d, m, g = 16, 1024, 32
    n_shards, cell, chunk_rows = 8, 65_536, 524_288
    assert n_obj % (n_shards * cell) == 0 and n_obj % chunk_rows == 0, n_obj
    mesh_chunks = n_obj // (n_shards * cell)
    host_chunks = n_obj // chunk_rows

    k1, k2 = jax.random.split(jax.random.PRNGKey(18))
    obj_feat = jax.random.normal(k1, (n_obj, d), jnp.float32)
    node_feat = jax.random.normal(k2, (d, m), jnp.float32) * 0.2
    cap = jnp.ones((m,), jnp.float32)
    alive = jnp.ones((m,), jnp.float32)
    mesh = make_mesh(jax.devices()[:n_shards])
    # Drain the async feature-generation chain before either arm's wall
    # timer starts: O(N) pending RNG work would otherwise land in the
    # FIRST arm's wall/first-chunk numbers only, skewing the paired A/B.
    jax.block_until_ready((obj_feat, node_feat))

    def arm(fn, **kw):
        t0 = time.perf_counter()
        res, chunk_ms = fn(obj_feat, node_feat, cap, alive, n_groups=g, **kw)
        jax.block_until_ready(res.assignment)
        wall = time.perf_counter() - t0
        steady = (
            round(float(np.median(np.asarray(chunk_ms[1:]))), 3)
            if len(chunk_ms) > 1 else None
        )
        stats = {
            "n_chunks": len(chunk_ms),
            "first_chunk_ms": chunk_ms[0],
            "steady_chunk_ms": steady,
            "wall_s": round(wall, 2),
            "rate": round(n_obj / wall),
            "overflow": int(res.overflow),
            "chunk_ms": chunk_ms,
        }
        return np.asarray(res.assignment), stats

    a_mesh, mesh_stats = arm(
        lambda *a, **kw: mesh_chunked_hierarchical_assign_timed(mesh, *a, **kw),
        n_chunks=mesh_chunks,
    )
    a_chunk, chunk_stats = arm(
        chunked_hierarchical_assign_timed, n_chunks=host_chunks
    )

    idx = np.arange(0, n_obj, max(1, n_obj // 65_536))[:65_536]
    on_s = np.asarray(obj_feat[idx] @ node_feat)
    best = on_s.max(axis=1)
    rows = np.arange(len(idx))
    cost_mesh = float(np.mean(best - on_s[rows, a_mesh[idx]]))
    cost_chunk = float(np.mean(best - on_s[rows, a_chunk[idx]]))
    result = {
        "ok": True,
        "kind": "hier_mesh_ab",
        "n_obj": n_obj,
        "n_nodes": m,
        "n_groups": g,
        "devices": n_shards,
        "cell_rows": cell,
        "mesh_chunk": mesh_stats,
        "chunked_only": chunk_stats,
        "transport_cost": {
            "mesh_chunk": round(cost_mesh, 5),
            "chunked_only": round(cost_chunk, 5),
            "ratio": round(cost_mesh / max(cost_chunk, 1e-12), 4),
        },
    }
    print(json.dumps(result), flush=True)


def hier_mesh_ab(n_obj: int = 2_097_152, deadline: float = 900.0) -> dict:
    """Paired mesh x chunk vs chunked-only A/B at matched N (host stage).

    Runs in a CPU child (``JAX_PLATFORMS=cpu`` + 8 virtual devices, axon
    sitecustomize bypassed) so the orchestrator's backend state and the
    relay are never touched — banked into the cpu sidecar under host
    provenance like every host stage, never carried into a tpu bank.
    """
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--hier", "--mesh-ab", "--tier", str(n_obj),
        "--platform", "cpu", "--deadline", str(deadline),
    ]
    proc = subprocess.run(
        cmd, stdout=subprocess.PIPE, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
        timeout=deadline + 60,
    )
    parsed = None
    for line in proc.stdout.decode(errors="replace").strip().splitlines():
        try:
            candidate = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(candidate, dict) and candidate.get("ok"):
            parsed = candidate
    if parsed is None:
        raise RuntimeError(f"hier mesh A/B child failed (rc={proc.returncode})")
    parsed.pop("ok", None)
    parsed.pop("kind", None)
    parsed["host"] = _host_provenance()
    print(
        f"# hier mesh A/B ({parsed['n_obj']} x {parsed['n_nodes']}): "
        f"mesh x chunk first-chunk {parsed['mesh_chunk']['first_chunk_ms']} ms "
        f"/ wall {parsed['mesh_chunk']['wall_s']} s vs chunked-only "
        f"first-chunk {parsed['chunked_only']['first_chunk_ms']} ms / wall "
        f"{parsed['chunked_only']['wall_s']} s; transport-cost ratio "
        f"{parsed['transport_cost']['ratio']}",
        file=sys.stderr,
    )
    return parsed


def run_collapsed_tier(n_obj: int, platform: str, deadline: float) -> None:
    """Child entry for the collapsed-rebalance (fast path) + warm tiers.

    The cheapest device tier (M x M solve + two O(N) sorts), so it runs
    FIRST among the TPU children — the headline is banked before any heavy
    dense tier can burn the relay window.
    """
    start = time.monotonic()
    init_watchdog = _arm_watchdog(deadline, EXIT_WATCHDOG)
    probe_timer = _arm_watchdog(min(PROBE_DEADLINE_S, deadline), EXIT_INIT_FAIL)
    import jax

    try:
        devices = jax.devices()
    except Exception as e:
        print(f"# backend init failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    probe_timer.cancel()
    print(f"# devices: {devices}", file=sys.stderr)
    if platform == "tpu" and devices[0].platform != "tpu":
        print(f"# expected tpu, got platform={devices[0].platform}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    init_watchdog.cancel()
    _arm_watchdog(deadline - (time.monotonic() - start), EXIT_TIER_TIMEOUT)
    try:
        # Reserve ~60 s of the deadline for the warm-assign extra below.
        tier = _collapsed_rate(
            n_obj,
            chain_budget_s=deadline - (time.monotonic() - start) - 60.0,
        )
    except Exception as e:
        print(f"# collapsed tier failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_SOLVE_FAIL)
    result = {
        "ok": True,
        "kind": "collapsed",
        "platform": platform,
        "device": str(devices[0]),
        "n_obj": n_obj,
        **tier,
    }
    print(json.dumps(result), flush=True)  # bank before the optional extras
    remaining = deadline - (time.monotonic() - start)
    if remaining > 75 + 6 * tier.get("single_shot_ms", tier["full_ms"]) / 1e3:
        try:
            result["warm_assign"] = _warm_assign_rate(
                65_536,
                chain_budget_s=deadline - (time.monotonic() - start) - 90.0,
            )
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"# warm-assign tier failed: {type(e).__name__}: {e}", file=sys.stderr)
    # BASELINE row 4 combined cycle (alloc batch + churn re-solve chained):
    # budget from the MEASURED collapsed single-shot — the cycle adds one
    # compile of comparable cost plus the alloc batch's waterfill.
    remaining = deadline - (time.monotonic() - start)
    if remaining > 90 + 12 * tier.get("single_shot_ms", tier["full_ms"]) / 1e3:
        try:
            result["incremental"] = _incremental_rate(
                n_obj,
                chain_budget_s=deadline - (time.monotonic() - start) - 30.0,
            )
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"# incremental tier failed: {type(e).__name__}: {e}", file=sys.stderr)


def _delta_churn_rate(n_obj: int, n_nodes: int = 64, mode: str = "sinkhorn") -> dict:
    """A/B one churn event's full re-solve against the incremental delta
    path on the same cluster shape (provider-level, through the public
    ``rebalance`` API): seat ``n_obj`` objects on ``n_nodes`` nodes, run
    an establishing full solve (pays every jit compile and commits the
    PlanState), kill one node -> timed ``rebalance(delta=False)`` (the
    full path), kill a second node -> timed ``rebalance()`` (the delta
    path). The two events are symmetric — each displaces ~n/n_nodes
    objects, and after a quota-exact full solve the second kill makes
    every survivor's quota grow, so the delta's displaced set is EXACTLY
    the dead node's population and undisplaced objects must not move.

    Reports wall ms and moved counts for both sides, the delta's
    ``undisplaced_moves`` (must be 0) and ``cost_ratio`` (achieved
    quadratic congestion vs the integer-quota ideal; must be ~1.0).
    """
    import asyncio

    import numpy as np

    from rio_tpu.object_placement.jax_placement import JaxObjectPlacement
    from rio_tpu.ops import integer_fair_quotas
    from rio_tpu.registry import ObjectId

    class _Member:
        def __init__(self, address: str, active: bool = True) -> None:
            self.address = address
            self.active = active

    members = [f"10.99.{i // 256}.{i % 256}:7000" for i in range(n_nodes)]

    async def _run() -> dict:
        dead_warm = n_nodes - 1
        p = JaxObjectPlacement(mode=mode, node_axis_size=n_nodes)
        p.sync_members([_Member(a) for a in members])
        ids = [ObjectId("Bench", str(i)) for i in range(n_obj)]
        await p.assign_batch(ids)
        await p.rebalance(delta=False)  # compiles paid + plan established
        # Warm-up churn event (untimed): the delta path's class-refresh
        # executable compiles on its first event, exactly like the full
        # path's compiles paid by the establishing solve above. Both timed
        # events below then measure steady-state churn reaction.
        p.sync_members(
            [_Member(a, i != dead_warm) for i, a in enumerate(members)]
        )
        await p.rebalance()

        # Event A: node 0 dies -> FULL re-solve, timed.
        p.sync_members(
            [_Member(a, i not in (dead_warm, 0)) for i, a in enumerate(members)]
        )
        t0 = time.perf_counter()
        full_moved = await p.rebalance(delta=False)
        full_ms = (time.perf_counter() - t0) * 1e3
        full_mode = p.stats.mode

        # Event B: node 1 dies -> DELTA re-solve, timed. Snapshot seats
        # first (untimed) for the undisplaced-move audit.
        pre_seats = dict(p._placements)
        p.sync_members(
            [
                _Member(a, i not in (dead_warm, 0, 1))
                for i, a in enumerate(members)
            ]
        )
        t1 = time.perf_counter()
        delta_moved = await p.rebalance()
        delta_ms = (time.perf_counter() - t1) * 1e3
        delta_mode = p.stats.mode
        displaced = p.stats.displaced

        dead_idx = p._nodes[members[1]].index
        undisplaced_moves = sum(
            1
            for k, v in pre_seats.items()
            if v != dead_idx and p._placements.get(k) != v
        )
        counts_after = np.asarray(
            [len(p._by_node.get(i, ())) for i in range(p._node_axis)],
            np.float64,
        )
        cap_alive = np.zeros((p._node_axis,), np.float64)
        for i, a in enumerate(members):
            cap_alive[p._nodes[a].index] = (
                0.0 if i in (dead_warm, 0, 1) else 1.0
            )
        quota = integer_fair_quotas(cap_alive, n_obj).astype(np.float64)
        safe = np.maximum(cap_alive, 1e-9)
        cost_ratio = float(
            np.sum(counts_after**2 / safe) / max(np.sum(quota**2 / safe), 1e-9)
        )
        return {
            "n_obj": n_obj,
            "n_nodes": n_nodes,
            "full_mode": full_mode,
            "delta_mode": delta_mode,
            "full_ms": round(full_ms, 2),
            "full_moved": int(full_moved),
            "delta_ms": round(delta_ms, 2),
            "delta_moved": int(delta_moved),
            "displaced": int(displaced),
            "undisplaced_moves": int(undisplaced_moves),
            "speedup": round(full_ms / max(delta_ms, 1e-6), 2),
            "cost_ratio": round(cost_ratio, 5),
        }

    return asyncio.run(_run())


def run_delta_tier(n_obj: int, platform: str, deadline: float) -> None:
    """Child entry for the churn-reaction A/B (full vs delta rebalance).

    Same defensive shape as every other tier child: watchdog armed before
    any jax touch, backend probed exactly once, result line printed and
    flushed the moment it exists. CPU-rehearsable:
    ``python bench.py --delta --platform cpu``.
    """
    start = time.monotonic()
    init_watchdog = _arm_watchdog(deadline, EXIT_WATCHDOG)
    probe_timer = _arm_watchdog(min(PROBE_DEADLINE_S, deadline), EXIT_INIT_FAIL)
    import jax

    try:
        devices = jax.devices()
    except Exception as e:
        print(f"# backend init failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    probe_timer.cancel()
    print(f"# devices: {devices}", file=sys.stderr)
    if platform == "tpu" and devices[0].platform != "tpu":
        print(f"# expected tpu, got platform={devices[0].platform}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    init_watchdog.cancel()
    _arm_watchdog(deadline - (time.monotonic() - start), EXIT_TIER_TIMEOUT)
    try:
        tier = _delta_churn_rate(n_obj)
    except Exception as e:
        print(f"# delta tier failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_SOLVE_FAIL)
    result = {
        "ok": True,
        "kind": "delta",
        "platform": platform,
        "device": str(devices[0]),
        **tier,
    }
    print(json.dumps(result), flush=True)


def run_tier(n_obj: int, platform: str, deadline: float) -> None:
    """Child entry: probe backend once, run one tier, print JSON result lines.

    The tier result is printed (and flushed) the moment it exists — before
    any optional extra stage — so a hang later in the child can never
    destroy an already-successful measurement; the parent takes the last
    parseable line. (Pallas validation lives in tpu_pallas_check.py: its
    Mosaic compile can hang through the tunnel, and a watchdog exit
    mid-TPU-op wedges the relay — observed r3.)
    """
    start = time.monotonic()
    init_watchdog = _arm_watchdog(deadline, EXIT_WATCHDOG)
    probe_timer = _arm_watchdog(min(PROBE_DEADLINE_S, deadline), EXIT_INIT_FAIL)

    import jax
    import jax.numpy as jnp

    try:
        devices = jax.devices()
    except Exception as e:
        print(f"# backend init failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    probe_timer.cancel()
    print(f"# devices: {devices}", file=sys.stderr)
    if platform == "tpu" and devices[0].platform != "tpu":
        # The ambient env fell back to CPU silently (e.g. sitecustomize
        # absent); never record a host run as a TPU number.
        print(f"# expected tpu, got platform={devices[0].platform}", file=sys.stderr)
        sys.exit(EXIT_INIT_FAIL)
    # Probe was healthy: a deadline from here on means "tier too big/slow",
    # not "backend wedged" — the parent may still try a smaller tier.
    init_watchdog.cancel()
    _arm_watchdog(deadline - (time.monotonic() - start), EXIT_TIER_TIMEOUT)

    kernel_dtype = jnp.bfloat16 if platform == "tpu" else jnp.float32
    try:
        # Reserve ~100 s of the deadline for the row-3 extra below.
        tier = _solve_rate(
            n_obj, kernel_dtype,
            chain_budget_s=deadline - (time.monotonic() - start) - 100.0,
        )
    except Exception as e:
        print(f"# tier {n_obj} failed: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(EXIT_SOLVE_FAIL)

    result = {
        "ok": True,
        "rate": tier["rate"],
        "n_obj": n_obj,
        "platform": platform,
        "device": str(devices[0]),
        **{k: v for k, v in tier.items() if k != "rate"},
    }
    print(json.dumps(result), flush=True)  # bank the OT result first
    remaining = deadline - (time.monotonic() - start)
    if platform == "cpu" and remaining > 30 + 3 * tier.get("single_shot_ms", tier["full_ms"]) / 1e3:
        # A CPU-only deployment runs mode="greedy" (JaxObjectPlacement's
        # mode="auto" picks it off-TPU), not the dense OT solve — record
        # its rate on the same inputs so the fallback headline reflects
        # the mode the framework actually selects on this hardware.
        try:
            result["greedy"] = _greedy_rate(n_obj)
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"# greedy tier failed: {type(e).__name__}: {e}", file=sys.stderr)
    remaining = deadline - (time.monotonic() - start)
    # BASELINE row 3 is the <50 ms-class config: 1M objects x 256 nodes on
    # one chip (a quarter of the 1k-node headline's bandwidth). Budget from
    # the MEASURED headline cost — a watchdog exit mid-TPU-op wedges the
    # relay, so a stage must never start unless it clearly fits.
    row3_budget = 90.0 + 10.0 * tier.get("single_shot_ms", tier["full_ms"]) / 1e3
    if platform == "tpu" and n_obj >= 1_048_576 and remaining > row3_budget:
        try:
            # 15 iters = 1.5x the measured convergence point for this
            # cost model (marginal err and mean_cost flat from iter 10;
            # both recorded in the tier dict as proof).
            row3 = _solve_rate(
                1_048_576, kernel_dtype, n_nodes=256, n_iters=15,
                chain_budget_s=deadline - (time.monotonic() - start) - 30.0,
            )
            result["baseline_row3_1m_x_256"] = row3
            print(f"# row-3 tier (1M x 256): {row3}", file=sys.stderr)
            print(json.dumps(result), flush=True)
        except Exception as e:
            print(f"# row-3 tier failed: {type(e).__name__}: {e}", file=sys.stderr)



# ---------------------------------------------------------------------------
# Orchestrator
# ---------------------------------------------------------------------------


def _run_child(
    n_obj: int, platform: str, deadline: float, hier: bool = False,
    collapsed: bool = False, delta: bool = False,
):
    """Run one tier child; returns (rc, parsed_json_or_None)."""
    env = os.environ.copy()
    if platform == "cpu":
        # Bypass the axon sitecustomize entirely (CLAUDE.md: works even
        # while the TPU relay is wedged by a killed claim).
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = ""
    else:
        # The orchestrator pinned itself to cpu; TPU children get the
        # platform the driver launched us with (usually "axon").
        if _TPU_PLATFORMS is not None:
            env["JAX_PLATFORMS"] = _TPU_PLATFORMS
        else:
            env.pop("JAX_PLATFORMS", None)
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--tier", str(n_obj), "--platform", platform, "--deadline", str(deadline),
    ]
    if hier:
        cmd.append("--hier")
    if collapsed:
        cmd.append("--collapsed")
    if delta:
        cmd.append("--delta")
    try:
        proc = subprocess.run(
            cmd, stdout=subprocess.PIPE, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            timeout=deadline + 60,  # backstop; the child's watchdog fires first
        )
    except subprocess.TimeoutExpired:
        print(f"# tier {n_obj}/{platform}: parent backstop timeout", file=sys.stderr)
        return EXIT_WATCHDOG, None
    # Take the last parseable result line regardless of exit code: the child
    # prints the tier result before the pallas smoke, so a smoke hang
    # (rc=EXIT_TIER_TIMEOUT) still yields a valid measurement.
    parsed = None
    for line in proc.stdout.decode(errors="replace").strip().splitlines():
        try:
            candidate = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(candidate, dict) and candidate.get("ok"):
            parsed = candidate
    return proc.returncode, parsed


def _host_provenance() -> dict:
    """Host conditions stamped onto every rpc_* stage result.

    msgs/s on this box is meaningless without knowing how many cores the
    stage actually had (cpu_count vs the cgroup/affinity mask can differ)
    and what else was running (loadavg) — the sharded A/Bs in particular
    read completely differently on 1 core vs 4.
    """
    prov: dict = {"cpu_count": os.cpu_count()}
    try:
        prov["sched_affinity"] = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        prov["sched_affinity"] = None
    try:
        prov["loadavg"] = [round(x, 2) for x in os.getloadavg()]
    except OSError:
        prov["loadavg"] = None
    return prov


def rpc_throughput(baseline: float | None = None) -> dict:
    """Actor data-plane msgs/sec per transport; also printed to stderr.

    Every msgs/s figure is ANCHORED to the sqlite baseline measured in the
    SAME session (``vs_sqlite`` ratio): the bench box's absolute throughput
    drifts ±30-40% across hours on identical code (PROFILE_RPC.md), so
    only the in-session ratio is comparable across artifacts.
    """
    import asyncio

    from rio_tpu import native
    from rio_tpu.utils.routing_live import measure_rpc_throughput

    if baseline is None:
        baseline = sqlite_baseline_rate()
    transports = ["asyncio"] + (["native"] if native.get() is not None else [])
    rates: dict = {
        "sqlite_baseline_in_session": round(baseline),
        "host": _host_provenance(),
    }
    for transport in transports:
        # 600 req/worker: long enough to amortize pool warm-up (the 400
        # default under-reads the steady state by ~25%).
        rate = asyncio.run(
            measure_rpc_throughput(transport=transport, requests_per_worker=600)
        )
        rates[transport] = round(rate)
        rates[f"{transport}_vs_sqlite"] = round(rate / baseline, 3)
        note = ""
        if transport == "native" and not native.engine_profitable():
            note = " (engine demoted: single-core host, thread handoff is pure loss)"
        print(
            f"# rpc throughput ({transport}, 2 servers, 64 workers): "
            f"{rate:,.0f} msgs/sec = {rate / baseline:.2f}x in-session "
            f"sqlite baseline{note}",
            file=sys.stderr,
        )
    return rates


def rpc_egress(baseline: float | None = None) -> dict:
    """Egress-coalescing A/B (``RIO_TPU_EGRESS_COALESCE``), paired in-session.

    The load is the standard pipelined echo shape: 64 concurrent senders
    share one client's pooled connections, so completed HEAD responses
    flush from done-callback waves on the server. Coalesced (the default)
    joins each wave into ONE buffer per connection — one write syscall in
    the asyncio transport, one engine handoff + sendmsg gather in the
    native one; per-frame is the pre-coalescing egress (one syscall per
    response). Interleaved batches, median per-batch ratio — only the
    ratio is comparable across artifacts (host absolute rates drift
    ±30-40%; PROFILE_RPC.md). The knob gates the same seam in BOTH
    transports (rio_tpu/aio.py + rio_tpu/native/transport.py), so both are
    measured when the native library is available.
    """
    import asyncio
    import statistics

    from rio_tpu import aio, native
    from rio_tpu.utils.routing_live import measure_rpc_throughput

    if baseline is None:
        baseline = sqlite_baseline_rate()
    try:
        from rio_tpu.native import transport as native_transport
    except Exception:  # pragma: no cover - native build unavailable
        native_transport = None

    def set_coalesce(enabled: bool) -> None:
        aio._EGRESS_COALESCE = enabled
        if native_transport is not None:
            native_transport._EGRESS_COALESCE = enabled

    env_default = os.environ.get("RIO_TPU_EGRESS_COALESCE", "1") != "0"
    out: dict = {
        "sqlite_baseline_in_session": round(baseline),
        "host": _host_provenance(),
    }
    transports = ["asyncio"] + (["native"] if native.get() is not None else [])
    try:
        for transport in transports:
            # 5 batches, like the batch-decode A/B: a syscall-count delta
            # is a few percent on loopback and needs the extra pairs to
            # resolve out of scheduler noise.
            per_frame, coalesced = [], []
            for _ in range(5):
                set_coalesce(False)
                per_frame.append(asyncio.run(
                    measure_rpc_throughput(
                        transport=transport, requests_per_worker=600
                    )
                ))
                set_coalesce(True)
                coalesced.append(asyncio.run(
                    measure_rpc_throughput(
                        transport=transport, requests_per_worker=600
                    )
                ))
            ratio = statistics.median(
                c / p for p, c in zip(per_frame, coalesced)
            )
            out[transport] = {
                "per_frame": [round(r) for r in per_frame],
                "coalesced": [round(r) for r in coalesced],
                "coalesced_vs_per_frame": round(ratio, 3),
                "vs_sqlite": round(coalesced[-1] / baseline, 3),
            }
            print(
                f"# rpc egress ({transport}, coalesced vs per-frame flush, "
                f"paired): {coalesced[-1]:,.0f} vs {per_frame[-1]:,.0f} "
                f"msgs/sec = {ratio:.3f}x",
                file=sys.stderr,
            )
    finally:
        set_coalesce(env_default)
    return out


def rpc_sharded(baseline: float | None = None) -> dict:
    """Sharded data-plane A/B battery (real worker processes, loopback).

    Four measurements, every pair interleaved in the SAME session (only
    ratios are comparable across artifacts; ``host`` records how many
    cores the stage actually had — the aggregate reads completely
    differently on 1 core vs 4):

    * ``sharded_vs_plain`` — 1 sharded worker (front door + identity port
      + shard router machinery) vs 1 plain server child: the price of the
      sharding envelope itself, acceptance ≥ ~0.9.
    * ``batch_decode`` — workers with the per-read batch decode on vs off
      (``RIO_TPU_BATCH_DECODE``), same topology otherwise.
    * ``n_workers`` — aggregate msgs/s through N workers, driven by
      ``--loadgen`` children (WARM/GO-coordinated concurrent windows).
    * ``shard_aware`` — same N-worker loadgen shape, clients computing
      crc32 % N locally (``Client(shard_aware=True)``) vs redirect-
      following, plus the redirect-elimination audit (shard-aware clients
      must pay ZERO redirects for unplaced traffic).
    * ``engine`` — N workers on the native transport vs asyncio (identity
      ports only: the front-door listener is asyncio's), plus the
      ``engine_profitable`` verdict the dispatch rule would apply.
    """
    import asyncio
    import shutil
    import statistics
    import tempfile

    from rio_tpu import native
    from rio_tpu.sharded import ShardedServer, sqlite_members
    from rio_tpu.utils.routing_live import measure_rpc_external

    if baseline is None:
        baseline = sqlite_baseline_rate()
    here = os.path.dirname(os.path.abspath(__file__))
    base_env = {
        "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
        "HOME": os.environ.get("HOME", "/tmp"),
        "PYTHONPATH": here,
        "JAX_PLATFORMS": "cpu",
    }
    echo = "rio_tpu.utils.routing_live:build_echo_registry"
    nodes: list = []
    tmps: list[str] = []

    def boot(workers, *, router=True, front_door=True, env=None,
             server_kwargs=None):
        tmp = tempfile.mkdtemp(prefix="rio_sharded_bench_")
        tmps.append(tmp)
        node = ShardedServer(
            address="127.0.0.1:0", workers=workers, registry=echo,
            data_dir=tmp, router=router, front_door=front_door,
            env=env, server_kwargs=server_kwargs,
        )
        node.start()
        nodes.append(node)
        asyncio.run(node.wait_ready(60.0))
        return node

    def window(node, n_workers=32, per=300, n_objects=128):
        members = sqlite_members(node.data_dir)
        try:
            return asyncio.run(
                measure_rpc_external(
                    members, n_workers=n_workers, requests_per_worker=per,
                    n_objects=n_objects,
                )
            )
        finally:
            members.close()

    def paired(node_a, node_b, batches=3):
        """Interleaved A/B windows; median per-batch ratio b/a."""
        ra, rb = [], []
        for _ in range(batches):
            ra.append(window(node_a))
            rb.append(window(node_b))
        ratio = statistics.median(b / a for a, b in zip(ra, rb))
        return [round(r) for r in ra], [round(r) for r in rb], round(ratio, 3)

    def loadgen_aggregate(node, n_gens=2, shard_aware=False, tag="lg"):
        """Concurrent measured windows from separate loadgen processes."""
        procs = []
        for g in range(n_gens):
            p = subprocess.Popen(
                [sys.executable, "-m", "rio_tpu.sharded", "--loadgen"],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                env=base_env, text=True,
            )
            spec = {
                "members": node.members_spec, "data_dir": node.data_dir,
                "n_objects": 128, "n_workers": 16,
                "requests_per_worker": 200, "prefix": f"{tag}{g}",
                "shard_aware": shard_aware,
            }
            p.stdin.write(json.dumps(spec) + "\n")
            p.stdin.flush()
            procs.append(p)
        try:
            for p in procs:  # all generators warm before any measures
                assert "WARM" in p.stdout.readline()
            for p in procs:  # GO
                p.stdin.write("\n")
                p.stdin.flush()
            gens = []
            for p in procs:
                for line in p.stdout:
                    if line.startswith("RESULT "):
                        gens.append(json.loads(line[len("RESULT "):]))
                        break
                p.wait(timeout=60)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        return {
            "aggregate_rate": round(sum(g["rate"] for g in gens)),
            "redirects": sum(g.get("redirects", 0) for g in gens),
            "shard_routes": sum(g.get("shard_routes", 0) for g in gens),
            "generators": gens,
        }

    out: dict = {
        "sqlite_baseline_in_session": round(baseline),
        "host": _host_provenance(),
        "engine_profitable": native.engine_profitable(),
    }
    try:
        n = max(2, min(4, os.cpu_count() or 1))
        plain = boot(1, router=False, front_door=False)
        sharded1 = boot(1)
        pr, sr, ratio = paired(plain, sharded1)
        out["one_worker"] = {
            "plain_1proc": pr, "sharded_1worker": sr,
            "sharded_vs_plain": ratio,
            "vs_sqlite": round(sr[-1] / baseline, 3),
        }
        print(
            f"# rpc sharded (1 worker vs plain child, paired): "
            f"{sr[-1]:,.0f} vs {pr[-1]:,.0f} msgs/sec = {ratio:.3f}x",
            file=sys.stderr,
        )

        decode_off = boot(
            1, env={**base_env, "RIO_TPU_BATCH_DECODE": "0"}
        )
        # 5 batches: the decode delta is ~1% on one core, inside 3-batch
        # noise (a 7-batch calibration run read median 1.009, range
        # 0.97-1.03 — the win needs the extra pairs to resolve).
        offr, onr, on_vs_off = paired(decode_off, sharded1, batches=5)
        out["batch_decode"] = {
            "off": offr, "on": onr, "on_vs_off": on_vs_off,
        }
        print(
            f"# rpc sharded (batch decode on vs off, paired): "
            f"{onr[-1]:,.0f} vs {offr[-1]:,.0f} msgs/sec = {on_vs_off:.3f}x",
            file=sys.stderr,
        )

        node_n = boot(n)
        agg = loadgen_aggregate(node_n)
        agg["n_workers"] = n
        agg["vs_sqlite"] = round(agg["aggregate_rate"] / baseline, 3)
        out["n_workers"] = agg
        print(
            f"# rpc sharded ({n} workers, {len(agg['generators'])} loadgen "
            f"procs): {agg['aggregate_rate']:,.0f} msgs/sec aggregate "
            f"({agg['vs_sqlite']:.2f}x in-session sqlite baseline)",
            file=sys.stderr,
        )

        # Shard-aware front door A/B: identical topology and loadgen
        # shape, the only variable being Client(shard_aware=) — crc32 % N
        # computed client-side with direct identity dials vs the reference
        # redirect-follow policy. Fresh object prefixes per batch keep the
        # traffic genuinely unplaced, so the redirect audit measures the
        # claim exactly: shard-aware clients pay ZERO redirects for
        # unplaced traffic while redirect-routed clients pay one per
        # mis-picked first touch.
        rr_rates, sa_rates = [], []
        rr_redirects = sa_redirects = sa_routes = 0
        for b in range(3):
            a = loadgen_aggregate(node_n, shard_aware=False, tag=f"rd{b}g")
            s = loadgen_aggregate(node_n, shard_aware=True, tag=f"sa{b}g")
            rr_rates.append(a["aggregate_rate"])
            sa_rates.append(s["aggregate_rate"])
            rr_redirects += a["redirects"]
            sa_redirects += s["redirects"]
            sa_routes += s["shard_routes"]
        sa_ratio = statistics.median(
            s / a for a, s in zip(rr_rates, sa_rates)
        )
        out["shard_aware"] = {
            "n_workers": n,
            "redirect_routed": rr_rates,
            "shard_aware": sa_rates,
            "shard_aware_vs_redirect": round(sa_ratio, 3),
            "redirects": {
                "redirect_routed": rr_redirects, "shard_aware": sa_redirects,
            },
            "shard_routes": sa_routes,
        }
        print(
            f"# rpc sharded ({n} workers, shard-aware vs redirect-routed "
            f"clients, paired): {sa_rates[-1]:,.0f} vs {rr_rates[-1]:,.0f} "
            f"msgs/sec aggregate = {sa_ratio:.3f}x; redirects "
            f"{sa_redirects} vs {rr_redirects}, {sa_routes} direct shard "
            f"dials",
            file=sys.stderr,
        )

        # Native-engine A/B, identity ports only: the front-door socket is
        # the asyncio transport's (the native engine owns its one
        # listener). On a <2-core host engine_profitable() already says
        # the handoff is pure loss — the measurement shows it anyway.
        if native.get() is not None:
            try:
                node_async = boot(n, front_door=False)
                node_native = boot(
                    n, front_door=False,
                    server_kwargs={"transport": "native"},
                )
                ar, nr, native_vs = paired(node_async, node_native)
                out["engine"] = {
                    "asyncio": ar, "native": nr,
                    "native_vs_asyncio": native_vs,
                }
                print(
                    f"# rpc sharded ({n} workers, native vs asyncio "
                    f"transport, paired): {nr[-1]:,.0f} vs {ar[-1]:,.0f} "
                    f"msgs/sec = {native_vs:.3f}x (engine_profitable="
                    f"{out['engine_profitable']})",
                    file=sys.stderr,
                )
            except Exception as e:
                out["engine"] = {"error": repr(e)}
                print(f"# rpc sharded engine A/B failed: {e!r}", file=sys.stderr)
    finally:
        for node in nodes:
            try:
                node.stop()
            except Exception:
                pass
        for tmp in tmps:
            shutil.rmtree(tmp, ignore_errors=True)
    return out


def migration_drain() -> dict:
    """Migrations/sec + mean pinned-window ms for a 1k-object drain,
    batched+prefetch vs per-key actuation, measured in the SAME session
    (the speedup ratio is the stable artifact; absolute rates drift with
    the box like every host-stage number)."""
    import asyncio

    from rio_tpu.utils.migration_live import measure_migration_drain

    out = asyncio.run(measure_migration_drain())
    pk, bt = out["per_key"], out["batched"]
    print(
        f"# migration drain ({out['n_objects']} objects x "
        f"{out['payload_bytes']} B volatile state, 2 servers): "
        f"batched+prefetch {bt['migrations_per_sec']:,.0f}/s "
        f"(pinned mean {bt['pinned_ms_mean']} ms, {bt['bursts']} bursts, "
        f"{bt['prefetch_hits']} prefetch hits) vs per-key "
        f"{pk['migrations_per_sec']:,.0f}/s "
        f"(pinned mean {pk['pinned_ms_mean']} ms) = "
        f"{out.get('speedup', 0):.2f}x, pinned-window ratio "
        f"{out.get('pinned_window_ratio', 0):.3f}",
        file=sys.stderr,
    )
    return out


def hotkey_scaleout() -> dict:
    """Hot-key read p99, replica reads vs read-through-primary, under the
    SAME seeded zipf open-loop stream (one celebrity key = 30% of traffic)
    in the SAME session — the hot_p99_ratio is the stable artifact;
    absolute latencies drift with the box like every host-stage number."""
    import asyncio

    from rio_tpu.utils.hotkey_live import measure_hotkey

    out = asyncio.run(measure_hotkey())
    base, rep = out["baseline"], out["replica_reads"]
    print(
        f"# hot-key read scale-out ({out['n_requests']} reqs @ "
        f"{out['rate_per_sec']:,.0f}/s open loop, hot key "
        f"{out['hot_fraction']:.0%} of stream, {out['work_ms']:.0f} ms/read, "
        f"3 servers): replica reads hot p99 {rep['hot_p99_ms']:,.1f} ms "
        f"({rep.get('standby_reads', 0)} standby reads, "
        f"{rep.get('read_sheds', 0)} sheds, "
        f"{rep.get('stale_refusals', 0)} stale refusals) vs "
        f"read-through-primary {base['hot_p99_ms']:,.1f} ms = "
        f"{out.get('hot_p99_ratio', 0):.3f}x",
        file=sys.stderr,
    )
    return out


def tracing_overhead() -> dict:
    """RPC-loop cost of the observability layer, A/B/C'd in the SAME
    session: spans disabled (pre-observability hot path) vs the
    shipping default (histogram record only, sampling 0) vs everything on
    (sample rate 1.0 + live sink). The overhead percentages are the stable
    artifact; absolute msgs/sec drift with the box like every host-stage
    number."""
    import asyncio

    from rio_tpu.utils.tracing_live import measure_tracing_overhead

    out = asyncio.run(measure_tracing_overhead())
    m = out["msgs_per_sec"]
    print(
        f"# tracing overhead ({out['batches']} interleaved batches x "
        f"{out['n_requests_per_batch']} reqs, 2 servers/mode, median "
        f"paired ratio): disabled {m['disabled']:,.0f}/s, record-only "
        f"{m['record']:,.0f}/s ({out['record_overhead_pct']:+}%), "
        f"sampled@1.0+sink {m['sampled']:,.0f}/s "
        f"({out['sampled_overhead_pct']:+}%)",
        file=sys.stderr,
    )
    return out


def journal_overhead() -> dict:
    """RPC-loop cost of the control-plane flight recorder, A/B'd in the
    SAME session: servers with journal=False vs the shipping default
    (journal on, capacity 4096). Events record on control transitions
    only, so the echo loop should price the journal at ~0; the ISSUE 9
    acceptance bar is ≤ ~2%. Median paired ratio is the stable artifact."""
    import asyncio

    from rio_tpu.utils.journal_live import measure_journal_overhead

    out = asyncio.run(measure_journal_overhead())
    m = out["msgs_per_sec"]
    print(
        f"# journal overhead ({out['batches']} interleaved batches x "
        f"{out['n_requests_per_batch']} reqs, 2 servers/mode, median "
        f"paired ratio): off {m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({out['journal_overhead_pct']:+}%, "
        f"{out['events_recorded_on']} control events recorded)",
        file=sys.stderr,
    )
    return out


def faults_overhead() -> dict:
    """Disabled-overhead parity of the fault-injection layer, A/B'd in the
    SAME session: bare storage backends vs the same backends wrapped in
    Faulty* wrappers around a DISABLED schedule (passthrough swap active —
    the inner bound methods serve directly, so the per-request directory
    lookup pays nothing). The trait-lookup ladder also prices armed-idle
    delegation (what a soak pays while no fault fires). Median paired
    ratio is the stable artifact."""
    import asyncio

    from rio_tpu.utils.faults_live import measure_faults_overhead

    out = asyncio.run(measure_faults_overhead())
    out["host"] = _host_provenance()
    m = out["msgs_per_sec"]
    lk = out["lookup_ops_per_sec"]
    print(
        f"# faults overhead ({out['batches']} interleaved batches x "
        f"{out['n_requests_per_batch']} reqs, 2 servers/mode, median "
        f"paired ratio): off {m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({out['faults_overhead_pct']:+}%); trait lookup bare "
        f"{lk['bare']:,.0f}/s, disabled {lk['disabled']:,.0f}/s "
        f"({out['lookup_overhead_disabled_pct']:+}%), armed-idle "
        f"{lk['armed_idle']:,.0f}/s "
        f"({out['lookup_overhead_armed_idle_pct']:+}%)",
        file=sys.stderr,
    )
    return out


def autoscale_stage() -> dict:
    """Elastic autoscaling evidence, two halves in one stage. (1) Idle
    cost: the RPC loop A/B'd with autoscaling absent vs armed-but-pinned
    (min_nodes == max_nodes — the controller ticks, aggregates gauges and
    evaluates trend rules but can never act); disabled is additionally
    asserted structurally free (``server.autoscale is None``). (2) The
    ramp soak: offered load ~10x up and back down against a supervisor
    with a SubprocessProvisioner, under storage blips plus a real SIGKILL
    mid-scale-in drain — zero lost acked writes, bounded p99, node count
    tracking load, and the journal's alarm → SCALE → drain → retire chain
    are all asserted inside the measurement (a violated bar raises, so a
    banked number IS a passed soak)."""
    import asyncio

    from rio_tpu.utils.autoscale_live import (
        measure_autoscale_idle_overhead,
        measure_autoscale_ramp,
    )

    out: dict = {"idle": asyncio.run(measure_autoscale_idle_overhead())}
    out["ramp"] = asyncio.run(measure_autoscale_ramp())
    out["host"] = _host_provenance()
    idle, ramp = out["idle"], out["ramp"]
    m = idle["msgs_per_sec"]
    print(
        f"# autoscale idle overhead ({idle['batches']} interleaved batches "
        f"x {idle['n_requests_per_batch']} reqs, median paired ratio): off "
        f"{m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({idle['autoscale_overhead_pct']:+}%, {idle['controller_ticks_on']} "
        f"controller ticks); ramp soak {ramp['seconds']:.0f}s: "
        f"{ramp['scale_outs']} out / {ramp['scale_ins']} in, "
        f"{ramp['acked_writes']} acked writes lost={ramp['lost']} "
        f"(dups {ramp['duplicates']}), p99 {ramp['p99_ms']:.0f} ms, "
        f"SIGKILL mid-drain {ramp['killed_mid_drain'] or 'NONE'}, "
        f"{ramp['storage_blips']} storage blips",
        file=sys.stderr,
    )
    return out


def streams_throughput() -> dict:
    """Durable-stream data-path rates, A/B'd in the SAME session: the
    redelivery backstop idle (no reminders — delivery rides the publish
    wake alone) vs ticking at 0.05 s per partition (40x the shipping 2 s
    cadence). Acked-publish rate is the producer-facing durability cost;
    the end-to-end rate covers publish → delivered-then-committed; the
    median paired ratio prices the at-least-once backstop. Both modes
    must deliver every acked publish (zero-loss rides along)."""
    import asyncio

    from rio_tpu.utils.streams_live import measure_streams_overhead

    out = asyncio.run(measure_streams_overhead())
    out["host"] = _host_provenance()
    pub, e2e = out["publish_acks_per_sec"], out["deliver_msgs_per_sec"]
    print(
        f"# streams throughput ({out['batches']} interleaved batches x "
        f"{out['publishes_per_batch']} publishes, 2 servers/mode, "
        f"{out['partitions_active']['on']} partitions, median paired "
        f"ratio): publish acks off {pub['off']:,.0f}/s, on "
        f"{pub['on']:,.0f}/s; e2e deliver off {e2e['off']:,.0f}/s, on "
        f"{e2e['on']:,.0f}/s ({out['redelivery_overhead_pct']:+}% "
        f"redelivery backstop); zero loss both modes "
        f"({out['delivered']['on']} delivered)",
        file=sys.stderr,
    )
    return out


def qos_stage() -> dict:
    """Both QoS promises priced in the SAME session (ISSUE 20): the
    uniform half A/Bs the RPC loop with the scheduler off vs the default
    ``QosConfig`` under identical unclassified echo traffic (median
    paired ratio; bar <= ~2%), and the flood half A/Bs interactive p99
    while a bulk tenant floods one hot object (per-object serialized
    execution is the contention; bars: >= 3x better with QoS on, zero
    interactive sheds)."""
    import asyncio

    from rio_tpu.utils.qos_live import measure_qos

    out = asyncio.run(measure_qos())
    out["host"] = _host_provenance()
    u, f = out["uniform"], out["flood"]
    m = u["msgs_per_sec"]
    print(
        f"# qos ({u['batches']} interleaved batches x "
        f"{u['n_requests_per_batch']} echoes, 2 servers/mode): uniform "
        f"off {m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({u['qos_overhead_pct']:+}% median paired); flood "
        f"({f['bulk_workers']} bulk workers on one hot object, "
        f"max_concurrent {f['max_concurrent_on']}): interactive p99 "
        f"off {f['off']['interactive_p99_ms']} ms -> on "
        f"{f['on']['interactive_p99_ms']} ms "
        f"({f['interactive_p99_improvement']}x), "
        f"{f['interactive_sheds_on']} interactive sheds",
        file=sys.stderr,
    )
    return out


def affinity_payoff() -> dict:
    """Affinity-aware placement payoff + sampler cost, A/B'd in the SAME
    session. Payoff: an adversarial multi-hop pipeline (producer + stream
    cursors seated on node 0, consumers on node 1) runs affinity-blind,
    then the merged edge graph is fed back through ``set_edge_graph`` +
    ``rebalance`` and the same traffic re-runs — the honest numerator is
    the transports' TCP byte counters, and the ISSUE 17 bar is a >= 2x
    drop plus formerly cross-node delivery hops vanishing from the wire
    span rings. Cost: the dispatch-path sampler priced off-vs-on over an
    affinity-neutral echo cluster, median paired ratio (bar: <= ~2%)."""
    import asyncio

    from rio_tpu.utils.affinity_live import (
        measure_affinity_payoff,
        measure_sampler_overhead,
    )

    out = asyncio.run(measure_affinity_payoff())
    out["sampler"] = asyncio.run(measure_sampler_overhead())
    out["host"] = _host_provenance()
    tcp, spans = out["tcp_bytes"], out["delivery_wire_spans"]
    m = out["sampler"]["msgs_per_sec"]
    print(
        f"# affinity payoff ({out['n_records']} records x "
        f"{out['pad_bytes']}B over {out['partitions']} partitions, "
        f"{out['edges_installed']} edges fed back, {out['moves']} moves, "
        f"solved as {out['solved_as']}): TCP bytes blind "
        f"{tcp['blind']:,} -> affinity {tcp['affinity']:,} "
        f"({out['bytes_ratio']:.1f}x), cross-node delivery wire spans "
        f"{spans['blind']} -> {spans['affinity']}, "
        f"{out['pairs_colocated']}/{out['partitions']} pairs co-located; "
        f"sampler off {m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({out['sampler']['sampler_overhead_pct']:+}% median paired)",
        file=sys.stderr,
    )
    return out


def series_overhead() -> dict:
    """RPC-loop cost of gauge time-series sampling + HealthWatch, A/B'd in
    the SAME session: servers with timeseries=False vs sampling at an
    aggressive 0.05 s cadence (20x the shipping 1 s default). The ISSUE 11
    acceptance bar is ≤ ~1% steady-state; median paired ratio is the
    stable artifact, stamped with host provenance like every host stage."""
    import asyncio

    from rio_tpu.utils.series_live import measure_series_overhead

    out = asyncio.run(measure_series_overhead())
    out["host"] = _host_provenance()
    m = out["msgs_per_sec"]
    print(
        f"# series overhead ({out['batches']} interleaved batches x "
        f"{out['n_requests_per_batch']} reqs, 2 servers/mode, sampling @"
        f"{out['sample_interval_s']}s, median paired ratio): off "
        f"{m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({out['series_overhead_pct']:+}%, {out['samples_on']} samples, "
        f"{out['health_alerts_fired_on']} alerts fired)",
        file=sys.stderr,
    )
    return out


def spans_overhead() -> dict:
    """RPC-loop cost of request-waterfall span retention, A/B'd in the
    SAME session: servers with spans=False vs retention on with head
    sampling off and tail capture armed at a 1 ms SLO (250x tighter than
    the shipping default). The ISSUE 14 acceptance bar is ≤ ~2% at the
    request level; median paired ratio is the stable artifact, stamped
    with host provenance like every host stage."""
    import asyncio

    from rio_tpu.utils.spans_live import measure_spans_overhead

    out = asyncio.run(measure_spans_overhead())
    out["host"] = _host_provenance()
    m = out["msgs_per_sec"]
    print(
        f"# spans overhead ({out['batches']} interleaved batches x "
        f"{out['n_requests_per_batch']} reqs, 2 servers/mode, tail SLO "
        f"{out['slo_ms']}ms, median paired ratio): off "
        f"{m['off']:,.0f}/s, on {m['on']:,.0f}/s "
        f"({out['spans_overhead_pct']:+}%, {out['retained_on']} retained, "
        f"{out['tail_captured_on']} tail-captured)",
        file=sys.stderr,
    )
    return out


_TPU_PLATFORMS = os.environ.get("JAX_PLATFORMS")  # as the driver launched us


def _detail_platform(detail: dict) -> str:
    """"tpu" if any tier in this run executed on hardware, else "cpu"."""
    for v in detail.values():
        if isinstance(v, dict) and v.get("platform") == "tpu":
            return "tpu"
    return "cpu"


def _sync_contaminated_paths(node, prefix: str = "") -> list[str]:
    """Dotted paths of every relay-sync-contaminated field in a detail tree."""
    paths: list[str] = []
    if isinstance(node, dict):
        for key, val in node.items():
            dotted = f"{prefix}.{key}" if prefix else key
            if key in _SYNC_CONTAMINATED_FIELDS and isinstance(val, (int, float)):
                paths.append(dotted)
            else:
                paths.extend(_sync_contaminated_paths(val, dotted))
    return paths


def _relay_health(out: dict) -> dict:
    """Relay-condition annotation for a banked tpu capture.

    The relay DEGRADES before it dies (r4: pull_ms 349→747; r5 session 2:
    212→1119 then a mid-compile watchdog exit re-wedged it), so the banked
    evidence records the pull latencies the run itself observed and an
    explicit trend verdict — a later reader must be able to tell "healthy
    window" from "numbers captured while the relay was collapsing" without
    re-deriving it from raw tier fields. Only THIS run's samples feed the
    verdict: carried tiers' latencies describe a prior session's window.
    """
    health: dict = {
        "pull_ceiling_ms": HIER_PULL_MAX_MS,
        # Banked for forensics, poison for perf analysis: these fields
        # time the tunnel's dispatch+sync, not device compute.
        "sync_contaminated": sorted(
            p
            for tier in _CARRYABLE_TIERS
            for p in _sync_contaminated_paths(out.get(tier), tier)
        ),
    }
    samples: list[tuple[str, float]] = []
    collapsed = out.get("collapsed_tier")
    if (
        isinstance(collapsed, dict)
        and "collapsed_tier_carried" not in out
        and isinstance(collapsed.get("pull_ms"), (int, float))
    ):
        # The run's FIRST device-tier pull (the collapsed tier runs before
        # every other TPU child).
        health["first_pull_ms"] = collapsed["pull_ms"]
        samples.append(("collapsed_tier.pull_ms", float(collapsed["pull_ms"])))
    hier = out.get("baseline_row5_hier")
    if (
        isinstance(hier, dict)
        and "baseline_row5_hier_carried" not in out
        and isinstance(hier.get("preflight_pull_ms"), (int, float))
    ):
        # min-of-3 warm 4 MB pull, fresh device array per sample (a re-pull
        # of the same array measures a host-cache lookup, not the relay).
        health["hier_preflight_min3_ms"] = hier["preflight_pull_ms"]
        samples.append(
            ("baseline_row5_hier.preflight_pull_ms",
             float(hier["preflight_pull_ms"]))
        )
    if not samples:
        health["trend"] = "unknown"
        health["note"] = "no fresh pull samples this run (tiers carried/absent)"
    elif len(samples) == 1:
        _, v = samples[0]
        health["trend"] = "degraded" if v > HIER_PULL_MAX_MS else "single-sample"
    else:
        first, last = samples[0][1], samples[-1][1]
        if last > HIER_PULL_MAX_MS or last > 2.0 * first:
            health["trend"] = "degrading"
            health["note"] = (
                "pull latency rose in-run — treat as 'stop launching TPU "
                "children' (r4/r5 wedge precursor)"
            )
        else:
            health["trend"] = "stable"
    return health


def _write_detail(detail: dict, here: str | None = None) -> None:
    """Bank the sidecar clobber-proof.

    Hardware evidence is scarce (the relay can wedge for a whole round) so a
    CPU fallback run must never destroy a TPU capture: every run writes its
    own per-platform file ``BENCH_DETAIL.{tpu,cpu}.json``, and the legacy
    ``BENCH_DETAIL.json`` is only touched when this run has hardware numbers
    or the existing file doesn't (r4 lost its working-tree TPU capture to
    exactly this overwrite).
    """
    if here is None:
        here = os.path.dirname(os.path.abspath(__file__))
    plat = _detail_platform(detail)
    targets = [os.path.join(here, f"BENCH_DETAIL.{plat}.json")]
    legacy = os.path.join(here, "BENCH_DETAIL.json")
    out = detail
    if plat == "tpu":
        # A tier this run SKIPPED (e.g. the hier ladder behind its
        # relay-health gate) must not erase the banked capture from a
        # healthier window: carry forward any top-level tpu-run key the
        # new detail lacks, marked with its provenance. Merge on a COPY —
        # the caller's dict keeps only this run's numbers, so the later
        # end-of-run write re-derives what is still missing (a host stage
        # that has since produced a fresh value sheds the stale marker).
        # The tpu sidecar is the primary carry source; the legacy file is
        # the fallback (a crash mid-sidecar-write must not cost the last
        # banked copy — this run overwrites BOTH targets below).
        prior = None
        for cand in (targets[0], legacy):
            try:
                with open(cand) as fh:
                    parsed = json.load(fh)
            except (OSError, ValueError):
                continue
            if isinstance(parsed, dict) and _detail_platform(parsed) == "tpu":
                prior = parsed
                break
        out = dict(detail)  # annotations below must not leak into the caller
        if prior is not None:
            for key, val in prior.items():
                if key not in _CARRYABLE_TIERS or val is None:
                    # Only device tiers carry: host-stage numbers (rpc,
                    # routing, live clusters) are only comparable against
                    # the same session's sqlite baseline, so pairing a
                    # prior session's host numbers with this run's
                    # baseline would fabricate a ratio no session measured.
                    continue
                cur = out.get(key)
                # None counts as missing: a tier that ran but failed (e.g.
                # solve_tier = None when every dense child exits) must not
                # clobber the banked capture either.
                if cur is None:
                    out[key] = val
                    out[f"{key}_carried"] = "prior tpu capture"
                elif (
                    isinstance(val, dict)
                    and val.get("platform") == "tpu"
                    and isinstance(cur, dict)
                    and cur.get("platform") not in (None, "tpu")
                ):
                    # A cpu-fallback tier in an otherwise-tpu run (dense
                    # children failed, 131k cpu tier filled in) must not
                    # displace banked hardware numbers in the tpu file;
                    # keep the fresh fallback under its own key.
                    out[f"{key}_cpu_fallback"] = cur
                    out[key] = val
                    out[f"{key}_carried"] = "prior tpu capture"
        out["relay_health"] = _relay_health(out)
        targets.append(legacy)
    else:
        try:
            with open(legacy) as fh:
                existing = json.load(fh)
            existing_is_tpu = (
                isinstance(existing, dict) and _detail_platform(existing) == "tpu"
            )
        except (OSError, ValueError):
            existing_is_tpu = False
        if not existing_is_tpu:
            targets.append(legacy)
        else:
            print(
                "# BENCH_DETAIL.json holds a TPU capture; cpu run banked to "
                "BENCH_DETAIL.cpu.json only",
                file=sys.stderr,
            )
    for path in targets:
        try:
            with open(path, "w") as fh:
                json.dump(out, fh, indent=1)
        except OSError as e:  # never let the sidecar kill the headline line
            print(f"# {os.path.basename(path)} write failed: {e}", file=sys.stderr)


def _tpu_banked_block(here: str | None = None) -> dict | None:
    """The banked hardware headline, for embedding in a CPU-fallback line.

    A fallback run's final JSON used to be indistinguishable from a
    hardware run to a scorer that only reads the last line; this block
    makes the banked TPU evidence ride along explicitly — rate and
    vs_baseline come from the CAPTURE's own session (its sqlite baseline,
    never this run's: pairing a prior session's device rate with a fresh
    baseline would fabricate a ratio no session measured), stamped with
    when and under what relay conditions it was taken.
    """
    if here is None:
        here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "BENCH_DETAIL.tpu.json")
    try:
        with open(path) as fh:
            banked = json.load(fh)
        mtime = os.path.getmtime(path)
    except (OSError, ValueError):
        return None
    if not isinstance(banked, dict) or _detail_platform(banked) != "tpu":
        return None
    collapsed = banked.get("collapsed_tier")
    if not isinstance(collapsed, dict) or collapsed.get("platform") != "tpu":
        return None
    block: dict = {
        "rate": round(float(collapsed["rate"]), 1),
        "captured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime(mtime)
        ),
        "provenance": (
            "banked tpu capture (BENCH_DETAIL.tpu.json); this run's "
            "headline value is a cpu fallback — do not score it as hardware"
        ),
    }
    baseline = banked.get("sqlite_baseline_rate")
    if isinstance(baseline, (int, float)) and baseline > 0:
        block["vs_baseline"] = round(float(collapsed["rate"]) / baseline, 2)
    health = banked.get("relay_health")
    if isinstance(health, dict):
        block["relay"] = health.get("trend", "unknown")
    else:
        block["relay"] = "unknown"
    return block


def _pin_orchestrator_to_cpu() -> None:
    """The orchestrator must NEVER touch the TPU backend itself.

    The live-cluster stages (rpc, routing, row-2) run real servers with a
    JaxObjectPlacement in THIS process; their first solve initializes the
    jax backend, and with the ambient ``JAX_PLATFORMS=axon`` a wedged
    relay hangs that init indefinitely with no watchdog (observed r3: the
    whole bench froze before printing anything). The shared helper pins
    cpu AND deregisters the axon PJRT factory; TPU tiers run in child
    processes that restore the original platform env.
    """
    from rio_tpu.utils.jaxenv import force_cpu

    force_cpu()


def main() -> None:
    _pin_orchestrator_to_cpu()
    detail: dict = {}
    baseline = sqlite_baseline_rate()  # ~2 s; needed for every ratio below
    detail["sqlite_baseline_rate"] = round(baseline)

    result = None
    collapsed = None
    tpu_down = False
    # TPU FIRST (r5): a healthy relay window is the scarcest resource in
    # the whole bench — it can degrade to a wedge in minutes (r4) — so
    # every device tier runs before the ~10 min of host-side stages (rpc,
    # routing, live clusters), not after. Within the device tiers, the
    # collapsed-rebalance tier is the HEADLINE (the directory's committed
    # fast path, BASELINE row 3's <50 ms class) and the cheapest — it goes
    # first so it is banked before the heavy dense tiers.
    rc, collapsed = _run_child(1_048_576, "tpu", 480.0, collapsed=True)
    if collapsed:
        detail["collapsed_tier"] = collapsed
        print(f"# collapsed rebalance tier: {collapsed}", file=sys.stderr)
    elif rc in (EXIT_INIT_FAIL, EXIT_WATCHDOG):
        tpu_down = True
        print("# TPU backend unavailable; falling back to CPU", file=sys.stderr)
    # Dense OT tiers, largest first. An init failure or watchdog exit means
    # the tunnel is down/wedged — retrying would burn ~25 min per attempt in
    # backend setup (the round-1 failure mode), so abort TPU entirely.
    if not tpu_down:
        for n_obj, deadline in ((1_048_576, 560.0), (524_288, 360.0), (262_144, 240.0)):
            rc, parsed = _run_child(n_obj, "tpu", deadline)
            if parsed:
                result = parsed
                break
            if rc in (EXIT_INIT_FAIL, EXIT_WATCHDOG):
                print("# TPU backend unavailable; falling back to CPU", file=sys.stderr)
                break
            # EXIT_SOLVE_FAIL (OOM) or EXIT_TIER_TIMEOUT (healthy probe, tier
            # too slow): a smaller tier may still fit the deadline.
            print(f"# tier {n_obj} rc={rc}; trying smaller tier", file=sys.stderr)
    # Churn-reaction A/B (full vs delta rebalance at 1M x 64): TPU
    # opportunistic — the acceptance numbers are CPU's, so a relay hiccup
    # here costs nothing banked.
    delta_tier = None
    if not tpu_down:
        rc, delta_tier = _run_child(1_048_576, "tpu", 480.0, delta=True)
        if delta_tier:
            detail["delta_tier"] = delta_tier
            print(f"# delta churn tier: {delta_tier}", file=sys.stderr)
        elif rc in (EXIT_INIT_FAIL, EXIT_WATCHDOG):
            tpu_down = True
            print("# TPU backend unavailable; falling back to CPU", file=sys.stderr)
    if result is not None and result.get("platform") == "tpu":
        # BASELINE row 5 (scale ceiling): hierarchical 2-level OT toward
        # 10M x 1k, in its OWN child so an overrun can't cost the banked
        # headline result; the child sizes itself adaptively. Relay-health
        # gating lives in the CHILD's min-of-3 pull pre-flight (a clean
        # exit BEFORE its first big compile): a 700 s budget the ladder
        # fit comfortably in a healthy window (total ~350 s) blows up
        # INSIDE a compile when the relay degrades, and that mid-compile
        # watchdog exit is what wedges the relay (r5 session 2). Main
        # deliberately has no pull_ms gate of its own — a single sample
        # overlaps the healthy range (170-970 ms) and would spuriously
        # skip; child init against a degraded relay is safe (init-time
        # watchdog exits never wedged, 38 observed). RIO_TPU_BENCH_HIER=1
        # forces past the pre-flight, =0 skips the child entirely.
        if os.environ.get("RIO_TPU_BENCH_HIER") == "0":
            print("# hier tier skipped (RIO_TPU_BENCH_HIER=0)", file=sys.stderr)
        else:
            rc, hier = _run_child(10_485_760, "tpu", 700.0, hier=True)
            if hier:
                detail["baseline_row5_hier"] = hier
                print(f"# row-5 hier tier: {hier}", file=sys.stderr)
            elif rc == EXIT_TIER_TIMEOUT:
                print(
                    "# hier tier skipped by child pre-flight (measured "
                    "slow, exited cleanly); banked evidence stands",
                    file=sys.stderr,
                )
            elif rc == EXIT_PREFLIGHT_HANG:
                print(
                    "# hier pre-flight pull HUNG (watchdog exit, not a "
                    "clean skip) — treat the relay as wedged; do not "
                    "launch further TPU children this round",
                    file=sys.stderr,
                )
    # Device tiers are done — bank them NOW, before the host-side stages
    # (a crash in a live-cluster stage must not cost banked TPU evidence).
    detail["solve_tier"] = result
    if collapsed is not None or result is not None:
        _write_detail(detail)

    # Host-side stages (in-process live clusters; the orchestrator is
    # CPU-pinned so none of these can touch the relay).
    try:
        detail["rpc_msgs_per_sec"] = rpc_throughput(baseline)
    except Exception as e:
        print(f"# rpc throughput failed: {e!r}", file=sys.stderr)
    try:
        detail["rpc_egress"] = rpc_egress(baseline)
    except Exception as e:
        print(f"# rpc egress failed: {e!r}", file=sys.stderr)
    try:
        detail["rpc_sharded"] = rpc_sharded(baseline)
    except Exception as e:
        print(f"# rpc sharded failed: {e!r}", file=sys.stderr)
    try:
        detail["migration_drain"] = migration_drain()
    except Exception as e:
        print(f"# migration drain failed: {e!r}", file=sys.stderr)
    try:
        detail["hotkey"] = hotkey_scaleout()
    except Exception as e:
        print(f"# hot-key scale-out failed: {e!r}", file=sys.stderr)
    try:
        detail["tracing"] = tracing_overhead()
    except Exception as e:
        print(f"# tracing overhead failed: {e!r}", file=sys.stderr)
    try:
        detail["journal"] = journal_overhead()
    except Exception as e:
        print(f"# journal overhead failed: {e!r}", file=sys.stderr)
    try:
        detail["series"] = series_overhead()
    except Exception as e:
        print(f"# series overhead failed: {e!r}", file=sys.stderr)
    try:
        detail["spans"] = spans_overhead()
    except Exception as e:
        print(f"# spans overhead failed: {e!r}", file=sys.stderr)
    try:
        detail["faults"] = faults_overhead()
    except Exception as e:
        print(f"# faults overhead failed: {e!r}", file=sys.stderr)
    try:
        detail["streams"] = streams_throughput()
    except Exception as e:
        print(f"# streams throughput failed: {e!r}", file=sys.stderr)
    try:
        detail["autoscale"] = autoscale_stage()
    except Exception as e:
        print(f"# autoscale stage failed: {e!r}", file=sys.stderr)
    try:
        detail["affinity"] = affinity_payoff()
    except Exception as e:
        print(f"# affinity payoff failed: {e!r}", file=sys.stderr)
    try:
        detail["qos"] = qos_stage()
    except Exception as e:
        print(f"# qos stage failed: {e!r}", file=sys.stderr)
    try:
        detail["hier_mesh_ab"] = hier_mesh_ab()
    except Exception as e:
        print(f"# hier mesh A/B failed: {e!r}", file=sys.stderr)
    try:
        detail["scaled_routing"] = scaled_route_hops()
    except Exception as e:
        print(f"# scaled routing failed: {e!r}", file=sys.stderr)
    try:
        detail["row2_jax_provider"] = row2_jax_provider_live()
    except Exception as e:
        print(f"# row-2 live measurement failed: {e!r}", file=sys.stderr)
    try:
        hops = live_route_hops()
        detail["route_hops"] = hops
        hop_str = (
            f"measured p99 hops {hops['ours']['p99']:.0f} "
            f"vs {hops['reference']['p99']:.0f}"
        )
    except Exception as e:
        print(f"# live hop measurement failed: {e!r}", file=sys.stderr)
        hops, hop_str = None, "hops unmeasured"

    if result is None:
        rc, parsed = _run_child(131_072, "cpu", 300.0)
        if parsed:
            result = parsed
    if collapsed is None:
        # No TPU collapsed number: still record the fast path on CPU (the
        # 1M x 1024 rebalance decision is ~1-2 s warm even on host).
        rc, collapsed = _run_child(1_048_576, "cpu", 300.0, collapsed=True)
        if collapsed:
            detail["collapsed_tier"] = collapsed
            print(f"# collapsed rebalance tier (cpu): {collapsed}", file=sys.stderr)
    if delta_tier is None:
        rc, delta_tier = _run_child(1_048_576, "cpu", 600.0, delta=True)
        if delta_tier:
            detail["delta_tier"] = delta_tier
            print(f"# delta churn tier (cpu): {delta_tier}", file=sys.stderr)
    detail["solve_tier"] = result
    _write_detail(detail)

    if collapsed is not None and collapsed.get("platform") == "tpu":
        # The headline: what the directory actually runs for a full 1M-scale
        # rebalance (class-collapsed device pipeline) — BASELINE row 3's
        # <50 ms-class target.  The dense general-cost solve stays visible.
        dense_str = (
            f"; dense OT {result['rate']:.0f}/s"
            if result is not None and result.get("platform") == "tpu"
            else ""
        )
        warm = collapsed.get("warm_assign")
        warm_str = f"; warm assign {warm['rate']:.0f}/s" if warm else ""
        sustain_str = (
            f" sustained over {collapsed['chain_steps']} chained churn steps "
            f"(single call incl. relay sync {collapsed['single_shot_ms']} ms)"
            if "chain_steps" in collapsed
            else ""
        )
        print(
            json.dumps(
                {
                    "metric": (
                        "placements/sec (committed rebalance fast path: "
                        "class-collapsed solve+expand+repair on device, "
                        f"{collapsed['n_obj']} objects x {collapsed['n_nodes']} "
                        f"nodes re-seated in {collapsed['full_ms']} ms"
                        f"{sustain_str} after "
                        f"{collapsed['dead_nodes']} node deaths, moved "
                        f"{collapsed['moved']} (displaced {collapsed['displaced']}), "
                        f"tpu{dense_str}{warm_str}; {hop_str})"
                    ),
                    "value": round(collapsed["rate"], 1),
                    "unit": "placements/sec",
                    "vs_baseline": round(collapsed["rate"] / baseline, 2),
                }
            )
        )
        return

    # Any non-tpu headline embeds the banked hardware evidence explicitly
    # (rate + vs_baseline from the capture's OWN session, captured_at,
    # relay trend) so a scorer reading only the final line can neither
    # mistake the fallback for hardware nor lose the banked number.
    banked_block = _tpu_banked_block()

    if result is None:
        # Solve tiers all failed: still emit a real measured number so the
        # artifact parses — the live hop metric stands on its own.
        if hops is not None:
            payload = {
                "metric": "p99 route hops (live 8-server cluster, "
                "directory policy; solve tiers failed)",
                "value": hops["ours"]["p99"],
                "unit": "hops",
                "vs_baseline": round(
                    hops["reference"]["p99"] / max(hops["ours"]["p99"], 1e-9), 2
                ),
            }
            if banked_block is not None:
                payload["tpu_banked"] = banked_block
            print(json.dumps(payload))
            return
        raise SystemExit("all benchmark tiers failed")

    if result["platform"] == "cpu" and "greedy" in result:
        # Headline the mode a CPU deployment actually runs (greedy tier);
        # the OT rate stays visible in the metric string and the sidecar.
        coll_str = (
            f"; collapsed 1M-rebalance {collapsed['full_ms']:.0f}ms"
            if collapsed is not None
            else ""
        )
        metric = (
            f"placements/sec (greedy tier — what mode='auto' selects off-TPU "
            f"— {result['n_obj']} objects x {N_NODES} nodes, cpu; OT solve "
            f"{result['rate']:.0f}/s{coll_str}; {hop_str})"
        )
        value = result["greedy"]["rate"]
    else:
        metric = (
            f"placements/sec (OT solve, {result['n_obj']} objects x "
            f"{N_NODES} nodes, {result['platform']}; {hop_str})"
        )
        value = result["rate"]
    payload = {
        "metric": metric,
        "value": round(value, 1),
        "unit": "placements/sec",
        "vs_baseline": round(value / baseline, 2),
    }
    if result.get("platform") != "tpu" and banked_block is not None:
        payload["tpu_banked"] = banked_block
    print(json.dumps(payload))


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--tier", type=int, default=None)
    parser.add_argument("--platform", choices=("tpu", "cpu"), default="tpu")
    parser.add_argument("--deadline", type=float, default=300.0)
    parser.add_argument("--hier", action="store_true")
    # Child-side marker for the mesh x chunk vs chunked-only paired A/B
    # (parents spawn it via `--hier --mesh-ab --tier N`); `--hier` with no
    # --tier runs the parent stage and banks into the cpu sidecar.
    parser.add_argument("--mesh-ab", action="store_true")
    parser.add_argument("--collapsed", action="store_true")
    # Churn-reaction A/B (full vs delta rebalance). Works without --tier
    # (defaults to the 1M x 64 acceptance shape); CPU rehearsal:
    # `python bench.py --delta --platform cpu`.
    parser.add_argument("--delta", action="store_true")
    # Rehearse the migration-drain host stage alone (CPU-safe: in-process
    # live cluster, never touches the relay).
    parser.add_argument("--migration", action="store_true")
    # Rehearse the hot-key read scale-out host stage alone (same CPU-safe
    # in-process-cluster shape as --migration).
    parser.add_argument("--hotkey", action="store_true")
    # Rehearse the tracing/metrics overhead A/B alone (same CPU-safe
    # in-process-cluster shape as --migration).
    parser.add_argument("--tracing", action="store_true")
    # Rehearse the control-plane journal overhead A/B alone (same CPU-safe
    # in-process-cluster shape as --migration).
    parser.add_argument("--journal", action="store_true")
    # Run the gauge time-series sampling A/B alone and bank it into the
    # cpu sidecar (same CPU-safe in-process-cluster shape as --migration).
    parser.add_argument("--series", action="store_true")
    # Run the request-waterfall span-retention A/B alone and bank it into
    # the cpu sidecar (same CPU-safe in-process-cluster shape as --series).
    parser.add_argument("--spans", action="store_true")
    # Run the sharded data-plane A/B battery alone and bank it into the
    # cpu sidecar (real worker processes on loopback; CPU-safe).
    parser.add_argument("--sharded", action="store_true")
    # Run the egress-coalescing A/B alone and bank it into the cpu sidecar
    # (in-process live cluster, both transports; CPU-safe).
    parser.add_argument("--egress", action="store_true")
    # Run the fault-injection disabled-overhead A/B alone and bank it into
    # the cpu sidecar (same CPU-safe in-process-cluster shape as --series).
    parser.add_argument("--faults", action="store_true")
    # Run the durable-streams publish/deliver + redelivery-backstop A/B
    # alone and bank it into the cpu sidecar (in-process clusters over
    # LocalStreamStorage; CPU-safe).
    parser.add_argument("--streams", action="store_true")
    # Run the affinity-placement bytes-over-TCP A/B + sampler-overhead
    # stage alone and bank it into the cpu sidecar (in-process clusters;
    # CPU-safe).
    parser.add_argument("--affinity", action="store_true")
    # Run the QoS uniform-overhead + flood-protection A/B alone and bank
    # it into the cpu sidecar (in-process clusters; CPU-safe).
    parser.add_argument("--qos", action="store_true")
    # Run the autoscale idle A/B + ramp soak alone and bank it into the
    # cpu sidecar (in-process + subprocess clusters on loopback;
    # CPU-safe).
    parser.add_argument("--autoscale", action="store_true")
    args = parser.parse_args()
    if args.migration:
        _pin_orchestrator_to_cpu()
        print(json.dumps(migration_drain()))
    elif args.hotkey:
        _pin_orchestrator_to_cpu()
        print(json.dumps(hotkey_scaleout()))
    elif args.tracing:
        _pin_orchestrator_to_cpu()
        print(json.dumps(tracing_overhead()))
    elif args.journal:
        _pin_orchestrator_to_cpu()
        print(json.dumps(journal_overhead()))
    elif args.series:
        # Standalone --series updates the banked cpu sidecar in place (the
        # --sharded pattern): the A/B carries its own paired baseline, so
        # it can refresh independently of the other host stages.
        _pin_orchestrator_to_cpu()
        out = series_overhead()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["series"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.spans:
        # Standalone --spans updates the banked cpu sidecar in place (the
        # --series pattern): the A/B carries its own paired baseline, so
        # it can refresh independently of the other host stages.
        _pin_orchestrator_to_cpu()
        out = spans_overhead()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["spans"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.sharded:
        # Standalone --sharded updates the banked cpu sidecar in place:
        # the stage carries its own in-session sqlite baseline, so it can
        # refresh independently of the other host stages (each of which
        # embeds its own baseline too).
        _pin_orchestrator_to_cpu()
        out = rpc_sharded()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["rpc_sharded"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.egress:
        # Standalone --egress updates the banked cpu sidecar in place (the
        # --sharded pattern): the A/B carries its own paired baseline, so
        # it can refresh independently of the other host stages.
        _pin_orchestrator_to_cpu()
        out = rpc_egress()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["rpc_egress"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.faults:
        # Standalone --faults updates the banked cpu sidecar in place (the
        # --series pattern): the A/B carries its own paired baseline, so
        # it can refresh independently of the other host stages.
        _pin_orchestrator_to_cpu()
        out = faults_overhead()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["faults"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.streams:
        # Standalone --streams updates the banked cpu sidecar in place (the
        # --faults pattern): the A/B carries its own paired baseline, so
        # it can refresh independently of the other host stages.
        _pin_orchestrator_to_cpu()
        out = streams_throughput()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["streams"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.autoscale:
        # Standalone --autoscale updates the banked cpu sidecar in place
        # (the --streams pattern): both halves carry their own paired
        # baseline / inline assertions, so the stage can refresh
        # independently of the other host stages.
        _pin_orchestrator_to_cpu()
        out = autoscale_stage()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["autoscale"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.affinity:
        # Standalone --affinity updates the banked cpu sidecar in place
        # (the --streams pattern): the A/B carries its own paired
        # baseline, so it can refresh independently of the other host
        # stages.
        _pin_orchestrator_to_cpu()
        out = affinity_payoff()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["affinity"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.qos:
        # Standalone --qos updates the banked cpu sidecar in place (the
        # --streams pattern): both halves carry their own paired
        # baseline, so the stage can refresh independently of the other
        # host stages.
        _pin_orchestrator_to_cpu()
        out = qos_stage()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["qos"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.delta:
        run_delta_tier(args.tier or 1_048_576, args.platform, args.deadline)
    elif args.mesh_ab and args.tier is not None:
        run_hier_mesh_ab_tier(args.tier, args.deadline)
    elif args.hier and args.tier is None:
        # Standalone `--hier` (no --tier) runs the ISSUE 18 mesh x chunk
        # vs chunked-only paired A/B and updates the banked cpu sidecar in
        # place (the --affinity pattern); the measurement itself runs in a
        # CPU child, so this is safe while the relay is wedged.
        _pin_orchestrator_to_cpu()
        out = hier_mesh_ab()
        here = os.path.dirname(os.path.abspath(__file__))
        try:
            with open(os.path.join(here, "BENCH_DETAIL.cpu.json")) as fh:
                detail = json.load(fh)
            if not isinstance(detail, dict):
                detail = {}
        except (OSError, ValueError):
            detail = {}
        detail["hier_mesh_ab"] = out
        _write_detail(detail, here)
        print(json.dumps(out))
    elif args.tier is not None and args.hier:
        run_hier_tier(args.tier, args.deadline, args.platform)
    elif args.tier is not None and args.collapsed:
        run_collapsed_tier(args.tier, args.platform, args.deadline)
    elif args.tier is not None:
        run_tier(args.tier, args.platform, args.deadline)
    else:
        main()
