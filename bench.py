"""rio-tpu headline benchmark: placements/sec @ 1M objects x 1k nodes.

Compares the TPU placement solve (entropic OT + capacity-aware rounding,
``rio_tpu/ops``) against the reference architecture's per-object SQL round
trip (one SELECT + one INSERT per placement, exactly the queries in
``rio-rs/src/object_placement/sqlite.rs:68-100``), measured here through
Python's C sqlite3 module on the same schema.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import sqlite3
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

N_NODES = 1024
CHUNK = 8192  # rows per rounding chunk (bounds rounding memory)


def sqlite_baseline_rate(n_samples: int = 5000) -> float:
    """Placements/sec for the reference's row-by-row SQL directory."""
    db = sqlite3.connect(":memory:")
    db.execute(
        "CREATE TABLE object_placement ("
        "struct_name TEXT NOT NULL, object_id TEXT NOT NULL,"
        "server_address TEXT, PRIMARY KEY (struct_name, object_id))"
    )
    db.execute("CREATE INDEX idx_addr ON object_placement (server_address)")
    t0 = time.perf_counter()
    for i in range(n_samples):
        # The allocate path: lookup miss then upsert (service.rs:193-254).
        db.execute(
            "SELECT server_address FROM object_placement "
            "WHERE struct_name=? AND object_id=?",
            ("Bench", str(i)),
        ).fetchone()
        db.execute(
            "INSERT INTO object_placement (struct_name, object_id, server_address) "
            "VALUES (?, ?, ?) ON CONFLICT (struct_name, object_id) "
            "DO UPDATE SET server_address=excluded.server_address",
            ("Bench", str(i), f"10.0.0.{i % 64}:5000"),
        )
        db.commit()
    return n_samples / (time.perf_counter() - t0)


def tpu_solve_rate(n_obj: int) -> tuple[float, int]:
    """Placements/sec for the on-device OT solve; returns (rate, n_obj used).

    Uses the scaling-form solver (``rio_tpu/ops/scaling.py``): K = exp(-C/eps)
    is built once and each iteration is two matrix-vector products — no
    per-iteration transcendentals, bandwidth-bound on reading K.
    """
    from rio_tpu.ops import plan_rounded_assign, scaling_sinkhorn

    def step(cost, mass, cap):
        res = scaling_sinkhorn(cost, mass, cap, eps=0.05, n_iters=30)
        # Chunk the rounding pass so its softmax/cumsum temps stay bounded.
        n_chunks = cost.shape[0] // CHUNK
        cost_c = cost.reshape(n_chunks, CHUNK, cost.shape[1])
        f_c = res.f.reshape(n_chunks, CHUNK)

        def round_chunk(args):
            c, f = args
            return plan_rounded_assign(c, f, res.g, 0.05)

        assignment = lax.map(round_chunk, (cost_c, f_c)).reshape(-1)
        # Scalar checksum: pulling it to host forces full completion (the
        # axon tunnel's block_until_ready returns before execution finishes).
        return assignment, jnp.sum(assignment)

    key = jax.random.PRNGKey(0)
    cost = jax.random.uniform(key, (n_obj, N_NODES), jnp.float32)
    mass = jnp.ones((n_obj,), jnp.float32)
    cap = jnp.ones((N_NODES,), jnp.float32)

    fn = jax.jit(step)
    _, chk = fn(cost, mass, cap)
    float(chk)  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        _, chk = fn(cost, mass, cap)
        float(chk)
        times.append(time.perf_counter() - t0)
    return n_obj / min(times), n_obj


def route_hop_summary() -> str:
    """p99 route hops, simulated for both client policies (BASELINE metric)."""
    from rio_tpu.utils.routing_sim import simulate_route_hops

    stats = simulate_route_hops(n_requests=100_000)
    ref, ours = stats["reference"], stats["rio_tpu"]
    print(
        f"# route hops @1M obj/1k nodes: ours p99={ours.p99} mean={ours.mean:.2f}"
        f" | reference-policy p99={ref.p99} mean={ref.mean:.2f}",
        file=sys.stderr,
    )
    return f"p99 hops {ours.p99:.0f} vs {ref.p99:.0f}"


def main() -> None:
    baseline = sqlite_baseline_rate()
    hops = route_hop_summary()
    rate = None
    for n_obj in (1_048_576, 524_288, 262_144):
        try:
            rate, n_used = tpu_solve_rate(n_obj)
            break
        except Exception as e:  # OOM tier fallback
            print(f"# {n_obj} failed: {type(e).__name__}: {e}", file=sys.stderr)
    if rate is None:
        raise SystemExit("all problem sizes failed")
    print(
        json.dumps(
            {
                "metric": (
                    f"placements/sec (OT solve, {n_used} objects x {N_NODES} nodes; "
                    f"{hops})"
                ),
                "value": round(rate, 1),
                "unit": "placements/sec",
                "vs_baseline": round(rate / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
