"""On-hardware Pallas kernel validation (isolated, wedge-conscious).

The r3 bench's in-tier Pallas smoke hung (Mosaic compile through the axon
tunnel) and its watchdog exit wedged the relay. This runner validates each
fused kernel in its OWN child process with a long deadline and tiny
shapes, banking results to ``PALLAS_TPU.json`` between children, so:

* a hang costs one kernel's evidence, not the banked results;
* the long (default 600 s) deadline lets a slow-but-finite Mosaic compile
  land instead of being watchdog-killed mid-op (the wedge trigger);
* stderr shows which kernel was in flight if it does wedge.

Usage:  python tpu_pallas_check.py            # orchestrator
        python tpu_pallas_check.py --kernel pallas_scaling   # one child
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "PALLAS_TPU.json")
N_OBJ, N_NODES = 8192, 256  # small: bound on-chip time, still real tiles
KERNELS = ("pallas_scaling", "pallas_logdomain")


def child(kernel: str, deadline: float) -> None:
    t = threading.Timer(deadline, lambda: os._exit(99))
    t.daemon = True
    t.start()
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        devices = jax.devices()
    except Exception as e:
        print(json.dumps({"kernel": kernel, "error": f"init: {e}"}), flush=True)
        os._exit(97)
    if devices[0].platform != "tpu":
        print(json.dumps({"kernel": kernel, "error": "no tpu"}), flush=True)
        os._exit(97)
    from rio_tpu.ops import scaling_sinkhorn
    from rio_tpu.ops.pallas_sinkhorn import pallas_sinkhorn
    from rio_tpu.ops.scaling import pallas_scaling_sinkhorn

    key = jax.random.PRNGKey(7)
    cost = jax.random.uniform(key, (N_OBJ, N_NODES), jnp.float32)
    mass = jnp.ones((N_OBJ,), jnp.float32)
    cap = jnp.ones((N_NODES,), jnp.float32)
    kw = dict(eps=0.05, n_iters=20)

    print(f"# reference solve...", file=sys.stderr, flush=True)
    ref = scaling_sinkhorn(cost, mass, cap, **kw)
    jax.block_until_ready((ref.f, ref.g))
    float(jnp.sum(jnp.where(jnp.isfinite(ref.g), ref.g, 0.0)))

    fn = {
        "pallas_scaling": lambda: pallas_scaling_sinkhorn(
            cost, mass, cap, interpret=False, **kw
        ),
        "pallas_logdomain": lambda: pallas_sinkhorn(
            cost, mass, cap, interpret=False, **kw
        ),
    }[kernel]
    print(f"# compiling+running {kernel} (interpret=False)...", file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    res = fn()
    jax.block_until_ready((res.f, res.g))
    float(jnp.sum(jnp.where(jnp.isfinite(res.g), res.g, 0.0)))
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    res = fn()
    jax.block_until_ready((res.f, res.g))
    float(jnp.sum(jnp.where(jnp.isfinite(res.g), res.g, 0.0)))
    run_ms = (time.perf_counter() - t0) * 1e3

    g_ref, g = np.asarray(ref.g), np.asarray(res.g)
    finite = np.isfinite(g_ref) & np.isfinite(g)
    if not finite.any():
        # A Mosaic miscompile can yield all-NaN potentials — record it as a
        # PARITY FAILURE, not a hang.
        out = {
            "kernel": kernel,
            "ok": False,
            "device": str(devices[0]),
            "compile_s": round(compile_s, 2),
            "error": "no finite potentials (miscompile?)",
        }
        print(json.dumps(out), flush=True)
        os._exit(0)
    out = {
        "kernel": kernel,
        "ok": True,
        "device": str(devices[0]),
        "shape": [N_OBJ, N_NODES],
        "compile_s": round(compile_s, 2),
        "run_ms": round(run_ms, 2),
        "max_dg_vs_xla": float(np.max(np.abs(g_ref[finite] - g[finite]))),
    }
    print(json.dumps(out), flush=True)
    os._exit(0)


def main(deadline: float) -> None:
    results = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as fh:
                results = json.load(fh)
        except (json.JSONDecodeError, OSError):
            results = {}  # prior run died mid-write; start fresh
    for kernel in KERNELS:
        print(f"=== {kernel}", file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--kernel", kernel,
                 "--deadline", str(deadline)],
                stdout=subprocess.PIPE, timeout=deadline + 60,
            )
        except subprocess.TimeoutExpired:
            results[kernel] = {"kernel": kernel, "error": "parent backstop timeout"}
            with open(OUT, "w") as fh:
                json.dump(results, fh, indent=1)
            print("=== parent backstop fired; relay likely wedged; stopping",
                  file=sys.stderr)
            break
        parsed = None
        for line in proc.stdout.decode(errors="replace").splitlines():
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
        results[kernel] = parsed or {"kernel": kernel, "rc": proc.returncode,
                                     "error": "no result (hang/wedge?)"}
        with open(OUT, "w") as fh:  # bank after every child
            json.dump(results, fh, indent=1)
        print(f"=== {kernel}: {results[kernel]}", file=sys.stderr)
        if proc.returncode == 99:
            print("=== watchdog fired: relay likely wedged; stopping", file=sys.stderr)
            break
        if proc.returncode == 97:
            print("=== backend init failed; stopping (no point re-initing)",
                  file=sys.stderr)
            break


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--kernel", choices=KERNELS)
    ap.add_argument("--deadline", type=float, default=600.0)
    args = ap.parse_args()
    if args.kernel:
        child(args.kernel, args.deadline)
    else:
        main(args.deadline)
