"""On-hardware Pallas kernel validation + timing (isolated, wedge-conscious).

The r3 bench's in-tier Pallas smoke hung (Mosaic compile through the axon
tunnel) and its watchdog exit wedged the relay. This runner validates each
fused kernel in its OWN child process with a long deadline, banking results
to ``PALLAS_TPU.json`` between children, so:

* a hang costs one kernel's evidence, not the banked results;
* the long (default 600 s) deadline lets a slow-but-finite Mosaic compile
  land instead of being watchdog-killed mid-op (the wedge trigger);
* stderr shows which kernel was in flight if it does wedge.

r4 additions (per the r4 wedge postmortem in CLAUDE.md):

* NO eager jnp ops anywhere — syncs are plain value pulls on jit outputs
  (an eager-op warmup hung indefinitely through the relay in r4);
* per-iteration timing by SLOPE: each solver is timed at two iteration
  counts and (t_hi - t_lo) / (n_hi - n_lo) isolates the per-iteration
  device cost from the relay's ~300 ms per-call dispatch+sync overhead
  (which cancels in the difference);
* the XLA scaling-form solver is timed identically at the same shape, so
  the artifact records pallas-vs-XLA ms/iter head to head — the kernels'
  reason to exist (one HBM sweep of K per iteration, scaling.py:19-23)
  is only proven if their slope beats XLA's two-sweep slope.

Usage:  python tpu_pallas_check.py            # orchestrator
        python tpu_pallas_check.py --kernel pallas_scaling   # one child
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "PALLAS_TPU.json")
N_OBJ, N_NODES = 8192, 256  # parity shape: small, bounds on-chip time
PERF_N_OBJ, PERF_N_NODES = 262_144, 1024  # perf shape: K bf16 = 512 MB
ITERS_LO, ITERS_HI = 20, 60
KERNELS = ("pallas_scaling", "pallas_logdomain")


def _watchdog(deadline: float) -> None:
    def fire():
        print(f"# watchdog fired after {deadline:.0f}s", file=sys.stderr, flush=True)
        os._exit(99)

    t = threading.Timer(deadline, fire)
    t.daemon = True
    t.start()


def _time_solver(fn, n_iters_pair, label: str, t_deadline: float) -> dict:
    """Time fn(n_iters) at two iteration counts; slope = per-iter device ms.

    ``fn(n)`` must return a jittable scalar-reducing callable's OUTPUT
    (a device scalar): the plain float() pull is the only sync. The hi
    measurement is skipped (slope falls back to the overhead-inclusive
    lo average, marked ``"slope": False``) unless its projected cost —
    scaled from the MEASURED lo run plus a fresh compile — clearly fits
    before ``t_deadline`` (watchdogs must never fire mid-op).
    """
    lo, hi = n_iters_pair
    out = {}
    for name, n in (("lo", lo), ("hi", hi)):
        if name == "hi":
            projected = (
                2.5 * out["lo"]["compile_s"]
                + 3 * (hi / lo) * out["lo"]["ms"] / 1e3
            )
            if time.perf_counter() + projected > t_deadline:
                print(f"# {label}: skipping hi run (projected {projected:.0f}s "
                      f"over budget)", file=sys.stderr, flush=True)
                out["ms_per_iter"] = round(out["lo"]["ms"] / lo, 3)
                out["slope"] = False
                return out
        t0 = time.perf_counter()
        float(fn(n))
        compile_s = time.perf_counter() - t0
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            float(fn(n))
            times.append(time.perf_counter() - t0)
        out[name] = {"n_iters": n, "ms": round(min(times) * 1e3, 2),
                     "compile_s": round(compile_s, 1)}
        print(f"# {label} n_iters={n}: {out[name]}", file=sys.stderr, flush=True)
    out["ms_per_iter"] = round((out["hi"]["ms"] - out["lo"]["ms"]) / (hi - lo), 3)
    out["slope"] = True
    return out


def child(kernel: str, deadline: float) -> None:
    _watchdog(deadline)
    t_deadline = time.perf_counter() + deadline - 30.0
    # Mechanics-validation mode (RIO_TPU_PALLAS_DEBUG_CPU=1): run the WHOLE
    # protocol — parity, banking, slope timing, budget gates — on the CPU
    # backend with interpreted kernels at tiny shapes, so a script bug is
    # found on the host instead of burning a scarce healthy-relay window.
    # Artifacts from this mode are marked "debug_cpu" and must never be
    # read as hardware evidence.
    debug_cpu = os.environ.get("RIO_TPU_PALLAS_DEBUG_CPU") == "1"
    if debug_cpu:
        # Pin the CPU backend BEFORE any jax init: the ambient sitecustomize
        # sets JAX_PLATFORMS=axon, and a host rehearsal must never touch the
        # relay (wedged: hangs to the watchdog; healthy: burns the window).
        from rio_tpu.utils.jaxenv import force_cpu

        force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        devices = jax.devices()
    except Exception as e:
        print(json.dumps({"kernel": kernel, "error": f"init: {e}"}), flush=True)
        os._exit(97)
    if devices[0].platform != "tpu" and not debug_cpu:
        print(json.dumps({"kernel": kernel, "error": "no tpu"}), flush=True)
        os._exit(97)
    interpret = devices[0].platform != "tpu"
    n_obj, n_nodes = (1024, 128) if debug_cpu else (N_OBJ, N_NODES)
    perf_n_obj, perf_n_nodes = (
        (8192, 256) if debug_cpu else (PERF_N_OBJ, PERF_N_NODES)
    )
    from rio_tpu.ops import scaling_sinkhorn
    from rio_tpu.ops.pallas_sinkhorn import pallas_sinkhorn
    from rio_tpu.ops.scaling import pallas_scaling_sinkhorn

    pallas_fn = {
        "pallas_scaling": pallas_scaling_sinkhorn,
        "pallas_logdomain": pallas_sinkhorn,
    }[kernel]

    # ---- parity at the small shape --------------------------------------
    key = jax.random.PRNGKey(7)
    cost = jax.random.uniform(key, (n_obj, n_nodes), jnp.float32)
    mass = jnp.ones((n_obj,), jnp.float32)
    cap = jnp.ones((n_nodes,), jnp.float32)
    kw = dict(eps=0.05, n_iters=ITERS_LO)

    print("# reference solve...", file=sys.stderr, flush=True)
    ref = scaling_sinkhorn(cost, mass, cap, **kw)
    g_ref = np.asarray(ref.g)  # transfer pull = sync; no eager ops

    print(f"# compiling+running {kernel} (interpret={interpret})...",
          file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    res = pallas_fn(cost, mass, cap, interpret=interpret, **kw)
    g = np.asarray(res.g)
    compile_s = time.perf_counter() - t0

    finite = np.isfinite(g_ref) & np.isfinite(g)
    if not finite.any():
        # A Mosaic miscompile can yield all-NaN potentials — record it as a
        # PARITY FAILURE, not a hang.
        out = {
            "kernel": kernel,
            "ok": False,
            "device": str(devices[0]),
            "compile_s": round(compile_s, 2),
            "error": "no finite potentials (miscompile?)",
        }
        print(json.dumps(out), flush=True)
        os._exit(0)
    out = {
        "kernel": kernel,
        "ok": True,
        "device": str(devices[0]),
        "debug_cpu": debug_cpu,
        "shape": [n_obj, n_nodes],
        "compile_s": round(compile_s, 2),
        "max_dg_vs_xla": float(np.max(np.abs(g_ref[finite] - g[finite]))),
    }
    print(json.dumps(out), flush=True)  # bank parity before perf timing

    # ---- per-iteration slope at the perf shape --------------------------
    # K bf16 = 512 MB: XLA's two sweeps/iter = 1 GB HBM, the fused kernel's
    # one sweep = 0.5 GB — ~0.6 vs ~1.2 ms/iter at v5e roofline. Timed by
    # slope so the relay's per-call overhead cancels (see module docstring).
    key = jax.random.PRNGKey(11)
    cost_p = jax.random.uniform(key, (perf_n_obj, perf_n_nodes), jnp.float32)
    mass_p = jnp.ones((perf_n_obj,), jnp.float32)
    cap_p = jnp.ones((perf_n_nodes,), jnp.float32)

    import functools

    # Optional layout experiment knob for the perf phase: the grid walks
    # n/block_rows steps per iteration, so if per-step overhead (not HBM)
    # dominates, a larger block should show it immediately in the slope.
    block_rows = int(os.environ.get("RIO_TPU_PALLAS_BLOCK_ROWS", "0"))
    pallas_kw = {"block_rows": block_rows} if block_rows else {}

    @functools.partial(jax.jit, static_argnames=("n",))
    def run_pallas(cost, mass, cap, n):
        r = pallas_fn(
            cost, mass, cap, eps=0.05, n_iters=n, interpret=interpret, **pallas_kw
        )
        return jnp.sum(jnp.where(jnp.isfinite(r.g), r.g, 0.0))

    @functools.partial(jax.jit, static_argnames=("n",))
    def run_xla(cost, mass, cap, n):
        r = scaling_sinkhorn(cost, mass, cap, eps=0.05, n_iters=n)
        return jnp.sum(jnp.where(jnp.isfinite(r.g), r.g, 0.0))

    out["perf_shape"] = [perf_n_obj, perf_n_nodes]
    if block_rows:
        out["block_rows"] = block_rows
    # Budget each lo run from MEASURED prior-stage timings (CLAUDE.md rule;
    # the parity stage above is the only measurement we have for the first
    # projection). 32x the data of the parity shape: assume compile scales
    # ~4x and execution ~32x — deliberately pessimistic so a degraded
    # relay banks what it has and exits instead of letting the watchdog
    # fire mid-op.
    xla_projected = 4.0 * compile_s + 10.0
    if time.perf_counter() + xla_projected > t_deadline:
        print(f"# skipping perf section (projected {xla_projected:.0f}s "
              f"over budget)", file=sys.stderr, flush=True)
        print(json.dumps(out), flush=True)
        os._exit(0)
    out["xla_ref"] = _time_solver(
        lambda n: run_xla(cost_p, mass_p, cap_p, n),
        (ITERS_LO, ITERS_HI), "xla_ref", t_deadline,
    )
    print(json.dumps(out), flush=True)  # bank XLA baseline before the kernel
    # Mosaic compiles slower than XLA and is the historically hang-prone
    # step: project from the measured XLA perf-shape timings, doubled.
    ref_lo = out["xla_ref"].get("lo", {"compile_s": compile_s, "ms": 1e4})
    pallas_projected = 2.0 * ref_lo["compile_s"] + 6.0 * ref_lo["ms"] / 1e3 + 10.0
    if time.perf_counter() + pallas_projected > t_deadline:
        print(f"# skipping pallas perf (projected {pallas_projected:.0f}s "
              f"over budget)", file=sys.stderr, flush=True)
        print(json.dumps(out), flush=True)
        os._exit(0)
    out["pallas"] = _time_solver(
        lambda n: run_pallas(cost_p, mass_p, cap_p, n),
        (ITERS_LO, ITERS_HI), kernel, t_deadline,
    )
    # Head-to-head ratio only when BOTH numbers are true slopes and
    # positive — a slope/fallback mix or a jitter-negative slope would
    # record an apples-to-oranges or negative headline.
    xr, pr = out["xla_ref"], out["pallas"]
    if xr.get("slope") and pr.get("slope") and xr["ms_per_iter"] > 0 and pr["ms_per_iter"] > 0:
        out["pallas_vs_xla"] = round(xr["ms_per_iter"] / pr["ms_per_iter"], 2)
    else:
        out["pallas_vs_xla"] = None
    print(json.dumps(out), flush=True)
    os._exit(0)


def main(deadline: float, only: str | None = None) -> None:
    global OUT
    if os.environ.get("RIO_TPU_PALLAS_DEBUG_CPU") == "1":
        # Mechanics-validation artifacts must never clobber hardware evidence.
        OUT = OUT.replace("PALLAS_TPU", "PALLAS_DEBUG")
    # A block-rows sweep (RIO_TPU_PALLAS_BLOCK_ROWS) banks under its OWN
    # key so it can never replace the default-layout hardware result.
    block_rows = os.environ.get("RIO_TPU_PALLAS_BLOCK_ROWS", "")
    suffix = f"_br{block_rows}" if block_rows else ""
    results = {}
    if os.path.exists(OUT):
        try:
            with open(OUT) as fh:
                results = json.load(fh)
        except (json.JSONDecodeError, OSError):
            results = {}  # prior run died mid-write; start fresh
    for kernel in KERNELS:
        if only is not None and kernel != only:
            continue
        rkey = kernel + suffix
        print(f"=== {rkey}", file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--kernel", kernel,
                 "--deadline", str(deadline)],
                stdout=subprocess.PIPE, timeout=deadline + 60,
            )
        except subprocess.TimeoutExpired:
            results[rkey] = {"kernel": kernel, "error": "parent backstop timeout"}
            with open(OUT, "w") as fh:
                json.dump(results, fh, indent=1)
            print("=== parent backstop fired; relay likely wedged; stopping",
                  file=sys.stderr)
            break
        parsed = None
        for line in proc.stdout.decode(errors="replace").splitlines():
            try:
                candidate = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(candidate, dict):
                parsed = candidate  # last banked line wins
        fresh = parsed or {"kernel": kernel, "rc": proc.returncode,
                           "error": "no result (hang/wedge?)"}
        prior = results.get(rkey)
        if (
            isinstance(prior, dict)
            and prior.get("ok")
            and not fresh.get("ok")
            and "device" not in fresh
        ):
            # Never replace a banked hardware success with a wedge/init
            # error that never reached the chip (a failed re-run against a
            # down relay overwrote the r4 capture once) — keep the
            # evidence, note the failed attempt. A real on-hardware parity
            # failure carries a "device" key and DOES overwrite.
            print(f"=== {kernel}: keeping prior ok result; new attempt "
                  f"failed ({fresh.get('error', fresh.get('rc'))})",
                  file=sys.stderr)
            results[rkey] = {**prior, "last_failed_attempt": fresh}
        else:
            results[rkey] = fresh
        with open(OUT, "w") as fh:  # bank after every child
            json.dump(results, fh, indent=1)
        print(f"=== {rkey}: {results[rkey]}", file=sys.stderr)
        if proc.returncode == 99:
            print("=== watchdog fired: relay likely wedged; stopping", file=sys.stderr)
            break
        if proc.returncode == 97:
            print("=== backend init failed; stopping (no point re-initing)",
                  file=sys.stderr)
            break


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--kernel", choices=KERNELS)
    ap.add_argument("--only", choices=KERNELS, default=None,
                    help="orchestrator mode: run a single kernel")
    ap.add_argument("--deadline", type=float, default=600.0)
    args = ap.parse_args()
    if args.kernel:
        child(args.kernel, args.deadline)
    else:
        main(args.deadline, args.only)
