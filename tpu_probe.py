"""Lightweight TPU-availability probe (safe under a wedged axon relay).

Runs jax.devices() in THIS process under a hard os._exit watchdog, so a
hung PJRT init through the axon tunnel cannot orphan a chip grant: the
process dies cleanly before touching any TPU op.  Exit codes:

  0  — TPU present (prints device list)
  97 — backend init failed (relay down / fell back to non-tpu)
  99 — watchdog fired during init (relay wedged)

Run it as a child:  python tpu_probe.py   (never import this in-process).
"""

from __future__ import annotations

import os
import sys
import threading
import time


def main(deadline: float = 120.0) -> None:
    t = threading.Timer(deadline, lambda: os._exit(99))
    t.daemon = True
    t.start()
    t0 = time.monotonic()
    try:
        import jax

        devices = jax.devices()
    except Exception as e:
        print(f"init failed: {type(e).__name__}: {e}", flush=True)
        os._exit(97)
    dt = time.monotonic() - t0
    print(f"devices={devices} init_s={dt:.1f}", flush=True)
    if devices[0].platform != "tpu":
        os._exit(97)
    # Tiny smoke op to confirm the chip actually executes (still under the
    # watchdog; a wedged relay typically hangs here, not at devices()).
    import jax.numpy as jnp

    x = jnp.ones((128, 128))
    val = float((x @ x).sum())
    print(f"smoke matmul ok: {val}", flush=True)
    t.cancel()
    os._exit(0)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 120.0)
