"""Lightweight TPU-availability + relay-health probe (wedge-safe).

Runs jax.devices() in THIS process under a hard os._exit watchdog, so a
hung PJRT init through the axon tunnel cannot orphan a chip grant: the
process dies cleanly before touching any TPU op.  Exit codes:

  0  — TPU present (prints device list + latency health)
  97 — backend init failed (relay down / fell back to non-tpu)
  99 — watchdog fired during init, the smoke compile, or the health
       phase: the relay is wedged OR too degraded to finish one tiny
       compile + three round trips inside the deadline — either way,
       do NOT launch TPU work

Besides up/down, the probe prints LATENCY HEALTH — per-call dispatch+pull
round trip and a 4 MB device→host pull — because the relay DEGRADES
before it dies (r4: compile_s 66→106 and pull_ms 349→747 across
healthy-looking runs preceded the wedge). Treat rising numbers as "stop
launching TPU children now", not as noise. All syncs are jit + plain
value pulls: an eager-op sync hung indefinitely through the relay in r4.

Run it as a child:  python tpu_probe.py   (never import this in-process).
"""

from __future__ import annotations

import os
import sys
import threading
import time


def main(deadline: float = 120.0) -> None:
    t = threading.Timer(deadline, lambda: os._exit(99))
    t.daemon = True
    t.start()
    t0 = time.monotonic()
    try:
        import jax

        devices = jax.devices()
    except Exception as e:
        print(f"init failed: {type(e).__name__}: {e}", flush=True)
        os._exit(97)
    dt = time.monotonic() - t0
    print(f"devices={devices} init_s={dt:.1f}", flush=True)
    if devices[0].platform != "tpu":
        os._exit(97)
    # Smoke op to confirm the chip actually executes (still under the
    # watchdog; a wedged relay typically hangs here, not at devices()).
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def smoke(x):
        return jnp.sum(x @ x)

    x = jnp.ones((128, 128))
    val = float(smoke(x))
    print(f"smoke matmul ok: {val}", flush=True)

    # Latency health: best-of-3 dispatch+pull round trip on the tiny op
    # (already compiled above — the health phase adds NO compiles, so the
    # watchdog budget is unchanged from the pre-health probe), then one
    # 4 MB device→host pull. The pull is the ONLY sync on the buffer (no
    # block_until_ready, no eager reductions — the r4 hang pattern); it
    # includes the ones-fill, which is <1 ms of device work, so the time
    # is effectively the transfer.
    ts = []
    for _ in range(3):
        t1 = time.monotonic()
        float(smoke(x))
        ts.append((time.monotonic() - t1) * 1e3)
    big = jnp.ones((1024, 1024), jnp.float32)  # 4 MB
    t1 = time.monotonic()
    np.asarray(big)
    pull_ms = (time.monotonic() - t1) * 1e3
    print(f"roundtrip_ms={min(ts):.1f} pull4mb_ms={pull_ms:.1f}", flush=True)
    t.cancel()
    os._exit(0)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 120.0)
