"""Node-scoped admin/observability actor: the wire face of the ops plane.

Two services behind one ``rio.Admin`` actor per node (``__node_scoped__``,
id = the node's address, routed without the directory exactly like the
migration control plane):

* :class:`DumpStats` → :class:`StatsSnapshot` — the cluster scrape. One
  round trip returns the node's full :func:`rio_tpu.otel.server_gauges`
  snapshot plus its raw RED histogram rows
  (:meth:`rio_tpu.metrics.MetricsRegistry.snapshot_rows`), which are
  mergeable across nodes — a scraper walks the membership view, asks every
  node, and :func:`rio_tpu.metrics.merge_rows` yields cluster-wide
  p50/p99 (see ``examples/observability.py``).
* :class:`AdminRequest` → :class:`AdminAck` — a remote bridge onto the
  in-process :class:`~rio_tpu.commands.AdminSender` queue (drain this
  node, migrate an object, shut an object down) so ops tooling needs only
  a :class:`~rio_tpu.client.Client`.
* :class:`DumpEvents` → :class:`EventsSnapshot` — the control-plane
  flight recorder scrape (``rio_tpu/journal.py``): a filtered tail of the
  node's journal ring as wire rows, resumable by ``since_seq``.
  :func:`explain` walks every live node and merges the per-node streams
  into one causally ordered placement history for a single actor.

The gauge/histogram sources are injected at ``Server.bind()`` as a
:class:`StatsSource` — the actor itself stays free of server imports.

Operator CLI (see ``_cli_main``)::

    python -m rio_tpu.admin tail    --nodes host:p,host:p [--kind K] [--key K]
    python -m rio_tpu.admin explain --nodes host:p,host:p TYPE ID
    python -m rio_tpu.admin stats   --nodes host:p,host:p
    python -m rio_tpu.admin trace   --nodes host:p,host:p TRACE_ID
    python -m rio_tpu.admin edges   --nodes host:p,host:p [--limit K]
    python -m rio_tpu.admin qos     --nodes host:p,host:p [--limit K]
    python -m rio_tpu.admin --demo {tail|explain|stats|watch|trace|edges|qos}

A fourth wire pair serves the request-waterfall plane: :class:`DumpSpans`
→ :class:`SpansSnapshot` returns the node's retained request spans
(``rio_tpu/spans.py``); :func:`scrape_spans` + :func:`assemble_waterfall`
merge every ring — servers and the calling process's client ring — into
causally ordered per-trace hop trees rendered by the ``trace`` subcommand.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

from .app_data import AppData
from .commands import AdminCommand, AdminCommandKind, AdminSender
from .journal import Journal, JournalEvent, format_event, merge_events, subject_key
from .registry import handler, message, type_name
from .service_object import ServiceObject

#: Wire type-name of the node-scoped admin actor.
ADMIN_TYPE = "rio.Admin"


@message(name="rio.DumpStats")
@dataclass
class DumpStats:
    """Ask a node for its gauge + RED-histogram snapshot."""

    # Histograms dominate the payload on wide deployments; a pure-gauge
    # scrape can skip them.
    include_histograms: bool = True


@message(name="rio.StatsSnapshot")
@dataclass
class StatsSnapshot:
    """One node's observability snapshot (mergeable across nodes)."""

    address: str = ""
    gauges: dict[str, float] = field(default_factory=dict)
    # rio_tpu.metrics wire rows: [handler_type, message_type, count,
    # error_count, errors{kind:int}, buckets[], sum_s, max_s,
    # exemplar_trace, exemplar_s] — merge with metrics.merge_rows.
    histograms: list = field(default_factory=list)


@message(name="rio.DumpEvents")
@dataclass
class DumpEvents:
    """Ask a node for a filtered tail of its control-plane journal.

    Empty ``kinds``/``key`` mean "no filter"; ``since_seq`` resumes a tail
    (only events with ``seq > since_seq`` return); ``limit`` bounds the
    response to the NEWEST matches (0 = journal capacity).
    """

    kinds: list = field(default_factory=list)  # journal kind strings
    key: str = ""  # exact subject match, e.g. "Worker/w3"
    since_seq: int = 0
    limit: int = 512


@message(name="rio.EventsSnapshot")
@dataclass
class EventsSnapshot:
    """One node's journal tail (mergeable across nodes: ``merge_events``)."""

    address: str = ""
    node_seq: int = 0  # the node's latest journal seq (tail resume point)
    dropped: int = 0  # ring-overflow drop counter at scrape time
    # JournalEvent wire rows: [seq, wall_ts, mono_ts, node, epoch, kind,
    # key, attrs, trace_id] — decode with JournalEvent.from_row.
    rows: list = field(default_factory=list)

    def events(self) -> list[JournalEvent]:
        return [JournalEvent.from_row(r) for r in self.rows]


@message(name="rio.DumpSeries")
@dataclass
class DumpSeries:
    """Ask a node for a window of its gauge time-series ring.

    ``names`` projects each sample down to the named gauges (a trailing
    ``.`` makes a name a prefix filter, e.g. ``rio.handler.``); empty
    means every gauge. ``since_seq`` resumes a tail (only samples with
    ``seq > since_seq`` return); ``limit`` bounds the response to the
    NEWEST samples (0 = ring capacity).
    """

    names: list = field(default_factory=list)
    since_seq: int = 0
    limit: int = 240


@message(name="rio.SeriesSnapshot")
@dataclass
class SeriesSnapshot:
    """One node's gauge time-series window (merge with ``merge_series``)."""

    address: str = ""
    node_seq: int = 0  # the node's latest sample seq (tail resume point)
    dropped: int = 0  # ring-overwrite counter at scrape time
    # SeriesSample wire rows: [seq, wall_ts, mono_ts, node, gauges] —
    # decode with SeriesSample.from_row.
    rows: list = field(default_factory=list)
    # Node-side context that isn't a time series: solver mode, active
    # health alerts. String-keyed, append-only growth.
    meta: dict = field(default_factory=dict)

    def samples(self) -> list:
        from .timeseries import SeriesSample

        return [SeriesSample.from_row(r) for r in self.rows]


@message(name="rio.DumpSpans")
@dataclass
class DumpSpans:
    """Ask a node for retained request spans from its waterfall ring.

    ``trace_id`` filters to one trace (empty = every retained span);
    ``since_seq`` resumes a tail (only spans with ``seq > since_seq``
    return); ``limit`` bounds the response to the NEWEST matches
    (0 = ring capacity).
    """

    trace_id: str = ""
    since_seq: int = 0
    limit: int = 256


@message(name="rio.SpansSnapshot")
@dataclass
class SpansSnapshot:
    """One node's retained spans (merge with ``spans.merge_spans``)."""

    address: str = ""
    node_seq: int = 0  # the node's latest span seq (tail resume point)
    dropped: int = 0  # ring-overwrite counter at scrape time
    # SpanRecord wire rows: [seq, trace_id, span_id, parent_id, name,
    # node, wall_start, duration_us, attrs] — decode with
    # SpanRecord.from_row.
    rows: list = field(default_factory=list)

    def spans(self) -> list:
        from .spans import SpanRecord

        return [SpanRecord.from_row(r) for r in self.rows]


@message(name="rio.DumpEdges")
@dataclass
class DumpEdges:
    """Ask a node for its communication-edge graph (``rio_tpu/affinity``).

    ``limit`` bounds the response to the HEAVIEST edges by byte rate
    (0 = everything the sampler retained, itself top-K bounded).
    """

    limit: int = 256


@message(name="rio.EdgesSnapshot")
@dataclass
class EdgesSnapshot:
    """One node's sampled edge graph (merge with ``affinity.merge_edges``)."""

    address: str = ""
    # EdgeSampler wire rows: [src, dst, bytes_per_s, calls_per_s,
    # local_frac] — src/dst are "{type}.{id}" object keys ("client" for
    # external callers). Rows may only ever GROW by appending trailing
    # fields.
    rows: list = field(default_factory=list)
    sampled: int = 0  # dispatches observed (stride-scaled source count)
    evictions: int = 0  # cold edges dropped by the top-K bound
    cross_bytes_per_s: float = 0.0  # EMA byte rate of non-local traffic


@message(name="rio.DumpQos")
@dataclass
class DumpQos:
    """Ask a node for its request-QoS scheduler state (``rio_tpu/qos``).

    ``limit`` bounds the per-(tenant, class) RED rows to the busiest
    tenants by request count (0 = every row the scheduler retained).
    """

    limit: int = 64


@message(name="rio.QosSnapshot")
@dataclass
class QosSnapshot:
    """One node's QoS scheduler state. ``enabled`` is False (and every
    counter zero) on nodes built without a ``qos_config`` — a mixed
    cluster scrapes uniformly."""

    address: str = ""
    enabled: bool = False
    running: int = 0
    queued: int = 0
    admitted: int = 0
    sheds: int = 0
    deadline_drops: int = 0
    interactive_admitted: int = 0
    interactive_sheds: int = 0
    # Class label -> parked depth right now ("p2", "fair", ...).
    queue_depths: dict = field(default_factory=dict)
    # Per-(tenant, class) RED rows: [tenant, class, requests, errors,
    # avg_ms, avg_queue_ms, sheds, deadline_drops]. Rows may only ever
    # GROW by appending trailing fields.
    tenants: list = field(default_factory=list)


@message(name="rio.AdminRequest")
@dataclass
class AdminRequest:
    """Enqueue one :class:`~rio_tpu.commands.AdminCommand` on the node."""

    kind: str = ""  # an AdminCommandKind value, e.g. "drain_server"
    type_name: str = ""
    object_id: str = ""
    target: str = ""


@message(name="rio.AdminAck")
@dataclass
class AdminAck:
    ok: bool = False
    detail: str = ""


@dataclass
class StatsSource:
    """AppData-injectable snapshot providers (wired at ``Server.bind()``).

    ``gauges`` returns the :func:`~rio_tpu.otel.server_gauges` dict;
    ``histogram_rows`` returns the mergeable RED rows (empty when metrics
    are disabled). A dataclass wrapper — not bare callables — so AppData's
    type-keyed map can hold it.
    """

    gauges: Callable[[], dict[str, float]]
    histogram_rows: Callable[[], list[Any]]


@dataclass
class SeriesSource:
    """AppData-injectable time-series ring handle (wired at ``Server.bind()``).

    ``series`` is the node's :class:`~rio_tpu.timeseries.GaugeSeries`;
    ``meta`` returns scrape-time context that isn't a series (solver mode,
    active health alerts) for :class:`SeriesSnapshot.meta`.
    """

    series: Any  # rio_tpu.timeseries.GaugeSeries
    meta: Callable[[], dict] = dict


@type_name(ADMIN_TYPE)
class AdminControl(ServiceObject):
    """Node-scoped observability/ops endpoint (one per server; id = address)."""

    __node_scoped__ = True

    @handler
    async def dump_stats(self, msg: DumpStats, ctx: AppData) -> StatsSnapshot:
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        source = ctx.try_get(StatsSource)
        if source is None:
            return StatsSnapshot(address=info.address if info else "")
        rows = source.histogram_rows() if msg.include_histograms else []
        return StatsSnapshot(
            address=info.address if info else "",
            gauges=source.gauges(),
            histograms=rows,
        )

    @handler
    async def dump_events(self, msg: DumpEvents, ctx: AppData) -> EventsSnapshot:
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        address = info.address if info else ""
        journal = ctx.try_get(Journal)
        if journal is None:
            return EventsSnapshot(address=address)
        events = journal.events(
            kinds=msg.kinds or None,
            key=msg.key or None,
            since_seq=msg.since_seq,
            limit=msg.limit if msg.limit > 0 else None,
        )
        return EventsSnapshot(
            address=address,
            node_seq=journal.recorded,
            dropped=journal.dropped,
            rows=[e.to_row() for e in events],
        )

    @handler
    async def dump_series(self, msg: DumpSeries, ctx: AppData) -> SeriesSnapshot:
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        address = info.address if info else ""
        source = ctx.try_get(SeriesSource)
        if source is None or source.series is None:
            return SeriesSnapshot(address=address)
        series = source.series
        samples = series.window(
            names=msg.names or None,
            since_seq=msg.since_seq,
            limit=msg.limit if msg.limit > 0 else None,
        )
        return SeriesSnapshot(
            address=address,
            node_seq=series.sampled,
            dropped=series.dropped,
            rows=[s.to_row() for s in samples],
            meta=dict(source.meta() or {}),
        )

    @handler
    async def dump_spans(self, msg: DumpSpans, ctx: AppData) -> SpansSnapshot:
        from .commands import ServerInfo
        from .spans import SpanRing

        info = ctx.try_get(ServerInfo)
        address = info.address if info else ""
        ring = ctx.try_get(SpanRing)
        if ring is None:
            return SpansSnapshot(address=address)
        records = ring.spans(
            trace_id=msg.trace_id or None,
            since_seq=msg.since_seq,
            limit=msg.limit if msg.limit > 0 else None,
        )
        return SpansSnapshot(
            address=address,
            node_seq=ring.retained,
            dropped=ring.dropped,
            rows=[r.to_row() for r in records],
        )

    @handler
    async def dump_edges(self, msg: DumpEdges, ctx: AppData) -> EdgesSnapshot:
        from .affinity import EdgeSampler
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        address = info.address if info else ""
        sampler = ctx.try_get(EdgeSampler)
        if sampler is None:
            return EdgesSnapshot(address=address)
        sampler.fold(force=True)  # rows reflect traffic up to this scrape
        return EdgesSnapshot(
            address=address,
            rows=sampler.edges(limit=msg.limit),
            sampled=sampler.sampled,
            evictions=sampler.evictions,
            cross_bytes_per_s=round(sampler.cross_bytes_per_s, 3),
        )

    @handler
    async def dump_qos(self, msg: DumpQos, ctx: AppData) -> QosSnapshot:
        from .commands import ServerInfo
        from .qos import QosScheduler

        info = ctx.try_get(ServerInfo)
        address = info.address if info else ""
        qos = ctx.try_get(QosScheduler)
        if qos is None:
            return QosSnapshot(address=address)
        rows = qos.tenant_rows()
        if msg.limit > 0 and len(rows) > msg.limit:
            rows = sorted(rows, key=lambda r: -r[2])[: msg.limit]
        s = qos.stats
        return QosSnapshot(
            address=address,
            enabled=True,
            running=qos.running,
            queued=qos.queued,
            admitted=s.admitted,
            sheds=s.sheds,
            deadline_drops=s.deadline_drops,
            interactive_admitted=s.interactive_admitted,
            interactive_sheds=s.interactive_sheds,
            queue_depths=qos.queue_depths(),
            tenants=rows,
        )

    @handler
    async def admin(self, msg: AdminRequest, ctx: AppData) -> AdminAck:
        sender = ctx.try_get(AdminSender)
        if sender is None:
            return AdminAck(ok=False, detail="no admin queue on this node")
        try:
            kind = AdminCommandKind(msg.kind)
        except ValueError:
            return AdminAck(ok=False, detail=f"unknown admin kind {msg.kind!r}")
        sender.send(
            AdminCommand(kind, msg.type_name, msg.object_id, msg.target)
        )
        return AdminAck(ok=True)


# -- cluster-wide journal queries (the explain plane) ------------------------


async def _node_addresses(nodes: Any) -> list[str]:
    """Accept a MembershipStorage (live view) or an explicit address list."""
    if hasattr(nodes, "active_members"):
        return [m.address for m in await nodes.active_members()]
    return list(nodes)


async def scrape_events(
    client: Any,
    nodes: Any,
    *,
    kinds: Iterable[str] | None = None,
    key: str | None = None,
    since_seq: int = 0,
    limit: int = 512,
) -> list[EventsSnapshot]:
    """One :class:`DumpEvents` round trip per live node; dead nodes skipped.

    ``nodes`` is either a membership storage (scrape whoever is active,
    like ``cluster_scrape``) or an explicit iterable of addresses.
    """
    msg = DumpEvents(
        kinds=list(kinds or []), key=key or "", since_seq=since_seq, limit=limit
    )
    snapshots: list[EventsSnapshot] = []
    for address in await _node_addresses(nodes):
        try:
            snap = await client.send(ADMIN_TYPE, address, msg, returns=EventsSnapshot)
        except Exception:
            continue  # unreachable/draining node: explain over the survivors
        snapshots.append(snap)
    return snapshots


async def scrape_series(
    client: Any,
    nodes: Any,
    *,
    names: Iterable[str] | None = None,
    since_seq: int = 0,
    limit: int = 240,
) -> list[SeriesSnapshot]:
    """One :class:`DumpSeries` round trip per live node; dead nodes skipped.

    Nodes predating the series ring answer the admin envelope with an
    error (unknown message) — they are skipped like unreachable nodes, so
    a mixed-version cluster still yields the survivors' windows.
    """
    msg = DumpSeries(names=list(names or []), since_seq=since_seq, limit=limit)
    snapshots: list[SeriesSnapshot] = []
    for address in await _node_addresses(nodes):
        try:
            snap = await client.send(ADMIN_TYPE, address, msg, returns=SeriesSnapshot)
        except Exception:
            continue
        snapshots.append(snap)
    return snapshots


async def scrape_spans(
    client: Any,
    nodes: Any,
    *,
    trace_id: str = "",
    since_seq: int = 0,
    limit: int = 256,
) -> list[SpansSnapshot]:
    """One :class:`DumpSpans` round trip per live node; dead nodes skipped.

    Nodes predating span retention answer the admin envelope with an
    error (unknown message) — they are skipped like unreachable nodes, so
    a mixed-version cluster still yields the survivors' spans.
    """
    msg = DumpSpans(trace_id=trace_id, since_seq=since_seq, limit=limit)
    snapshots: list[SpansSnapshot] = []
    for address in await _node_addresses(nodes):
        try:
            snap = await client.send(ADMIN_TYPE, address, msg, returns=SpansSnapshot)
        except Exception:
            continue
        snapshots.append(snap)
    return snapshots


async def scrape_edges(
    client: Any,
    nodes: Any,
    *,
    limit: int = 256,
) -> list[EdgesSnapshot]:
    """One :class:`DumpEdges` round trip per live node; dead nodes skipped.

    Nodes predating the edge sampler answer the admin envelope with an
    error (unknown message) — they are skipped like unreachable nodes, so
    a mixed-version cluster still yields the survivors' graphs.
    """
    msg = DumpEdges(limit=limit)
    snapshots: list[EdgesSnapshot] = []
    for address in await _node_addresses(nodes):
        try:
            snap = await client.send(ADMIN_TYPE, address, msg, returns=EdgesSnapshot)
        except Exception:
            continue
        snapshots.append(snap)
    return snapshots


async def scrape_qos(
    client: Any,
    nodes: Any,
    *,
    limit: int = 64,
) -> list[QosSnapshot]:
    """One :class:`DumpQos` round trip per live node; dead nodes skipped.

    Nodes predating the QoS subsystem answer the admin envelope with an
    error (unknown message) — they are skipped like unreachable nodes, so
    a mixed-version cluster still yields the survivors' snapshots.
    """
    msg = DumpQos(limit=limit)
    snapshots: list[QosSnapshot] = []
    for address in await _node_addresses(nodes):
        try:
            snap = await client.send(ADMIN_TYPE, address, msg, returns=QosSnapshot)
        except Exception:
            continue
        snapshots.append(snap)
    return snapshots


async def cluster_edges(
    client: Any,
    nodes: Any,
    *,
    limit: int = 256,
) -> list[list]:
    """The cluster-merged communication graph, heaviest pairs first.

    Each node observes its own side of the traffic (dst-side for local
    sends, sender-side for remote ones), so the merge sums per-node rates
    into cluster-wide edge weights — the rows
    :meth:`JaxObjectPlacement.set_edge_graph` consumes directly.
    """
    from .affinity import merge_edges

    snapshots = await scrape_edges(client, nodes, limit=limit)
    return merge_edges([s.rows for s in snapshots])


async def cluster_events(
    client: Any,
    nodes: Any,
    *,
    kinds: Iterable[str] | None = None,
    key: str | None = None,
    since_seq: int = 0,
    limit: int = 512,
) -> list[JournalEvent]:
    """The merged, causally ordered cluster journal tail."""
    snapshots = await scrape_events(
        client, nodes, kinds=kinds, key=key, since_seq=since_seq, limit=limit
    )
    return merge_events(s.events() for s in snapshots)


async def explain(
    client: Any,
    nodes: Any,
    type_name: str,
    object_id: str,
    *,
    limit: int = 512,
) -> list[JournalEvent]:
    """One actor's causally ordered placement history, cluster-wide.

    Merges every live node's journal rows for subject ``type/id`` into a
    single timeline: activation seat, admission sheds, each migration
    phase (source AND target side), promotion/depose, replica churn —
    whatever the cluster recorded about this actor, in order, each row
    carrying the trace id of the request that drove it.
    """
    return await cluster_events(
        client, nodes, key=subject_key(type_name, object_id), limit=limit
    )


# -- request waterfalls (the trace plane) ------------------------------------


def assemble_waterfall(
    records: Iterable[Any], events: Iterable[JournalEvent] = ()
) -> dict[str, dict]:
    """Group merged span records into per-trace waterfall trees.

    Returns ``{trace_id: {"roots": [hop...], "hops": n, "events": [...]}}``
    where each hop is ``{"record": SpanRecord, "children": [hop...]}``.
    Roots are records whose ``parent_id`` is empty or names a span no ring
    retained (e.g. a caller that never armed its client ring); siblings
    order by wall-clock start, so a redirect hop on node A prints before
    the re-dispatched hop on node B it caused. Journal events carrying the
    trace id ride along, joining placement history to request timing.
    """
    from .spans import merge_spans

    merged = merge_spans([records])
    by_trace: dict[str, list] = {}
    for rec in merged:
        by_trace.setdefault(rec.trace_id, []).append(rec)
    ev_by_trace: dict[str, list[JournalEvent]] = {}
    for ev in events:
        if ev.trace_id:
            ev_by_trace.setdefault(ev.trace_id, []).append(ev)
    out: dict[str, dict] = {}
    for trace_id, recs in by_trace.items():
        span_ids = {r.span_id for r in recs}
        hops = [{"record": r, "children": []} for r in recs]
        by_span = {h["record"].span_id: h for h in hops}
        roots: list[dict] = []
        for h in hops:  # recs are merge-ordered, so children/roots stay sorted
            pid = h["record"].parent_id
            if pid and pid in span_ids and pid != h["record"].span_id:
                by_span[pid]["children"].append(h)
            else:
                roots.append(h)
        out[trace_id] = {
            "roots": roots,
            "hops": len(recs),
            "events": ev_by_trace.get(trace_id, []),
        }
    return out


def _phase_str(attrs: dict) -> str:
    """One-line phase decomposition for a hop (display order = pipeline)."""
    from .spans import PHASE_KEYS

    parts = [
        f"{k[:-3]}={attrs[k]}us" for k in PHASE_KEYS if k in attrs
    ]
    for k in ("send_us", "await_us"):  # client-hop phases
        if k in attrs:
            parts.append(f"{k[:-3]}={attrs[k]}us")
    return " ".join(parts)


def format_waterfall(trace_id: str, tree: dict) -> str:
    """Render one assembled trace as an indented per-hop waterfall."""
    lines = [f"trace {trace_id}  ({tree['hops']} hop(s))"]

    def walk(hop: dict, depth: int) -> None:
        r = hop["record"]
        attrs = r.attrs
        flags = []
        if attrs.get("status"):
            flags.append(f"status={attrs['status']}")
        if attrs.get("redirects"):
            flags.append(f"redirects={attrs['redirects']}")
        if attrs.get("error"):
            flags.append(f"error={attrs['error']}")
        if attrs.get("tail"):
            flags.append("tail")
        lines.append(
            "  " * (depth + 1)
            + f"{r.name} {attrs.get('handler', '?')} @{r.node or 'client'}"
            + f"  {r.duration_us / 1000.0:.2f} ms"
            + (f"  [{' '.join(flags)}]" if flags else "")
        )
        ph = _phase_str(attrs)
        if ph:
            lines.append("  " * (depth + 2) + ph)
        for child in hop["children"]:
            walk(child, depth + 1)

    for root in tree["roots"]:
        walk(root, 0)
    for ev in tree["events"]:
        lines.append("  * " + format_event(ev))
    return "\n".join(lines)


def _span_dict(r: Any) -> dict:
    return {
        "seq": r.seq,
        "trace_id": r.trace_id,
        "span_id": r.span_id,
        "parent_id": r.parent_id,
        "name": r.name,
        "node": r.node,
        "wall_start": r.wall_start,
        "duration_us": r.duration_us,
        "attrs": r.attrs,
    }


# -- operator CLI: python -m rio_tpu.admin {tail|explain|stats|watch|trace} --


def _watch_rows(snapshots: Sequence[SeriesSnapshot]) -> list[dict]:
    """Per-node ``watch`` table rows from DumpSeries scrapes.

    Each row carries the newest value and a trend arrow (over the scraped
    window) for rate/p99/inflight/sheds, plus the node's solver mode and
    active alerts from the snapshot meta. Pure function — the table the
    operator sees is exactly what the CLI test asserts on.
    """
    from .timeseries import series_values, trend_arrow

    rows: list[dict] = []
    for snap in sorted(snapshots, key=lambda s: s.address):
        samples = snap.samples()
        # Per-sample max over the per-handler p99 gauges: the node's worst
        # handler latency, trended like any scalar gauge.
        p99s = [
            max(v for k, v in s.gauges.items() if k.endswith(".p99_ms"))
            for s in samples
            if any(k.endswith(".p99_ms") for k in s.gauges)
        ]
        row: dict = {
            "address": snap.address,
            "samples": len(samples),
            "dropped": snap.dropped,
            "solver_mode": str(snap.meta.get("solver_mode", "") or "-"),
            "alerts": list(snap.meta.get("alerts", ())),
            # Exemplar trace id per firing alert ("rule:gauge" -> trace_id):
            # the slow request that tripped the rule, ready for
            # `admin trace <id>`. Absent on pre-waterfall nodes.
            "alert_traces": dict(snap.meta.get("alert_traces", {})),
            "p99_ms": p99s[-1] if p99s else 0.0,
            "p99_trend": trend_arrow(p99s),
        }
        for col, gauge in (
            ("rate", "rio.load.req_rate"),
            ("inflight", "rio.load.inflight"),
            ("sheds", "rio.load.sheds"),
        ):
            vals = series_values(samples, gauge)
            row[col] = vals[-1] if vals else 0.0
            row[f"{col}_trend"] = trend_arrow(vals)
        rows.append(row)
    return rows


def _format_watch(rows: Sequence[dict]) -> str:
    header = (
        f"{'node':<22} {'rate':>9}  {'p99_ms':>9}  {'inflight':>9} "
        f"{'sheds':>7}  {'mode':<12} alerts"
    )
    lines = [header, "-" * len(header)]
    for r in rows:
        lines.append(
            f"{r['address']:<22} "
            f"{r['rate']:>7.1f} {r['rate_trend']}  "
            f"{r['p99_ms']:>7.2f} {r['p99_trend']}  "
            f"{r['inflight']:>7.0f} {r['inflight_trend']} "
            f"{r['sheds']:>5.0f} {r['sheds_trend']}  "
            f"{r['solver_mode']:<12} "
            + (
                ",".join(
                    a
                    + (
                        f"[{r['alert_traces'][a][:8]}]"
                        if r.get("alert_traces", {}).get(a)
                        else ""
                    )
                    for a in r["alerts"]
                )
                or "-"
            )
        )
    return "\n".join(lines)


def _event_dict(ev: JournalEvent) -> dict:
    return {
        "seq": ev.seq,
        "wall_ts": ev.wall_ts,
        "node": ev.node,
        "epoch": ev.epoch,
        "kind": ev.kind,
        "key": ev.key,
        "attrs": ev.attrs,
        "trace_id": ev.trace_id,
    }


async def _cli_cluster(args: Any):
    """Resolve (client, nodes, cleanup) for the CLI: --nodes or --demo."""
    from .client import Client
    from .cluster.storage import LocalStorage, Member

    if args.demo and getattr(args, "cmd", "") == "scale":
        # The scale demo needs an autoscale-enabled cluster: one supervisor
        # with an in-process provisioner, pushed over its high band until
        # the controller has a real decision to show.
        import asyncio
        import time as _time

        from .autoscale import AutoscaleConfig, ScalePolicy
        from .autoscale.provision import InProcessProvisioner
        from .cluster.membership_protocol import LocalClusterProvider
        from .object_placement import LocalObjectPlacement
        from .server import Server
        from .utils.routing_live import Echo, EchoActor, build_echo_registry

        members = LocalStorage()
        placement = LocalObjectPlacement()
        provisioner = InProcessProvisioner(
            members,
            placement,
            registry_builder=build_echo_registry,
            server_kwargs={"load_interval": 0.1},
        )
        policy = ScalePolicy(
            min_nodes=1, max_nodes=2, high_pressure=50.0, low_pressure=8.0,
            sustain=2, inflight_weight=0.0, lag_weight=0.0, rate_weight=1.0,
            shed_weight=0.0, out_cooldown_s=5.0,
        )
        supervisor = Server(
            address="127.0.0.1:0",
            registry=build_echo_registry(),
            cluster_provider=LocalClusterProvider(members),
            object_placement_provider=placement,
            load_interval=0.1,
            autoscale_config=AutoscaleConfig(
                provisioner=provisioner, policy=policy, interval=0.1
            ),
        )
        await supervisor.prepare()
        await supervisor.bind()
        serve = asyncio.ensure_future(supervisor.run())
        client = Client(members)
        deadline = _time.monotonic() + 30.0
        i = 0
        while (
            supervisor.autoscale.scale_outs < 1
            and _time.monotonic() < deadline
        ):
            i += 1
            try:
                await client.send(
                    EchoActor, f"w{i % 8}", Echo(value=i), returns=Echo
                )
            except Exception:  # noqa: BLE001 — demo load, keep pushing
                await asyncio.sleep(0.01)

        async def cleanup() -> None:
            client.close()
            serve.cancel()
            await asyncio.gather(serve, return_exceptions=True)
            await provisioner.close()

        return client, members, cleanup

    if args.demo and getattr(args, "cmd", "") == "qos":
        # The qos demo needs a scheduler-enabled cluster: a weighted
        # interactive tenant plus a rate-limited bulk tenant driven past
        # its admission bucket, so the scrape has sheds and RED rows to
        # render.
        import asyncio

        from .errors import ClientError
        from .qos import QosConfig
        from .utils.routing_live import Echo, EchoActor, boot_echo_cluster

        members, placement, tasks, servers = await boot_echo_cluster(
            2,
            server_kwargs=dict(
                qos_config=QosConfig(
                    tenant_weights={"frontend": 4.0},
                    tenant_rates={"bulk": (200.0, 8.0)},
                )
            ),
        )
        client = Client(members)
        for i in range(40):
            try:
                # A short budget caps each shed's retry ladder so the
                # flood finishes promptly; spent budgets surface here as
                # DeadlineExceeded and simply count.
                await client.send(
                    EchoActor, f"b{i % 8}", Echo(value=i), returns=Echo,
                    tenant="bulk", deadline_ms=250,
                )
            except ClientError:
                pass
        for i in range(10):
            await client.send(
                EchoActor, f"f{i % 4}", Echo(value=i), returns=Echo,
                tenant="frontend", priority=2, deadline_ms=2000,
            )

        async def cleanup() -> None:
            client.close()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

        return client, members, cleanup

    if args.demo:
        import asyncio

        from . import tracing
        from .utils.routing_live import Echo, EchoActor, boot_echo_cluster
        from .registry import type_id

        tracing.set_sample_rate(1.0)  # demo journal rows carry trace ids
        if getattr(args, "cmd", "") == "trace":
            # Record the demo driver's own client hops so the waterfall
            # starts at the caller (send/await + redirect follows).
            from .spans import arm_client_ring

            arm_client_ring()
        members, placement, tasks, servers = await boot_echo_cluster(
            2,
            # Aggressive sampling so a one-shot demo scrape has a window.
            server_kwargs=dict(load_interval=0.05, timeseries_interval=0.05),
        )
        client = Client(members)
        tname = type_id(EchoActor)
        for i in range(20):
            await client.send(EchoActor, f"w{i % 4}", Echo(value=i), returns=Echo)
        # Drive one real migration so the tail shows the full phase chain.
        from .registry import ObjectId

        owner = await placement.lookup(ObjectId(tname, "w0"))
        target = next(s.local_address for s in servers if s.local_address != owner)
        if owner:
            await client.send(
                ADMIN_TYPE,
                owner,
                AdminRequest(
                    kind="migrate_object",
                    type_name=tname,
                    object_id="w0",
                    target=target,
                ),
                returns=AdminAck,
            )
            await asyncio.sleep(0.4)  # let the queued migration run
            await client.send(EchoActor, "w0", Echo(value=99), returns=Echo)
        if getattr(args, "cmd", "") == "watch":
            await asyncio.sleep(0.5)  # several sampler ticks → a trend window
        if not getattr(args, "subject", None):
            args.subject = (tname, "w0")
        if getattr(args, "cmd", "") == "trace" and not getattr(args, "trace_id", ""):
            # No trace id given: pick a demo request that crossed nodes
            # (a redirect follow) so the waterfall shows several hops.
            from .spans import client_ring

            recs = client_ring().spans()
            pick = next(
                (r for r in recs if r.attrs.get("redirects")),
                recs[-1] if recs else None,
            )
            args.trace_id = pick.trace_id if pick else ""

        async def cleanup() -> None:
            client.close()
            tracing.set_sample_rate(0.0)
            if getattr(args, "cmd", "") == "trace":
                from .spans import disarm_client_ring

                disarm_client_ring()
            for t in tasks:
                t.cancel()
            import asyncio

            await asyncio.gather(*tasks, return_exceptions=True)

        return client, members, cleanup

    members = LocalStorage()
    for address in (args.nodes or "").split(","):
        if address.strip():
            await members.push(Member.from_address(address.strip(), active=True))

    client = Client(members)

    async def cleanup() -> None:
        client.close()

    return client, members, cleanup


async def _cli_main(argv: Sequence[str] | None = None) -> int:
    """Operator CLI. Exit codes (scriptable, see the CLI test):

    * 0 — scrape succeeded (at least one node answered).
    * 1 — empty scrape: no node in the target set answered (unreachable /
      pre-series cluster).
    * 2 — usage (missing explain subject; argparse errors also exit 2).
    """
    import argparse
    import asyncio
    import json
    import time

    parser = argparse.ArgumentParser(
        prog="python -m rio_tpu.admin",
        description="Operator view of the control-plane flight recorder.",
    )
    parser.add_argument(
        "--nodes", default="", help="comma-separated node addresses (host:port,...)"
    )
    parser.add_argument(
        "--demo",
        action="store_true",
        help="boot a 2-node in-process cluster, drive traffic + one migration, "
        "then run the subcommand against it",
    )
    parser.add_argument(
        "--json",
        action="store_true",
        help="machine-readable output (one JSON document on stdout)",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    def _common(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
        # The shared flags are accepted on either side of the subcommand
        # (`--demo tail` and `watch --demo --once` both work); SUPPRESS
        # defaults keep a pre-subcommand value from being clobbered.
        p.add_argument("--nodes", default=argparse.SUPPRESS)
        p.add_argument("--demo", action="store_true", default=argparse.SUPPRESS)
        p.add_argument("--json", action="store_true", default=argparse.SUPPRESS)
        return p

    tail = _common(sub.add_parser("tail", help="merged cluster journal tail"))
    tail.add_argument("--kind", action="append", default=[], help="filter by kind")
    tail.add_argument("--key", default="", help="filter by subject key (type/id)")
    tail.add_argument("--since-seq", type=int, default=0)
    tail.add_argument("--limit", type=int, default=64)

    exp = _common(
        sub.add_parser("explain", help="one actor's causal placement history")
    )
    exp.add_argument("type_name", nargs="?", default="")
    exp.add_argument("object_id", nargs="?", default="")

    _common(
        sub.add_parser(
            "stats", help="per-node gauge snapshot (journal counters incl.)"
        )
    )

    watch = _common(
        sub.add_parser(
            "watch", help="live per-node trend table over the gauge time-series"
        )
    )
    watch.add_argument(
        "--once", action="store_true", help="print one table and exit"
    )
    watch.add_argument(
        "--interval", type=float, default=2.0, help="refresh period (seconds)"
    )
    watch.add_argument(
        "--window", type=int, default=64, help="samples scraped per node"
    )

    edges_p = _common(
        sub.add_parser(
            "edges",
            help="top chatty actor pairs from the communication-edge samplers",
        )
    )
    edges_p.add_argument(
        "--limit", type=int, default=16, help="pairs shown (heaviest first)"
    )

    trace_p = _common(
        sub.add_parser(
            "trace", help="assemble one request's cross-node waterfall"
        )
    )
    trace_p.add_argument(
        "trace_id", nargs="?", default="", help="128-bit hex trace id "
        "(empty = every retained trace; demo picks a redirect-follow)"
    )
    trace_p.add_argument(
        "--limit", type=int, default=256, help="spans scraped per node"
    )

    qos_p = _common(
        sub.add_parser(
            "qos",
            help="request-QoS scheduler state: queue depths, per-tenant "
            "RED rows, shed and deadline-drop counters",
        )
    )
    qos_p.add_argument(
        "--limit", type=int, default=64,
        help="per-(tenant, class) rows shown per node (busiest first)",
    )

    scale_p = _common(
        sub.add_parser(
            "scale",
            help="autoscale controller state: policy bands, cooldowns, "
            "recent decisions",
        )
    )
    scale_p.add_argument(
        "--limit", type=int, default=16, help="decision rows shown (newest)"
    )

    args = parser.parse_args(argv)
    args.subject = (
        (args.type_name, args.object_id)
        if args.cmd == "explain" and args.type_name and args.object_id
        else None
    )
    if not args.demo and not args.nodes:
        parser.error("--nodes is required without --demo")

    client, nodes, cleanup = await _cli_cluster(args)
    try:
        if args.cmd == "tail":
            snapshots = await scrape_events(
                client,
                nodes,
                kinds=args.kind or None,
                key=args.key or None,
                since_seq=args.since_seq,
                limit=args.limit,
            )
            events = merge_events(s.events() for s in snapshots)
            if args.json:
                print(json.dumps([_event_dict(e) for e in events]))
            else:
                for ev in events:
                    print(format_event(ev))
                print(f"[tail] {len(events)} events")
            return 0 if snapshots else 1
        if args.cmd == "explain":
            if not args.subject:
                print("explain: missing TYPE ID (demo picks its migrated actor)")
                return 2
            tname, oid = args.subject
            snapshots = await scrape_events(
                client, nodes, key=subject_key(tname, oid), limit=512
            )
            events = merge_events(s.events() for s in snapshots)
            traces = {e.trace_id for e in events if e.trace_id}
            if args.json:
                print(json.dumps({
                    "subject": subject_key(tname, oid),
                    "events": [_event_dict(e) for e in events],
                    "traces": sorted(traces),
                }))
            else:
                for ev in events:
                    print(format_event(ev))
                print(
                    f"[explain] {subject_key(tname, oid)}: {len(events)} events, "
                    f"{len(traces)} linked trace(s)"
                )
            return 0 if snapshots else 1
        if args.cmd == "stats":
            reached = 0
            out: dict[str, Any] = {}
            for address in await _node_addresses(nodes):
                try:
                    snap = await client.send(
                        ADMIN_TYPE, address, DumpStats(), returns=StatsSnapshot
                    )
                except Exception as e:
                    if not args.json:
                        print(f"{address}: unreachable ({e.__class__.__name__})")
                    continue
                reached += 1
                if args.json:
                    out[snap.address] = {
                        "gauges": snap.gauges,
                        "histograms": len(snap.histograms),
                    }
                    continue
                journal = {
                    k: v for k, v in snap.gauges.items() if k.startswith("rio.journal.")
                }
                print(
                    f"{snap.address}: {len(snap.gauges)} gauges, "
                    f"{len(snap.histograms)} histograms, journal="
                    + (
                        " ".join(f"{k.split('.')[-1]}={v:g}" for k, v in sorted(journal.items()))
                        or "off"
                    )
                )
            if args.json:
                print(json.dumps(out))
            return 0 if reached else 1
        if args.cmd == "edges":
            from .affinity import merge_edges

            snapshots = await scrape_edges(
                client, nodes, limit=max(args.limit * 4, 64)
            )
            merged = merge_edges([s.rows for s in snapshots])
            top = merged[: args.limit]
            total_bps = sum(r[2] for r in merged)
            local_bps = sum(r[2] * r[4] for r in merged)
            cross_bps = total_bps - local_bps
            if args.json:
                print(json.dumps({
                    "nodes": {
                        s.address: {
                            "sampled": s.sampled,
                            "evictions": s.evictions,
                            "cross_bytes_per_s": s.cross_bytes_per_s,
                        }
                        for s in snapshots
                    },
                    "edges": [
                        {
                            "src": r[0],
                            "dst": r[1],
                            "bytes_per_s": r[2],
                            "calls_per_s": r[3],
                            "local_frac": r[4],
                        }
                        for r in top
                    ],
                    "total_bytes_per_s": total_bps,
                    "local_bytes_per_s": local_bps,
                    "cross_bytes_per_s": cross_bps,
                }))
            else:
                header = (
                    f"{'src':<28} {'dst':<28} {'bytes/s':>12} "
                    f"{'calls/s':>10} {'local%':>7}"
                )
                print(header)
                print("-" * len(header))
                for r in top:
                    print(
                        f"{r[0]:<28} {r[1]:<28} {r[2]:>12.0f} "
                        f"{r[3]:>10.1f} {r[4] * 100:>6.1f}%"
                    )
                print(
                    f"[edges] {len(merged)} pair(s) from {len(snapshots)} "
                    f"node(s); bytes/s local={local_bps:.0f} "
                    f"cross={cross_bps:.0f}"
                )
            return 0 if snapshots else 1
        if args.cmd == "trace":
            from .spans import client_ring

            snapshots = await scrape_spans(
                client, nodes, trace_id=args.trace_id, limit=args.limit
            )
            records = [r for s in snapshots for r in s.spans()]
            ring = client_ring()
            if ring is not None:
                # Merge THIS process's client hops: the waterfall roots at
                # the caller when it armed retention before sending.
                records.extend(ring.spans(trace_id=args.trace_id or None))
            if args.trace_id:
                records = [r for r in records if r.trace_id == args.trace_id]
            ev_snaps = await scrape_events(client, nodes, limit=512)
            events = [
                e
                for e in merge_events(s.events() for s in ev_snaps)
                if e.trace_id
                and (not args.trace_id or e.trace_id == args.trace_id)
            ]
            trees = assemble_waterfall(records, events)
            if args.json:
                doc: dict[str, Any] = {}
                for tid, tree in trees.items():
                    flat: list[dict] = []

                    def _flatten(hop: dict, depth: int) -> None:
                        d = _span_dict(hop["record"])
                        d["depth"] = depth
                        flat.append(d)
                        for c in hop["children"]:
                            _flatten(c, depth + 1)

                    for root in tree["roots"]:
                        _flatten(root, 0)
                    doc[tid] = {
                        "hops": tree["hops"],
                        "spans": flat,
                        "events": [_event_dict(e) for e in tree["events"]],
                    }
                print(json.dumps(doc))
            else:
                for tid, tree in trees.items():
                    print(format_waterfall(tid, tree))
                print(f"[trace] {len(trees)} trace(s), {len(records)} span(s)")
            return 0 if (snapshots or records) else 1
        if args.cmd == "qos":
            snapshots = await scrape_qos(client, nodes, limit=args.limit)
            if args.json:
                print(json.dumps({
                    s.address: {
                        "enabled": s.enabled,
                        "running": s.running,
                        "queued": s.queued,
                        "admitted": s.admitted,
                        "sheds": s.sheds,
                        "deadline_drops": s.deadline_drops,
                        "interactive_admitted": s.interactive_admitted,
                        "interactive_sheds": s.interactive_sheds,
                        "queue_depths": s.queue_depths,
                        "tenants": [
                            {
                                "tenant": r[0],
                                "class": r[1],
                                "requests": r[2],
                                "errors": r[3],
                                "avg_ms": r[4],
                                "avg_queue_ms": r[5],
                                "sheds": r[6],
                                "deadline_drops": r[7],
                            }
                            for r in s.tenants
                        ],
                    }
                    for s in sorted(snapshots, key=lambda s: s.address)
                }))
                return 0 if snapshots else 1
            header = (
                f"{'tenant':<14} {'class':<6} {'reqs':>7} {'errs':>6} "
                f"{'avg_ms':>8} {'queue_ms':>9} {'sheds':>6} {'ddrops':>7}"
            )
            for snap in sorted(snapshots, key=lambda s: s.address):
                if not snap.enabled:
                    print(f"{snap.address}: qos off")
                    continue
                depths = (
                    " ".join(f"{k}={v}" for k, v in sorted(snap.queue_depths.items()))
                    or "-"
                )
                print(
                    f"{snap.address}: admitted={snap.admitted} "
                    f"sheds={snap.sheds} deadline_drops={snap.deadline_drops} "
                    f"running={snap.running} queued={snap.queued} [{depths}]"
                )
                if snap.tenants:
                    print(header)
                    print("-" * len(header))
                    for r in snap.tenants:
                        print(
                            f"{(r[0] or 'default'):<14} {r[1]:<6} {r[2]:>7} "
                            f"{r[3]:>6} {r[4]:>8.2f} {r[5]:>9.2f} "
                            f"{r[6]:>6} {r[7]:>7}"
                        )
            print(f"[qos] {len(snapshots)} node(s)")
            return 0 if snapshots else 1
        if args.cmd == "scale":
            from .autoscale import (
                AUTOSCALE_ID,
                AUTOSCALE_TYPE,
                ScaleSnapshot,
                ScaleStatus,
            )

            try:
                snap = await client.send(
                    AUTOSCALE_TYPE,
                    AUTOSCALE_ID,
                    ScaleStatus(limit=args.limit),
                    returns=ScaleSnapshot,
                )
            except Exception as e:
                print(f"scale: controller unreachable ({e.__class__.__name__})")
                return 1
            if not snap.address:
                # The singleton answered from a node with autoscaling off —
                # no runtime means no policy state worth rendering.
                print("scale: no autoscale runtime on the controller's node")
                return 1
            if args.json:
                print(json.dumps({
                    "controller": snap.address,
                    "pressure": snap.pressure,
                    "nodes": snap.nodes,
                    "over_streak": snap.over_streak,
                    "under_streak": snap.under_streak,
                    "cooldown_s": snap.cooldown_s,
                    "pending": snap.pending,
                    "scale_outs": snap.scale_outs,
                    "scale_ins": snap.scale_ins,
                    "ticks": snap.ticks,
                    "alerts": snap.alerts,
                    "policy": snap.policy,
                    "decisions": [
                        {
                            "wall_ts": d[0],
                            "action": d[1],
                            "node": d[2],
                            "rule": d[3],
                            "pressure": d[4],
                            "nodes": d[5],
                            "detail": d[6],
                        }
                        for d in snap.decisions
                    ],
                }))
                return 0
            pol = snap.policy
            print(
                f"controller {snap.address}: pressure={snap.pressure:.2f} "
                f"nodes={snap.nodes} over={snap.over_streak} "
                f"under={snap.under_streak} ticks={snap.ticks}"
            )
            print(
                f"policy: band=[{pol.get('low_pressure', 0):g}, "
                f"{pol.get('high_pressure', 0):g}] "
                f"sustain={pol.get('sustain', 0):g} "
                f"nodes=[{pol.get('min_nodes', 0):g}, "
                f"{pol.get('max_nodes', 0):g}] "
                f"cooldowns out/in={pol.get('out_cooldown_s', 0):g}/"
                f"{pol.get('in_cooldown_s', 0):g}s "
                f"drain_timeout={pol.get('drain_timeout_s', 0):g}s"
            )
            print(
                f"now: cooldown={snap.cooldown_s:.1f}s "
                f"pending={snap.pending or '-'} "
                f"alerts={','.join(snap.alerts) or '-'} "
                f"outs={snap.scale_outs} ins={snap.scale_ins}"
            )
            if snap.decisions:
                print("decisions (newest last):")
                for d in snap.decisions:
                    ts = time.strftime(
                        "%H:%M:%S", time.localtime(float(d[0]))
                    )
                    print(
                        f"  {ts} {d[1]:<10} {d[2]:<22} rule={d[3]} "
                        f"pressure={d[4]:.2f} nodes={d[5]}"
                        + (f" ({d[6]})" if d[6] else "")
                    )
            print(
                f"[scale] controller={snap.address} "
                f"{len(snap.decisions)} decision(s)"
            )
            return 0
        # watch: the trend table (one shot with --once/--json, else looped).
        while True:
            snapshots = await scrape_series(client, nodes, limit=args.window)
            rows = _watch_rows(snapshots)
            if args.json:
                print(json.dumps(rows))
            else:
                print(_format_watch(rows))
            if args.once or args.json or not snapshots:
                return 0 if snapshots else 1
            await asyncio.sleep(max(0.1, args.interval))
            print()
    finally:
        await cleanup()


if __name__ == "__main__":  # pragma: no cover - exercised via CLI smoke test
    import asyncio as _asyncio
    import sys as _sys

    _sys.exit(_asyncio.run(_cli_main()))
