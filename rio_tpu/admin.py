"""Node-scoped admin/observability actor: the wire face of the ops plane.

Two services behind one ``rio.Admin`` actor per node (``__node_scoped__``,
id = the node's address, routed without the directory exactly like the
migration control plane):

* :class:`DumpStats` → :class:`StatsSnapshot` — the cluster scrape. One
  round trip returns the node's full :func:`rio_tpu.otel.server_gauges`
  snapshot plus its raw RED histogram rows
  (:meth:`rio_tpu.metrics.MetricsRegistry.snapshot_rows`), which are
  mergeable across nodes — a scraper walks the membership view, asks every
  node, and :func:`rio_tpu.metrics.merge_rows` yields cluster-wide
  p50/p99 (see ``examples/observability.py``).
* :class:`AdminRequest` → :class:`AdminAck` — a remote bridge onto the
  in-process :class:`~rio_tpu.commands.AdminSender` queue (drain this
  node, migrate an object, shut an object down) so ops tooling needs only
  a :class:`~rio_tpu.client.Client`.
* :class:`DumpEvents` → :class:`EventsSnapshot` — the control-plane
  flight recorder scrape (``rio_tpu/journal.py``): a filtered tail of the
  node's journal ring as wire rows, resumable by ``since_seq``.
  :func:`explain` walks every live node and merges the per-node streams
  into one causally ordered placement history for a single actor.

The gauge/histogram sources are injected at ``Server.bind()`` as a
:class:`StatsSource` — the actor itself stays free of server imports.

Operator CLI (see ``_cli_main``)::

    python -m rio_tpu.admin tail    --nodes host:p,host:p [--kind K] [--key K]
    python -m rio_tpu.admin explain --nodes host:p,host:p TYPE ID
    python -m rio_tpu.admin stats   --nodes host:p,host:p
    python -m rio_tpu.admin --demo {tail|explain|stats}   # in-process 2-node demo
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

from .app_data import AppData
from .commands import AdminCommand, AdminCommandKind, AdminSender
from .journal import Journal, JournalEvent, format_event, merge_events, subject_key
from .registry import handler, message, type_name
from .service_object import ServiceObject

#: Wire type-name of the node-scoped admin actor.
ADMIN_TYPE = "rio.Admin"


@message(name="rio.DumpStats")
@dataclass
class DumpStats:
    """Ask a node for its gauge + RED-histogram snapshot."""

    # Histograms dominate the payload on wide deployments; a pure-gauge
    # scrape can skip them.
    include_histograms: bool = True


@message(name="rio.StatsSnapshot")
@dataclass
class StatsSnapshot:
    """One node's observability snapshot (mergeable across nodes)."""

    address: str = ""
    gauges: dict[str, float] = field(default_factory=dict)
    # rio_tpu.metrics wire rows: [handler_type, message_type, count,
    # error_count, errors{kind:int}, buckets[], sum_s, max_s,
    # exemplar_trace, exemplar_s] — merge with metrics.merge_rows.
    histograms: list = field(default_factory=list)


@message(name="rio.DumpEvents")
@dataclass
class DumpEvents:
    """Ask a node for a filtered tail of its control-plane journal.

    Empty ``kinds``/``key`` mean "no filter"; ``since_seq`` resumes a tail
    (only events with ``seq > since_seq`` return); ``limit`` bounds the
    response to the NEWEST matches (0 = journal capacity).
    """

    kinds: list = field(default_factory=list)  # journal kind strings
    key: str = ""  # exact subject match, e.g. "Worker/w3"
    since_seq: int = 0
    limit: int = 512


@message(name="rio.EventsSnapshot")
@dataclass
class EventsSnapshot:
    """One node's journal tail (mergeable across nodes: ``merge_events``)."""

    address: str = ""
    node_seq: int = 0  # the node's latest journal seq (tail resume point)
    dropped: int = 0  # ring-overflow drop counter at scrape time
    # JournalEvent wire rows: [seq, wall_ts, mono_ts, node, epoch, kind,
    # key, attrs, trace_id] — decode with JournalEvent.from_row.
    rows: list = field(default_factory=list)

    def events(self) -> list[JournalEvent]:
        return [JournalEvent.from_row(r) for r in self.rows]


@message(name="rio.AdminRequest")
@dataclass
class AdminRequest:
    """Enqueue one :class:`~rio_tpu.commands.AdminCommand` on the node."""

    kind: str = ""  # an AdminCommandKind value, e.g. "drain_server"
    type_name: str = ""
    object_id: str = ""
    target: str = ""


@message(name="rio.AdminAck")
@dataclass
class AdminAck:
    ok: bool = False
    detail: str = ""


@dataclass
class StatsSource:
    """AppData-injectable snapshot providers (wired at ``Server.bind()``).

    ``gauges`` returns the :func:`~rio_tpu.otel.server_gauges` dict;
    ``histogram_rows`` returns the mergeable RED rows (empty when metrics
    are disabled). A dataclass wrapper — not bare callables — so AppData's
    type-keyed map can hold it.
    """

    gauges: Callable[[], dict[str, float]]
    histogram_rows: Callable[[], list[Any]]


@type_name(ADMIN_TYPE)
class AdminControl(ServiceObject):
    """Node-scoped observability/ops endpoint (one per server; id = address)."""

    __node_scoped__ = True

    @handler
    async def dump_stats(self, msg: DumpStats, ctx: AppData) -> StatsSnapshot:
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        source = ctx.try_get(StatsSource)
        if source is None:
            return StatsSnapshot(address=info.address if info else "")
        rows = source.histogram_rows() if msg.include_histograms else []
        return StatsSnapshot(
            address=info.address if info else "",
            gauges=source.gauges(),
            histograms=rows,
        )

    @handler
    async def dump_events(self, msg: DumpEvents, ctx: AppData) -> EventsSnapshot:
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        address = info.address if info else ""
        journal = ctx.try_get(Journal)
        if journal is None:
            return EventsSnapshot(address=address)
        events = journal.events(
            kinds=msg.kinds or None,
            key=msg.key or None,
            since_seq=msg.since_seq,
            limit=msg.limit if msg.limit > 0 else None,
        )
        return EventsSnapshot(
            address=address,
            node_seq=journal.recorded,
            dropped=journal.dropped,
            rows=[e.to_row() for e in events],
        )

    @handler
    async def admin(self, msg: AdminRequest, ctx: AppData) -> AdminAck:
        sender = ctx.try_get(AdminSender)
        if sender is None:
            return AdminAck(ok=False, detail="no admin queue on this node")
        try:
            kind = AdminCommandKind(msg.kind)
        except ValueError:
            return AdminAck(ok=False, detail=f"unknown admin kind {msg.kind!r}")
        sender.send(
            AdminCommand(kind, msg.type_name, msg.object_id, msg.target)
        )
        return AdminAck(ok=True)


# -- cluster-wide journal queries (the explain plane) ------------------------


async def _node_addresses(nodes: Any) -> list[str]:
    """Accept a MembershipStorage (live view) or an explicit address list."""
    if hasattr(nodes, "active_members"):
        return [m.address for m in await nodes.active_members()]
    return list(nodes)


async def scrape_events(
    client: Any,
    nodes: Any,
    *,
    kinds: Iterable[str] | None = None,
    key: str | None = None,
    since_seq: int = 0,
    limit: int = 512,
) -> list[EventsSnapshot]:
    """One :class:`DumpEvents` round trip per live node; dead nodes skipped.

    ``nodes`` is either a membership storage (scrape whoever is active,
    like ``cluster_scrape``) or an explicit iterable of addresses.
    """
    msg = DumpEvents(
        kinds=list(kinds or []), key=key or "", since_seq=since_seq, limit=limit
    )
    snapshots: list[EventsSnapshot] = []
    for address in await _node_addresses(nodes):
        try:
            snap = await client.send(ADMIN_TYPE, address, msg, returns=EventsSnapshot)
        except Exception:
            continue  # unreachable/draining node: explain over the survivors
        snapshots.append(snap)
    return snapshots


async def cluster_events(
    client: Any,
    nodes: Any,
    *,
    kinds: Iterable[str] | None = None,
    key: str | None = None,
    since_seq: int = 0,
    limit: int = 512,
) -> list[JournalEvent]:
    """The merged, causally ordered cluster journal tail."""
    snapshots = await scrape_events(
        client, nodes, kinds=kinds, key=key, since_seq=since_seq, limit=limit
    )
    return merge_events(s.events() for s in snapshots)


async def explain(
    client: Any,
    nodes: Any,
    type_name: str,
    object_id: str,
    *,
    limit: int = 512,
) -> list[JournalEvent]:
    """One actor's causally ordered placement history, cluster-wide.

    Merges every live node's journal rows for subject ``type/id`` into a
    single timeline: activation seat, admission sheds, each migration
    phase (source AND target side), promotion/depose, replica churn —
    whatever the cluster recorded about this actor, in order, each row
    carrying the trace id of the request that drove it.
    """
    return await cluster_events(
        client, nodes, key=subject_key(type_name, object_id), limit=limit
    )


# -- operator CLI: python -m rio_tpu.admin {tail|explain|stats} --------------


async def _cli_cluster(args: Any):
    """Resolve (client, nodes, cleanup) for the CLI: --nodes or --demo."""
    from .client import Client
    from .cluster.storage import LocalStorage, Member

    if args.demo:
        import asyncio

        from . import tracing
        from .utils.routing_live import Echo, EchoActor, boot_echo_cluster
        from .registry import type_id

        tracing.set_sample_rate(1.0)  # demo journal rows carry trace ids
        members, placement, tasks, servers = await boot_echo_cluster(2)
        client = Client(members)
        tname = type_id(EchoActor)
        for i in range(20):
            await client.send(EchoActor, f"w{i % 4}", Echo(value=i), returns=Echo)
        # Drive one real migration so the tail shows the full phase chain.
        from .registry import ObjectId

        owner = await placement.lookup(ObjectId(tname, "w0"))
        target = next(s.local_address for s in servers if s.local_address != owner)
        if owner:
            await client.send(
                ADMIN_TYPE,
                owner,
                AdminRequest(
                    kind="migrate_object",
                    type_name=tname,
                    object_id="w0",
                    target=target,
                ),
                returns=AdminAck,
            )
            await asyncio.sleep(0.4)  # let the queued migration run
            await client.send(EchoActor, "w0", Echo(value=99), returns=Echo)
        if not getattr(args, "subject", None):
            args.subject = (tname, "w0")

        async def cleanup() -> None:
            client.close()
            tracing.set_sample_rate(0.0)
            for t in tasks:
                t.cancel()
            import asyncio

            await asyncio.gather(*tasks, return_exceptions=True)

        return client, members, cleanup

    members = LocalStorage()
    for address in (args.nodes or "").split(","):
        if address.strip():
            await members.push(Member.from_address(address.strip(), active=True))

    client = Client(members)

    async def cleanup() -> None:
        client.close()

    return client, members, cleanup


async def _cli_main(argv: Sequence[str] | None = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m rio_tpu.admin",
        description="Operator view of the control-plane flight recorder.",
    )
    parser.add_argument(
        "--nodes", default="", help="comma-separated node addresses (host:port,...)"
    )
    parser.add_argument(
        "--demo",
        action="store_true",
        help="boot a 2-node in-process cluster, drive traffic + one migration, "
        "then run the subcommand against it",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    tail = sub.add_parser("tail", help="merged cluster journal tail")
    tail.add_argument("--kind", action="append", default=[], help="filter by kind")
    tail.add_argument("--key", default="", help="filter by subject key (type/id)")
    tail.add_argument("--since-seq", type=int, default=0)
    tail.add_argument("--limit", type=int, default=64)

    exp = sub.add_parser("explain", help="one actor's causal placement history")
    exp.add_argument("type_name", nargs="?", default="")
    exp.add_argument("object_id", nargs="?", default="")

    sub.add_parser("stats", help="per-node gauge snapshot (journal counters incl.)")

    args = parser.parse_args(argv)
    args.subject = (
        (args.type_name, args.object_id)
        if args.cmd == "explain" and args.type_name and args.object_id
        else None
    )
    if not args.demo and not args.nodes:
        parser.error("--nodes is required without --demo")

    client, nodes, cleanup = await _cli_cluster(args)
    try:
        if args.cmd == "tail":
            events = await cluster_events(
                client,
                nodes,
                kinds=args.kind or None,
                key=args.key or None,
                since_seq=args.since_seq,
                limit=args.limit,
            )
            for ev in events:
                print(format_event(ev))
            print(f"[tail] {len(events)} events")
        elif args.cmd == "explain":
            if not args.subject:
                print("explain: missing TYPE ID (demo picks its migrated actor)")
                return 2
            tname, oid = args.subject
            events = await explain(client, nodes, tname, oid)
            traces = {e.trace_id for e in events if e.trace_id}
            for ev in events:
                print(format_event(ev))
            print(
                f"[explain] {subject_key(tname, oid)}: {len(events)} events, "
                f"{len(traces)} linked trace(s)"
            )
        else:  # stats
            for address in await _node_addresses(nodes):
                try:
                    snap = await client.send(
                        ADMIN_TYPE, address, DumpStats(), returns=StatsSnapshot
                    )
                except Exception as e:
                    print(f"{address}: unreachable ({e.__class__.__name__})")
                    continue
                journal = {
                    k: v for k, v in snap.gauges.items() if k.startswith("rio.journal.")
                }
                print(
                    f"{snap.address}: {len(snap.gauges)} gauges, "
                    f"{len(snap.histograms)} histograms, journal="
                    + (
                        " ".join(f"{k.split('.')[-1]}={v:g}" for k, v in sorted(journal.items()))
                        or "off"
                    )
                )
    finally:
        await cleanup()
    return 0


if __name__ == "__main__":  # pragma: no cover - exercised via CLI smoke test
    import asyncio as _asyncio
    import sys as _sys

    _sys.exit(_asyncio.run(_cli_main()))
