"""Node-scoped admin/observability actor: the wire face of the ops plane.

Two services behind one ``rio.Admin`` actor per node (``__node_scoped__``,
id = the node's address, routed without the directory exactly like the
migration control plane):

* :class:`DumpStats` → :class:`StatsSnapshot` — the cluster scrape. One
  round trip returns the node's full :func:`rio_tpu.otel.server_gauges`
  snapshot plus its raw RED histogram rows
  (:meth:`rio_tpu.metrics.MetricsRegistry.snapshot_rows`), which are
  mergeable across nodes — a scraper walks the membership view, asks every
  node, and :func:`rio_tpu.metrics.merge_rows` yields cluster-wide
  p50/p99 (see ``examples/observability.py``).
* :class:`AdminRequest` → :class:`AdminAck` — a remote bridge onto the
  in-process :class:`~rio_tpu.commands.AdminSender` queue (drain this
  node, migrate an object, shut an object down) so ops tooling needs only
  a :class:`~rio_tpu.client.Client`.

The gauge/histogram sources are injected at ``Server.bind()`` as a
:class:`StatsSource` — the actor itself stays free of server imports.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from .app_data import AppData
from .commands import AdminCommand, AdminCommandKind, AdminSender
from .registry import handler, message, type_name
from .service_object import ServiceObject

#: Wire type-name of the node-scoped admin actor.
ADMIN_TYPE = "rio.Admin"


@message(name="rio.DumpStats")
@dataclass
class DumpStats:
    """Ask a node for its gauge + RED-histogram snapshot."""

    # Histograms dominate the payload on wide deployments; a pure-gauge
    # scrape can skip them.
    include_histograms: bool = True


@message(name="rio.StatsSnapshot")
@dataclass
class StatsSnapshot:
    """One node's observability snapshot (mergeable across nodes)."""

    address: str = ""
    gauges: dict[str, float] = field(default_factory=dict)
    # rio_tpu.metrics wire rows: [handler_type, message_type, count,
    # error_count, errors{kind:int}, buckets[], sum_s, max_s,
    # exemplar_trace, exemplar_s] — merge with metrics.merge_rows.
    histograms: list = field(default_factory=list)


@message(name="rio.AdminRequest")
@dataclass
class AdminRequest:
    """Enqueue one :class:`~rio_tpu.commands.AdminCommand` on the node."""

    kind: str = ""  # an AdminCommandKind value, e.g. "drain_server"
    type_name: str = ""
    object_id: str = ""
    target: str = ""


@message(name="rio.AdminAck")
@dataclass
class AdminAck:
    ok: bool = False
    detail: str = ""


@dataclass
class StatsSource:
    """AppData-injectable snapshot providers (wired at ``Server.bind()``).

    ``gauges`` returns the :func:`~rio_tpu.otel.server_gauges` dict;
    ``histogram_rows`` returns the mergeable RED rows (empty when metrics
    are disabled). A dataclass wrapper — not bare callables — so AppData's
    type-keyed map can hold it.
    """

    gauges: Callable[[], dict[str, float]]
    histogram_rows: Callable[[], list[Any]]


@type_name(ADMIN_TYPE)
class AdminControl(ServiceObject):
    """Node-scoped observability/ops endpoint (one per server; id = address)."""

    __node_scoped__ = True

    @handler
    async def dump_stats(self, msg: DumpStats, ctx: AppData) -> StatsSnapshot:
        from .commands import ServerInfo

        info = ctx.try_get(ServerInfo)
        source = ctx.try_get(StatsSource)
        if source is None:
            return StatsSnapshot(address=info.address if info else "")
        rows = source.histogram_rows() if msg.include_histograms else []
        return StatsSnapshot(
            address=info.address if info else "",
            gauges=source.gauges(),
            histograms=rows,
        )

    @handler
    async def admin(self, msg: AdminRequest, ctx: AppData) -> AdminAck:
        sender = ctx.try_get(AdminSender)
        if sender is None:
            return AdminAck(ok=False, detail="no admin queue on this node")
        try:
            kind = AdminCommandKind(msg.kind)
        except ValueError:
            return AdminAck(ok=False, detail=f"unknown admin kind {msg.kind!r}")
        sender.send(
            AdminCommand(kind, msg.type_name, msg.object_id, msg.target)
        )
        return AdminAck(ok=True)
