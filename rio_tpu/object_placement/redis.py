"""Redis object-placement directory.

Reference: ``rio-rs/src/object_placement/redis.rs:36-87`` — one key per
object (``{prefix}:placement:{type}.{id} -> address``) plus a per-server set
of placed object keys so ``clean_server`` can bulk-unassign everything on a
dead node without scanning the keyspace.
"""

from __future__ import annotations

from typing import Callable

from ..registry import ObjectId
from ..utils.resp import RedisClient, RespError, check_replies
from . import ObjectPlacement, ObjectPlacementItem, sanitize_standby_row

# Optimistic-lock retries before a standby CAS gives up. Contention on one
# object's replica row is a handful of promoters post-death, not a hot path;
# hitting the ceiling means the row is being rewritten pathologically fast.
_CAS_ATTEMPTS = 64


class RedisObjectPlacement(ObjectPlacement):
    def __init__(self, client: RedisClient | str, key_prefix: str = "rio") -> None:
        self.client = (
            RedisClient.from_url(client) if isinstance(client, str) else client
        )
        self.prefix = key_prefix

    def _obj_key(self, key: str) -> str:
        return f"{self.prefix}:placement:{key}"

    def _server_key(self, address: str) -> str:
        return f"{self.prefix}:placement_server:{address}"

    def _standby_key(self, key: str) -> str:
        return f"{self.prefix}:standby:{key}"

    async def update(self, item: ObjectPlacementItem) -> None:
        await self.update_batch([item])

    async def update_batch(self, items: list[ObjectPlacementItem]) -> None:
        """Pipelined upsert (reference uses ``redis::pipe()`` similarly):
        one round trip to read old addresses, one for all writes."""
        if not items:
            return
        keys = [str(i.object_id) for i in items]
        olds = check_replies(
            await self.client.execute_pipeline(
                [("GET", self._obj_key(k)) for k in keys]
            )
        )
        cmds: list[tuple] = []
        for item, key, old in zip(items, keys, olds):
            if isinstance(old, bytes):
                cmds.append(("SREM", self._server_key(old.decode()), key))
            if item.server_address is None:
                cmds.append(("DEL", self._obj_key(key)))
            else:
                cmds.append(("SET", self._obj_key(key), item.server_address))
                cmds.append(("SADD", self._server_key(item.server_address), key))
        check_replies(await self.client.execute_pipeline(cmds))

    async def lookup(self, object_id: ObjectId) -> str | None:
        raw = await self.client.execute("GET", self._obj_key(str(object_id)))
        return raw.decode() if raw is not None else None

    async def clean_server(self, address: str) -> None:
        """Bulk-unassign a dead node's objects.

        The per-server set is a snapshot, so re-read each key and delete only
        those still pointing at ``address`` — a re-placement that lands
        before the GET survives. A re-placement racing *between* the GET and
        the DEL can still be lost (check-then-act; closing it fully needs
        Lua/WATCH compare-and-delete) — the same exposure class as the
        reference's snapshot-then-delete Redis impl, vs. the SQL backends'
        atomic ``DELETE WHERE server_address=?``. Pipelined: 2 round trips +
        1 variadic DEL regardless of object count.
        """
        raw_keys = await self.client.execute("SMEMBERS", self._server_key(address))
        keys = [k.decode() for k in raw_keys or []]
        if keys:
            current = check_replies(
                await self.client.execute_pipeline(
                    [("GET", self._obj_key(k)) for k in keys]
                )
            )
            stale = [
                self._obj_key(k)
                for k, cur in zip(keys, current)
                if isinstance(cur, bytes) and cur.decode() == address
            ]
            if stale:
                await self.client.execute("DEL", *stale)
        await self.client.execute("DEL", self._server_key(address))

    async def remove(self, object_id: ObjectId) -> None:
        key = str(object_id)
        old = await self.client.execute("GET", self._obj_key(key))
        cmds: list[tuple] = [("DEL", self._obj_key(key)), ("DEL", self._standby_key(key))]
        if old is not None:
            cmds.insert(0, ("SREM", self._server_key(old.decode()), key))
        check_replies(await self.client.execute_pipeline(cmds))

    @staticmethod
    def _parse_standby(raw: object) -> tuple[list[str], int]:
        # Value is ``"{epoch}|{addr,...}"``; legacy/garbage values (wrong
        # type, undecodable bytes, non-integer epoch) degrade to "no
        # standbys" rather than raising on the read path.
        if not isinstance(raw, bytes):
            return [], 0
        try:
            text = raw.decode()
        except UnicodeDecodeError:
            return [], 0
        epoch_s, _, held = text.partition("|")
        return sanitize_standby_row([a for a in held.split(",") if a], epoch_s)

    async def _standby_row(self, key: str) -> tuple[list[str], int]:
        return self._parse_standby(
            await self.client.execute("GET", self._standby_key(key))
        )

    async def _standby_cas(
        self,
        key: str,
        decide: Callable[[list[str], int], tuple[list[tuple] | None, int | None]],
    ) -> int | None:
        """Atomic read-modify-write on the standby row via WATCH/MULTI/EXEC.

        ``decide(held, epoch)`` returns ``(write_cmds, result)``;
        ``write_cmds is None`` aborts without touching the row. A concurrent
        writer between WATCH and EXEC voids the transaction (null EXEC
        reply) and the loop re-reads — the epoch fence can never be written
        from a stale read, unlike the plain read-then-SET this replaces
        (two racing promoters could both bump from the same epoch).
        """
        skey = self._standby_key(key)
        for _ in range(_CAS_ATTEMPTS):
            async with self.client.transaction() as txn:
                await txn.execute("WATCH", skey)
                held, epoch = self._parse_standby(await txn.execute("GET", skey))
                cmds, result = decide(held, epoch)
                if cmds is None:
                    await txn.execute("UNWATCH")
                    return result
                await txn.execute("MULTI")
                for c in cmds:
                    await txn.execute(*c)
                if await txn.execute("EXEC") is not None:
                    return result
        raise RespError(f"standby CAS on {key!r} lost {_CAS_ATTEMPTS} races")

    async def set_standbys(self, object_id: ObjectId, addresses: list[str]) -> int:
        key = str(object_id)
        skey = self._standby_key(key)

        def decide(held: list[str], epoch: int) -> tuple[list[tuple], int]:
            # Epoch only moves in promote_standby; writing under WATCH means
            # a promotion racing this replacement can't have its bump rolled
            # back to the pre-promotion value.
            if addresses or epoch:
                return [("SET", skey, f"{epoch}|{','.join(addresses)}")], epoch
            return [("DEL", skey)], epoch

        epoch = await self._standby_cas(key, decide)
        assert epoch is not None
        return epoch

    async def standbys(self, object_id: ObjectId) -> tuple[list[str], int]:
        return await self._standby_row(str(object_id))

    async def promote_standby(
        self, object_id: ObjectId, address: str, expected_epoch: int
    ) -> int | None:
        key = str(object_id)
        skey = self._standby_key(key)

        def decide(
            held: list[str], epoch: int
        ) -> tuple[list[tuple] | None, int | None]:
            if epoch != expected_epoch or address not in held:
                return None, None
            remaining = ",".join(a for a in held if a != address)
            return [("SET", skey, f"{epoch + 1}|{remaining}")], epoch + 1

        new_epoch = await self._standby_cas(key, decide)
        if new_epoch is None:
            return None
        await self.update(ObjectPlacementItem(object_id, address))
        return new_epoch

    async def lookup_batch(self, object_ids: list[ObjectId]) -> list[str | None]:
        # A failed GET must raise, not read as "unplaced" — a None here
        # green-lights a second activation of a possibly-seated object.
        raws = check_replies(
            await self.client.execute_pipeline(
                [("GET", self._obj_key(str(o))) for o in object_ids]
            )
        )
        return [r.decode() if isinstance(r, bytes) else None for r in raws]

    async def items(self) -> list[ObjectPlacementItem]:
        """Enumerate via KEYS on the placement prefix + one pipelined MGET
        pass. KEYS is O(keyspace) and blocking — acceptable for the warm
        RESTART path this exists for (PersistentJaxObjectPlacement.prepare
        runs once, before traffic), not for request-path use."""
        prefix = self._obj_key("")
        raw_keys = await self.client.execute("KEYS", prefix + "*")
        keys = [k.decode()[len(prefix):] for k in raw_keys or []]
        if not keys:
            return []
        raws = check_replies(
            await self.client.execute_pipeline(
                [("GET", self._obj_key(k)) for k in keys]
            )
        )
        return [
            ObjectPlacementItem(ObjectId(*k.split(".", 1)), r.decode())
            for k, r in zip(keys, raws)
            if isinstance(r, bytes)
        ]

    def close(self) -> None:
        self.client.close()
