"""Durability bridge: solver-speed directory with a write-behind backing store.

:class:`~rio_tpu.object_placement.jax_placement.JaxObjectPlacement` keeps
the directory in a host mirror for O(1) lookups and batched device solves
— a restart loses it and relies on lazy re-allocation (the reference's
recovery path, ``rio-rs/src/service.rs:227-298``). A rio-rs user migrating
from ``SqliteObjectPlacement`` gives up the durability they had.

:class:`PersistentJaxObjectPlacement` closes that gap without giving the
speed back: every mirror mutation (allocation, update, rebalance apply,
clean_server, remove) marks the key dirty, and a background flusher
coalesces the dirty set into batched writes against ANY reference-style
``ObjectPlacement`` backing store (SQLite / Postgres / Redis — whatever
the deployment already runs). ``prepare()`` warm-restores the whole
directory from the backing store via the trait's ``items()`` hook.

Consistency model — write-BEHIND, deliberately:

* the solver path never waits on the database (the whole point of the
  provider is removing the per-request SQL round trip);
* a crash loses at most ``flush_interval`` worth of placements, each of
  which lazy re-allocation re-seats on first touch — the same recovery
  the non-persistent provider relies on for EVERYTHING;
* flush failures keep the dirty set (newer marks win the merge) and retry
  on the next cycle — the backing store being briefly down degrades
  durability freshness, never availability.
"""

from __future__ import annotations

import asyncio
import logging

from ..registry import ObjectId
from . import ObjectPlacement, ObjectPlacementItem, sanitize_standby_row
from .jax_placement import JaxObjectPlacement

log = logging.getLogger("rio_tpu.object_placement.persistent")

__all__ = ["PersistentJaxObjectPlacement"]


class PersistentJaxObjectPlacement(JaxObjectPlacement):
    """JaxObjectPlacement + write-behind durability on a backing store."""

    def __init__(
        self,
        backing: ObjectPlacement,
        *,
        flush_interval: float = 0.05,
        **jax_kwargs,
    ) -> None:
        super().__init__(**jax_kwargs)
        self._backing = backing
        self._flush_interval = flush_interval
        self._dirty: dict[str, str | None] = {}  # key -> address | None=delete
        self._dirty_standbys: dict[str, list[str]] = {}  # key -> standby set
        self._flusher: asyncio.Task | None = None
        self._flush_wake: asyncio.Event | None = None  # created on the loop
        self._flush_lock = asyncio.Lock()  # serializes manual + background
        self._restoring = False

    # ------------------------------------------------------------- restore
    async def prepare(self) -> None:
        """Warm-restore the mirror from the backing store (once, at boot)."""
        await self._backing.prepare()
        items = await self._backing.items()
        async with self._lock:
            self._restoring = True
            known = set(self._nodes)
            try:
                for item in items:
                    if item.server_address is not None:
                        self._set_placement(
                            str(item.object_id),
                            self._node_index(item.server_address),
                        )
            finally:
                self._restoring = False
            # Nodes the restore itself had to invent are HEARSAY from the
            # stored directory — the node may have died while we were down.
            # Start them dead (sync_members/register_node revives the live
            # ones) so the solver never seats NEW objects on a ghost; their
            # restored placements stand until lookup/gossip re-seats them.
            for address in set(self._nodes) - known:
                self._nodes[address].alive = False
            # The restored population must count as load, or the next
            # allocation treats the cluster as empty and piles onto the
            # fullest node.
            self._recount_loads()
            if items:
                self._epoch += 1
        log.info("restored %d placements from %s",
                 len(items), type(self._backing).__name__)

    # ------------------------------------------------------- dirty tracking
    # Every mirror mutation in the base class flows through these two
    # methods (allocation apply, rebalance mover loop, update, remove,
    # clean_server), so overriding them catches the full write set.
    def _set_placement(self, key: str, idx: int) -> bool:
        changed = super()._set_placement(key, idx)
        if changed and not self._restoring:
            self._mark(key, self._node_order[idx])
        return changed

    def _drop_placement(self, key: str) -> int | None:
        idx = super()._drop_placement(key)
        if idx is not None and not self._restoring:
            self._mark(key, None)
        return idx

    def _set_standby_row(self, key: str, addresses: list[str], epoch: int) -> None:
        super()._set_standby_row(key, addresses, epoch)
        if not self._restoring:
            self._dirty_standbys[key] = list(addresses)
            self._wake_flusher()

    def _drop_standby_row(self, key: str) -> None:
        super()._drop_standby_row(key)
        if not self._restoring:
            self._dirty_standbys[key] = []
            self._wake_flusher()

    def _mark(self, key: str, address: str | None) -> None:
        self._dirty[key] = address
        self._wake_flusher()

    def _wake_flusher(self) -> None:
        if self._flush_wake is None:
            self._flush_wake = asyncio.Event()
        self._flush_wake.set()
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_running_loop().create_task(
                self._flush_loop()
            )

    # --------------------------------------------------------------- flush
    async def _flush_loop(self) -> None:
        assert self._flush_wake is not None
        while True:
            await self._flush_wake.wait()
            self._flush_wake.clear()
            # Coalesce a burst (one rebalance marks ~the displaced share)
            # into one batched write instead of thousands.
            await asyncio.sleep(self._flush_interval)
            try:
                await self.flush()
            except Exception:
                log.exception("placement write-behind flush failed; retrying")
                await asyncio.sleep(self._flush_interval)
                self._flush_wake.set()

    async def flush(self) -> int:
        """Write the current dirty set to the backing store (also callable
        directly, e.g. before a planned shutdown). Returns rows written.

        Serialized against the background flusher: a manual flush must not
        return while an in-flight background write still holds part of the
        dirty set — "flush then stop" would otherwise race its own flusher.
        """
        async with self._flush_lock:
            return await self._flush_locked()

    async def _flush_locked(self) -> int:
        flushed = await self._flush_standbys_locked()
        if not self._dirty:
            return flushed
        dirty, self._dirty = self._dirty, {}
        try:
            # ONE batched write for updates AND deletes: every backend's
            # update_batch treats server_address=None as unassign (Redis
            # pipelines SREM+DEL, SQL upserts NULL which lookup/items treat
            # as absent). Per-key awaited removes would turn a big
            # clean_server (500k keys at the 10M tier) into minutes of
            # round trips and blow the crash-loss window.
            await self._backing.update_batch(
                [
                    ObjectPlacementItem(ObjectId(*k.split(".", 1)), addr)
                    for k, addr in dirty.items()
                ]
            )
        except BaseException:
            # Keep failed rows dirty; marks made DURING the failed flush
            # are newer and win the merge. BaseException on purpose: a
            # flusher CANCELLED mid-write (aclose during a flush) must
            # also put its unwritten marks back for the final flush.
            for k, addr in dirty.items():
                self._dirty.setdefault(k, addr)
            raise
        return flushed + len(dirty)

    async def _flush_standbys_locked(self) -> int:
        if not self._dirty_standbys:
            return 0
        dirty, self._dirty_standbys = self._dirty_standbys, {}
        done = 0
        try:
            # Per-key writes (the trait has no standby batch hook): replica
            # sets change at placement/repair cadence, not per request, so
            # the write volume is nothing like the primary-row stream. The
            # backing preserves its own epoch — only promote_standby (write-
            # THROUGH below) ever moves it.
            for k, addrs in list(dirty.items()):
                await self._backing.set_standbys(
                    ObjectId(*k.split(".", 1)), addrs
                )
                dirty.pop(k)
                done += 1
        except BaseException:
            for k, addrs in dirty.items():
                self._dirty_standbys.setdefault(k, addrs)
            raise
        return done

    # ------------------------------------------------------- replica rows
    # Standby SETS ride the write-behind like primary rows; the EPOCH is
    # different — it is the failover fence, so it must be durable the
    # instant it moves. promote_standby is therefore write-THROUGH: the
    # backing store's CAS is the arbiter, the mirror follows its verdict.

    async def standbys(self, object_id) -> tuple[list[str], int]:
        key = str(object_id)
        row = self._standby_rows.get(key)
        if row is not None:
            held, epoch = row
            return sanitize_standby_row(held, epoch)
        # Mirror miss (cold restart): read through. Not cached — a row is
        # only mirrored once this node writes it, keeping restore lazy.
        return await self._backing.standbys(object_id)

    async def set_standbys(self, object_id, addresses: list[str]) -> int:
        # Seed the mirror with the BACKING's epoch on first touch after a
        # restart, or the returned fence would restart at 0 while the
        # durable row is ahead of it.
        key = str(object_id)
        if key not in self._standby_rows:
            _, epoch = await self._backing.standbys(object_id)
            async with self._lock:
                if key not in self._standby_rows:
                    self._set_standby_row(key, list(addresses), epoch)
                    return epoch
        return await super().set_standbys(object_id, addresses)

    async def promote_standby(
        self, object_id, address: str, expected_epoch: int
    ) -> int | None:
        # The durable CAS must see this node's standby writes first.
        await self.flush()
        new_epoch = await self._backing.promote_standby(
            object_id, address, expected_epoch
        )
        if new_epoch is None:
            return None
        key = str(object_id)
        # Cold-restart mirror miss: rebuilding the row from ([], 0) would
        # flush an EMPTY set over the surviving standbys' durable row with
        # k>=2, silently dropping seats until anti-entropy re-places them.
        # The post-CAS backing row is authoritative (it already excludes
        # the promoted address).
        survivors: list[str] | None = None
        if key not in self._standby_rows:
            survivors, _ = await self._backing.standbys(object_id)
        async with self._lock:
            row = self._standby_rows.get(key)
            if row is not None:
                survivors = [a for a in row[0] if a != address]
            self._set_standby_row(key, survivors or [], new_epoch)
            self._set_placement(key, self._node_index(address))
            self._epoch += 1
        return new_epoch

    async def aclose(self) -> None:
        """Final flush + stop the flusher (planned shutdown)."""
        if self._flusher is not None:
            self._flusher.cancel()
            try:
                await self._flusher
            except asyncio.CancelledError:
                pass
            self._flusher = None
        await self.flush()
