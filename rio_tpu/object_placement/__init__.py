"""Object placement: the cluster-wide actor directory.

Reference: ``rio-rs/src/object_placement/mod.rs:20-56`` — a CRUD mapping
``ObjectId -> server_address`` consulted on every request
(``service.rs:193-254``). The reference's *policy* is trivial (random client
pick + receiving-server self-assign, no load balancing); rio-tpu keeps this
trait boundary and adds :class:`~rio_tpu.object_placement.jax_placement.JaxObjectPlacement`,
which treats placement as a batched assignment problem solved on TPU
(see ``rio_tpu/ops/sinkhorn.py``).
"""

from __future__ import annotations

import abc
import dataclasses

from ..registry import ObjectId

__all__ = [
    "ObjectId",
    "ObjectPlacementItem",
    "ObjectPlacement",
    "LocalObjectPlacement",
    "sanitize_standby_row",
]


@dataclasses.dataclass
class ObjectPlacementItem:
    """One directory row (reference ``object_placement/mod.rs:20-37``)."""

    object_id: ObjectId
    server_address: str | None = None


def sanitize_standby_row(held: object, epoch: object) -> tuple[list[str], int]:
    """Defensive decode of a standby row read back from a backend.

    Replica rows outlive code versions: a directory written by an older
    deployment (or hand-edited, or corrupted) must degrade to "no standbys"
    — a read-capacity loss — never to an exception on the request path. A
    non-integer or negative epoch poisons the fence, so the whole row is
    dropped; individually malformed addresses are filtered while the rest
    of the set survives.
    """
    try:
        ep = int(epoch)  # type: ignore[call-overload]
    except (TypeError, ValueError):
        return [], 0
    if ep < 0:
        return [], 0
    if not isinstance(held, (list, tuple)):
        return [], ep
    addrs: list[str] = []
    for a in held:
        if isinstance(a, bytes):
            try:
                a = a.decode()
            except UnicodeDecodeError:
                continue
        if not isinstance(a, str):
            continue
        host, sep, port = a.rpartition(":")
        if sep and host and port.isdigit():
            addrs.append(a)
    return addrs, ep


class ObjectPlacement(abc.ABC):
    """CRUD directory trait (reference ``object_placement/mod.rs:39-56``)."""

    async def prepare(self) -> None:
        return None

    @abc.abstractmethod
    async def update(self, item: ObjectPlacementItem) -> None:
        """Upsert an object's address (atomic per key)."""

    @abc.abstractmethod
    async def lookup(self, object_id: ObjectId) -> str | None: ...

    @abc.abstractmethod
    async def clean_server(self, address: str) -> None:
        """Bulk-unassign every object placed on ``address`` (dead node)."""

    @abc.abstractmethod
    async def remove(self, object_id: ObjectId) -> None: ...

    # Batch hooks — default to per-item loops; accelerated providers
    # (JaxObjectPlacement) override with a single device solve.
    async def lookup_batch(self, object_ids: list[ObjectId]) -> list[str | None]:
        return [await self.lookup(oid) for oid in object_ids]

    async def update_batch(self, items: list[ObjectPlacementItem]) -> None:
        for item in items:
            await self.update(item)

    async def items(self) -> list[ObjectPlacementItem]:
        """Every directory row (optional trait method, like the state
        loaders' optional surface): required of a provider used as the
        durable BACKING store behind
        :class:`~rio_tpu.object_placement.persistent.PersistentJaxObjectPlacement`,
        whose warm restart reloads the whole directory."""
        raise NotImplementedError(f"{type(self).__name__} cannot enumerate")

    # ------------------------------------------------------------------
    # Replica rows (replication subsystem). Every backend stores, next to
    # the primary row, an optional ``(standbys, epoch)`` pair per object.
    # The epoch is the fence: it only ever moves through
    # :meth:`promote_standby`'s compare-and-swap, so a partitioned old
    # primary still shipping state with a stale epoch can be detected and
    # nacked by the standby side (see ``rio_tpu/replication``).
    # ------------------------------------------------------------------

    async def set_standbys(self, object_id: ObjectId, addresses: list[str]) -> int:
        """Replace the standby set; the epoch is preserved (created at 0).

        Returns the row's current epoch so the caller can fence its ships.
        """
        raise NotImplementedError(f"{type(self).__name__} stores no standbys")

    async def standbys(self, object_id: ObjectId) -> tuple[list[str], int]:
        """``(standby addresses, epoch)``; ``([], 0)`` when no replica row
        exists (an epoch-0 row and no row are indistinguishable on purpose:
        promotion from either state produces epoch 1)."""
        raise NotImplementedError(f"{type(self).__name__} stores no standbys")

    async def promote_standby(
        self, object_id: ObjectId, address: str, expected_epoch: int
    ) -> int | None:
        """CAS promotion: if ``address`` is a current standby and the row's
        epoch equals ``expected_epoch``, make it the primary (primary row
        flipped, ``address`` removed from the standby set, epoch bumped)
        and return the new epoch. Returns ``None`` when the CAS loses —
        someone else promoted first, or the standby set changed."""
        raise NotImplementedError(f"{type(self).__name__} stores no standbys")


class LocalObjectPlacement(ObjectPlacement):
    """In-memory directory; clones alias the same dict.

    Reference ``object_placement/local.rs:12-68`` (keying scheme
    ``"{type}.{id}"`` preserved for parity).
    """

    def __init__(self) -> None:
        self._placements: dict[str, str] = {}
        self._standbys: dict[str, tuple[list[str], int]] = {}

    async def update(self, item: ObjectPlacementItem) -> None:
        key = str(item.object_id)
        if item.server_address is None:
            self._placements.pop(key, None)
        else:
            self._placements[key] = item.server_address

    async def lookup(self, object_id: ObjectId) -> str | None:
        return self._placements.get(str(object_id))

    async def clean_server(self, address: str) -> None:
        stale = [k for k, v in self._placements.items() if v == address]
        for k in stale:
            del self._placements[k]

    async def remove(self, object_id: ObjectId) -> None:
        self._placements.pop(str(object_id), None)
        self._standbys.pop(str(object_id), None)

    async def set_standbys(self, object_id: ObjectId, addresses: list[str]) -> int:
        key = str(object_id)
        _, epoch = self._standbys.get(key, ([], 0))
        if addresses:
            self._standbys[key] = (list(addresses), epoch)
        elif epoch:
            self._standbys[key] = ([], epoch)
        else:
            self._standbys.pop(key, None)
        return epoch

    async def standbys(self, object_id: ObjectId) -> tuple[list[str], int]:
        held, epoch = self._standbys.get(str(object_id), ([], 0))
        return sanitize_standby_row(held, epoch)

    async def promote_standby(
        self, object_id: ObjectId, address: str, expected_epoch: int
    ) -> int | None:
        key = str(object_id)
        held, epoch = self._standbys.get(key, ([], 0))
        if epoch != expected_epoch or address not in held:
            return None
        self._standbys[key] = ([a for a in held if a != address], epoch + 1)
        self._placements[key] = address
        return epoch + 1

    async def items(self) -> list[ObjectPlacementItem]:
        return [
            ObjectPlacementItem(ObjectId(*k.split(".", 1)), v)
            for k, v in self._placements.items()
        ]

    def count(self) -> int:
        return len(self._placements)
