"""PostgreSQL object-placement directory.

Reference: ``rio-rs/src/object_placement/postgres.rs:25-50`` ff — same table
shape as SQLite, so query logic is inherited from
:class:`~rio_tpu.object_placement.sqlite.SqliteObjectPlacement`; only the
connection and migrations differ. Driver-gated (``rio_tpu/utils/pg.py``).
"""

from __future__ import annotations

from ..utils.pg import PgDb
from .sqlite import SqliteObjectPlacement

MIGRATIONS = [
    """
    CREATE TABLE IF NOT EXISTS object_placement (
        struct_name    TEXT NOT NULL,
        object_id      TEXT NOT NULL,
        server_address TEXT,
        PRIMARY KEY (struct_name, object_id)
    );
    CREATE INDEX IF NOT EXISTS idx_object_placement_server
        ON object_placement (server_address)
    """,
    """
    CREATE TABLE IF NOT EXISTS object_standby (
        struct_name TEXT NOT NULL,
        object_id   TEXT NOT NULL,
        standbys    TEXT NOT NULL DEFAULT '',
        epoch       INTEGER NOT NULL DEFAULT 0,
        PRIMARY KEY (struct_name, object_id)
    )
    """,
]


class PostgresObjectPlacement(SqliteObjectPlacement):
    def __init__(self, dsn: str) -> None:
        self.db = PgDb(dsn)

    async def prepare(self) -> None:
        await self.db.migrate(MIGRATIONS)
