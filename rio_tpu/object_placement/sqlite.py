"""SQLite object-placement directory.

Reference: ``rio-rs/src/object_placement/sqlite.rs`` — table
``object_placement(struct_name, object_id, server_address)`` with an index
on ``server_address``; upsert (``:68-85``), lookup (``:86-100``),
``clean_server`` DELETE-by-address (``:101-112``).
"""

from __future__ import annotations

from ..registry import ObjectId
from ..utils.sqlite import SqliteDb
from . import ObjectPlacement, ObjectPlacementItem

MIGRATIONS = [
    """
    CREATE TABLE IF NOT EXISTS object_placement (
        struct_name    TEXT NOT NULL,
        object_id      TEXT NOT NULL,
        server_address TEXT,
        PRIMARY KEY (struct_name, object_id)
    );
    CREATE INDEX IF NOT EXISTS idx_object_placement_server
        ON object_placement (server_address);
    """
]


class SqliteObjectPlacement(ObjectPlacement):
    def __init__(self, path: str) -> None:
        self.db = SqliteDb(path)

    async def prepare(self) -> None:
        await self.db.migrate(MIGRATIONS)

    async def update(self, item: ObjectPlacementItem) -> None:
        await self.db.execute(
            "INSERT INTO object_placement (struct_name, object_id, server_address) "
            "VALUES (?,?,?) ON CONFLICT(struct_name, object_id) "
            "DO UPDATE SET server_address=excluded.server_address",
            item.object_id.type_name, item.object_id.id, item.server_address,
        )

    async def lookup(self, object_id: ObjectId) -> str | None:
        rows = await self.db.execute(
            "SELECT server_address FROM object_placement "
            "WHERE struct_name=? AND object_id=?",
            object_id.type_name, object_id.id,
        )
        return rows[0][0] if rows else None

    async def clean_server(self, address: str) -> None:
        await self.db.execute(
            "DELETE FROM object_placement WHERE server_address=?", address
        )

    async def remove(self, object_id: ObjectId) -> None:
        await self.db.execute(
            "DELETE FROM object_placement WHERE struct_name=? AND object_id=?",
            object_id.type_name, object_id.id,
        )

    async def items(self) -> list[ObjectPlacementItem]:
        rows = await self.db.execute(
            "SELECT struct_name, object_id, server_address "
            "FROM object_placement WHERE server_address IS NOT NULL"
        )
        return [
            ObjectPlacementItem(ObjectId(t, i), addr) for t, i, addr in rows
        ]

    def close(self) -> None:
        self.db.close()
