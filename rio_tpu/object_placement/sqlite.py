"""SQLite object-placement directory.

Reference: ``rio-rs/src/object_placement/sqlite.rs`` — table
``object_placement(struct_name, object_id, server_address)`` with an index
on ``server_address``; upsert (``:68-85``), lookup (``:86-100``),
``clean_server`` DELETE-by-address (``:101-112``).
"""

from __future__ import annotations

from ..registry import ObjectId
from ..utils.sqlite import SqliteDb
from . import ObjectPlacement, ObjectPlacementItem, sanitize_standby_row

MIGRATIONS = [
    """
    CREATE TABLE IF NOT EXISTS object_placement (
        struct_name    TEXT NOT NULL,
        object_id      TEXT NOT NULL,
        server_address TEXT,
        PRIMARY KEY (struct_name, object_id)
    );
    CREATE INDEX IF NOT EXISTS idx_object_placement_server
        ON object_placement (server_address);
    CREATE TABLE IF NOT EXISTS object_standby (
        struct_name TEXT NOT NULL,
        object_id   TEXT NOT NULL,
        standbys    TEXT NOT NULL DEFAULT '',
        epoch       INTEGER NOT NULL DEFAULT 0,
        PRIMARY KEY (struct_name, object_id)
    );
    """
]


class SqliteObjectPlacement(ObjectPlacement):
    def __init__(self, path: str) -> None:
        self.db = SqliteDb(path)

    async def prepare(self) -> None:
        await self.db.migrate(MIGRATIONS)

    async def update(self, item: ObjectPlacementItem) -> None:
        await self.db.execute(
            "INSERT INTO object_placement (struct_name, object_id, server_address) "
            "VALUES (?,?,?) ON CONFLICT(struct_name, object_id) "
            "DO UPDATE SET server_address=excluded.server_address",
            item.object_id.type_name, item.object_id.id, item.server_address,
        )

    async def lookup(self, object_id: ObjectId) -> str | None:
        rows = await self.db.execute(
            "SELECT server_address FROM object_placement "
            "WHERE struct_name=? AND object_id=?",
            object_id.type_name, object_id.id,
        )
        return rows[0][0] if rows else None

    async def clean_server(self, address: str) -> None:
        await self.db.execute(
            "DELETE FROM object_placement WHERE server_address=?", address
        )

    async def remove(self, object_id: ObjectId) -> None:
        await self.db.execute(
            "DELETE FROM object_placement WHERE struct_name=? AND object_id=?",
            object_id.type_name, object_id.id,
        )
        await self.db.execute(
            "DELETE FROM object_standby WHERE struct_name=? AND object_id=?",
            object_id.type_name, object_id.id,
        )

    async def set_standbys(self, object_id: ObjectId, addresses: list[str]) -> int:
        # Upsert that PRESERVES the fence: only promote_standby moves epoch.
        await self.db.execute(
            "INSERT INTO object_standby (struct_name, object_id, standbys, epoch) "
            "VALUES (?,?,?,0) ON CONFLICT(struct_name, object_id) "
            "DO UPDATE SET standbys=excluded.standbys",
            object_id.type_name, object_id.id, ",".join(addresses),
        )
        _, epoch = await self.standbys(object_id)
        return epoch

    async def standbys(self, object_id: ObjectId) -> tuple[list[str], int]:
        rows = await self.db.execute(
            "SELECT standbys, epoch FROM object_standby "
            "WHERE struct_name=? AND object_id=?",
            object_id.type_name, object_id.id,
        )
        if not rows:
            return [], 0
        held, epoch = rows[0]
        # TEXT-affinity columns round-trip whatever a legacy writer stored;
        # degrade garbage to "no standbys" instead of crashing the read path.
        if isinstance(held, bytes):
            try:
                held = held.decode()
            except UnicodeDecodeError:
                held = ""
        if not isinstance(held, str):
            held = ""
        return sanitize_standby_row([a for a in held.split(",") if a], epoch)

    async def promote_standby(
        self, object_id: ObjectId, address: str, expected_epoch: int
    ) -> int | None:
        held, epoch = await self.standbys(object_id)
        if epoch != expected_epoch or address not in held:
            return None
        remaining = ",".join(a for a in held if a != address)
        # CAS: the epoch guard in the WHERE makes a lost race a 0-row
        # update; the re-read below distinguishes "we won" from "someone
        # else promoted a different standby first".
        await self.db.execute(
            "UPDATE object_standby SET standbys=?, epoch=epoch+1 "
            "WHERE struct_name=? AND object_id=? AND epoch=?",
            remaining, object_id.type_name, object_id.id, expected_epoch,
        )
        held2, epoch2 = await self.standbys(object_id)
        if epoch2 != expected_epoch + 1 or address in held2:
            return None
        await self.update(ObjectPlacementItem(object_id, address))
        return epoch2

    async def items(self) -> list[ObjectPlacementItem]:
        rows = await self.db.execute(
            "SELECT struct_name, object_id, server_address "
            "FROM object_placement WHERE server_address IS NOT NULL"
        )
        return [
            ObjectPlacementItem(ObjectId(t, i), addr) for t, i, addr in rows
        ]

    def close(self) -> None:
        self.db.close()
