"""JaxObjectPlacement: the TPU-accelerated placement provider.

Implements the reference's ``ObjectPlacement`` trait
(``rio-rs/src/object_placement/mod.rs:39-56``) — so it drops into
``Service.get_or_create_placement`` unchanged — but replaces the per-request
SQL round trip (``rio-rs/src/service.rs:220``, named the bottleneck in
``BASELINE.md``) with:

- a **host-mirrored directory** (dict) answering ``lookup`` in O(1) with no
  I/O — the fast read path the router consumes;
- a **device-resident solve**: batched assignment of unplaced objects via
  cached node potentials (one cost row + one argmin per object,
  :func:`rio_tpu.ops.assignment.assign_from_potentials`), refreshed by full
  Sinkhorn/greedy re-solves (:func:`rio_tpu.ops.sinkhorn.sinkhorn_assign`,
  sharded across a mesh via :mod:`rio_tpu.parallel` at scale);
- **epoch versioning** for consistency: every mutation bumps an epoch; a
  re-solve snapshots the epoch and its result is discarded if the directory
  moved underneath it (single-writer semantics replacing the reference's
  reliance on SQL upsert atomicity, ``object_placement/sqlite.rs:72-85``).

Liveness flows in from gossip (``MembershipStorage``) via
:meth:`JaxObjectPlacement.sync_members`, mirroring how the reference's
service checks ``is_active`` before honoring a placement
(``service.rs:213-238``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field, replace

import jax
import jax.numpy as jnp
import numpy as np

from ..errors import NoSchedulableCapacity
from ..registry import ObjectId
from ..ops import (
    build_cost_matrix,
    greedy_balanced_assign,
    integer_fair_quotas,
    plan_rounded_assign,
    residual_capacity_assign,
    scaling_sinkhorn,
    sinkhorn,
)
from . import ObjectPlacement, ObjectPlacementItem, sanitize_standby_row

log = logging.getLogger(__name__)

_FEAT_DIM = 16  # hashed-identity feature width for the hierarchical mode


def _hash_features(keys: list[str], dim: int = _FEAT_DIM) -> jax.Array:
    """Stable pseudo-random feature per key (identity/cache-warmth proxy).

    crc32 of the key seeds a per-key PRNG; the feature is deterministic
    across processes, so affinity survives restarts without storage.
    """
    import zlib

    seeds = np.asarray([zlib.crc32(k.encode()) & 0x7FFFFFFF for k in keys], np.uint32)
    return jax.vmap(lambda s: jax.random.normal(jax.random.PRNGKey(s), (dim,)))(
        jnp.asarray(seeds)
    )


class AffinityTracker:
    """Turns observed traffic into placement features for hierarchical mode.

    The two-level solver scores ``affinity[i, j] = obj_feat[i] @ node_feat
    [:, j]``; this tracker makes that product mean something: each node gets
    a stable embedding, and each object's feature is a request-weighted EMA
    of the embeddings of nodes that served it (cache warmth / state
    locality), so the OT objective pulls an object toward where its state
    is hot — while the capacity marginals still enforce balance. The
    reference has no counterpart (placement there is a random pick,
    ``client/mod.rs:255-262``); this is the hook VERDICT flagged as missing
    from the hierarchical mode.

    Wire it up::

        tracker = AffinityTracker()
        placement = JaxObjectPlacement(
            mode="hierarchical",
            obj_features=tracker.obj_features,
            node_features=tracker.node_features,
        )
        ...
        tracker.observe(str(object_id), serving_address, weight=1.0)
    """

    def __init__(
        self,
        dim: int = _FEAT_DIM,
        stickiness: float = 0.25,
        max_objects: int = 262_144,
    ) -> None:
        self.dim = dim
        # Hard bound on per-object state (_obj warmth vectors, rate EMAs,
        # state-bytes records): a high-cardinality workload — millions of
        # one-shot actor ids — would otherwise grow the tracker without
        # limit. fold_rates() enforces it by evicting the COLDEST entries
        # (lowest folded req/sec; unknown rate counts as 0) down to the
        # cap; the hottest objects, the only ones whose warmth can change
        # a placement decision, always survive. ``evictions`` counts
        # dropped entries for telemetry.
        self.max_objects = int(max_objects)
        self.evictions = 0
        # EMA coefficient toward the serving node's embedding per unit
        # weight; 0.0 disables learning.  The default keeps MULTI-node
        # warmth: with interleaved traffic the feature converges to the
        # traffic-share mix of the serving nodes' embeddings (a 3:1 split
        # leaves a clearly detectable secondary component), while a high
        # value (~1) degenerates to last-server-wins and erases every
        # warm replica the moment traffic touches the primary — measured
        # to destroy the churn-failover payoff in
        # ``tests/test_affinity_payoff.py``.
        self.stickiness = stickiness
        self._obj: dict[str, np.ndarray] = {}
        self._node_cache: dict[str, np.ndarray] = {}
        # Measured per-object cost features (rio_tpu/load): request counts
        # accumulated since the last fold_rates() tick, the folded req/sec
        # EMA, and the last observed migration-snapshot size. move_weights()
        # turns these into per-object move prices for the solver; the
        # LoadMonitor's sampling loop drives fold_rates(). All maps follow
        # the same atomic-swap discipline as _obj (solver thread reads
        # concurrently).
        self._req_window: dict[str, float] = {}
        self._rates: dict[str, float] = {}
        self._state_bytes: dict[str, float] = {}
        self._rate_fold_t = time.monotonic()

    def _node_vec(self, address: str) -> np.ndarray:
        vec = self._node_cache.get(address)
        if vec is None:
            vec = np.asarray(_hash_features([address], self.dim))[0]
            vec = vec / max(float(np.linalg.norm(vec)), 1e-9)
            self._node_cache[address] = vec
        return vec

    def observe(self, key: str, node_address: str, weight: float = 1.0) -> None:
        """Record that ``key`` was served by ``node_address``.

        ``weight`` scales the pull (e.g. request count since last observe,
        or bytes of state touched).  Alpha is capped below 1 so a single
        heavy observation can never fully erase accumulated warmth."""
        self._req_window[key] = self._req_window.get(key, 0.0) + max(0.0, weight)
        alpha = min(0.95, self.stickiness * weight)
        if alpha <= 0.0:
            return
        target = self._node_vec(node_address)
        cur = self._obj.get(key)
        if cur is None and len(self._obj) >= 2 * self.max_objects:
            # Backstop when no LoadMonitor drives fold_rates(): force a
            # fold (which evicts down to max_objects) before admitting a
            # new key, so the tracker never exceeds 2x its cap.
            self.fold_rates(min_dt=0.0)
            cur = self._obj.get(key)
        if cur is None:
            # Cold object: blend from the same weak hashed-identity base
            # obj_features() would have used, so a low-weight stray request
            # nudges rather than fully re-homes it.
            cur = np.asarray(_hash_features([key], self.dim), np.float32)[0] * 0.1
        # Atomic swap (never mutate in place): the solver thread reads
        # self._obj concurrently via obj_features() during a rebalance.
        new = (1.0 - alpha) * cur + alpha * target
        norm = float(np.linalg.norm(new))
        if norm > 1e-9:
            new = new / norm
        self._obj[key] = new

    def obj_features(self, keys: list[str]) -> np.ndarray:
        """(n, dim) features: learned EMA, hashed-identity for cold objects."""
        out = np.asarray(_hash_features(keys, self.dim), np.float32) * 0.1
        for i, k in enumerate(keys):
            vec = self._obj.get(k)
            if vec is not None:
                out[i] = vec
        return out

    def node_features(self, addresses: list[str]) -> np.ndarray:
        """(m, dim) embeddings matching what ``observe`` pulled toward."""
        if not addresses:
            return np.zeros((0, self.dim), np.float32)
        return np.stack([self._node_vec(a) for a in addresses]).astype(np.float32)

    # ------------------------------------------- measured cost features
    def fold_rates(self, beta: float = 0.3, min_dt: float = 0.05) -> None:
        """Fold the since-last-tick request window into per-object req/sec
        EMAs (driven by the LoadMonitor's sampling loop). Builds fresh
        dicts and swaps — never mutates in place, the solver thread reads
        move_weights() concurrently."""
        now = time.monotonic()
        dt = now - self._rate_fold_t
        if dt < min_dt:
            return
        self._rate_fold_t = now
        window, self._req_window = self._req_window, {}
        rates: dict[str, float] = {}
        for k, old in self._rates.items():
            new = (1.0 - beta) * old + beta * (window.pop(k, 0.0) / dt)
            if new > 1e-6:  # drop cooled-off objects: the map stays bounded
                rates[k] = new
        for k, cnt in window.items():
            rates[k] = beta * (cnt / dt)
        self._rates = rates
        # Enforce the max_objects bound on every per-object map. Build
        # fresh dicts and swap (solver thread reads concurrently); evict
        # coldest-by-rate first so the warmth that matters survives.
        for name in ("_obj", "_state_bytes"):
            cur = getattr(self, name)
            over = len(cur) - self.max_objects
            if over <= 0:
                continue
            doomed = sorted(cur, key=lambda k: rates.get(k, 0.0))[:over]
            kept = dict(cur)
            for k in doomed:
                del kept[k]
            setattr(self, name, kept)
            self.evictions += over
        if len(rates) > self.max_objects:
            over = len(rates) - self.max_objects
            doomed = sorted(rates, key=rates.get)[:over]
            kept_r = dict(rates)
            for k in doomed:
                del kept_r[k]
            self._rates = kept_r
            self.evictions += over

    def total_rate(self) -> float:
        return float(sum(self._rates.values()))

    def object_rates(self) -> dict[str, float]:
        """Snapshot of the folded per-object req/sec EMAs.

        Keys are observer keys (``"{type_name}.{id}"`` == ``str(ObjectId)``).
        The read-scale hotness detector consumes this; a plain dict copy of
        the atomically-swapped map, safe against the concurrent fold."""
        return dict(self._rates)

    def note_state_bytes(self, key: str, nbytes: int) -> None:
        """Record the object's last migration-snapshot size (its state
        weight). Called by the migration manager at handoff time."""
        self._state_bytes[key] = float(max(0, nbytes))

    def move_weights(
        self,
        keys: list[str],
        *,
        rate_scale: float = 10.0,
        bytes_scale: float = 1 << 20,
        max_weight: float = 16.0,
    ) -> np.ndarray:
        """(n,) per-object move prices for the solver's stay-put discount.

        ``1.0`` for a cold object, growing with measured request rate
        (cache warmth lost on a move) and snapshot size (bytes that must
        cross the wire), capped so one pathological actor can't dominate
        the objective. ``JaxObjectPlacement`` consumes this via its
        ``object_costs`` hook."""
        rates, sizes = self._rates, self._state_bytes  # snapshot refs
        out = np.ones((len(keys),), np.float32)
        for i, k in enumerate(keys):
            w = 1.0 + rates.get(k, 0.0) / rate_scale + sizes.get(k, 0.0) / bytes_scale
            out[i] = min(max_weight, w)
        return out


def _profiler_trace(name: str):
    """jax.profiler annotation for solver steps (SURVEY §5.1); no-op off-JAX."""
    import contextlib

    try:
        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover - profiler unavailable
        return contextlib.nullcontext()


# Hierarchical solves chunk the object axis above this row count (power
# of two, so it divides every larger po2 bucket): the TPU backend's
# compile time is superlinear in the flat row count while the chunked
# lax.map body compiles once at the chunk shape. On a mesh the bound
# applies PER DEVICE — devices divide the rows first, chunks divide each
# device's slice (parallel/hierarchical.py mesh_chunked_hierarchical_
# assign); on a single chip it bounds the lax.map chunk directly.
# RIO_TPU_HIER_CHUNK_ROWS overrides (po2; CI smokes use a tiny value to
# exercise the composed dispatch at test shapes in seconds).
_HIER_CHUNK_ROWS = int(os.environ.get("RIO_TPU_HIER_CHUNK_ROWS") or 524_288)

# Flat (collapsed) OT rebalances above this many padded rows route through
# the hierarchical solve instead: the TPU backend's compile time for the
# flat O(N) expansion pipeline is superlinear in the row count — neither
# 10.5M nor 4.2M rows finished a 900 s compile budget (v5e, 2026-07-31)
# while 1M compiles in ~80 s — and the chunked two-level solve compiles
# in ~50 s and executes 10.5M in 2.6 s. The threshold is the largest
# flat bucket actually proven on hardware; on a mesh it applies to the
# per-shard row count, and the routed re-solve lands on the mesh x chunk
# composed path (never a giant flat compile per shard).
# RIO_TPU_FLAT_REBALANCE_MAX_ROWS overrides (CI smoke knob).
_FLAT_REBALANCE_MAX_ROWS = int(
    os.environ.get("RIO_TPU_FLAT_REBALANCE_MAX_ROWS") or 1_048_576
)

# Row cap for the affinity refine's subset solve: the communication graph
# is top-K bounded per node (EdgeSampler), so the edge-touching object set
# is small by construction; the cap is a second fence so a pathological
# merged graph can never turn the post-solve refine into a directory-sized
# dense problem. Heaviest-degree objects win the slots.
_AFFINITY_MAX_ROWS = 4096


def _next_bucket(n: int, minimum: int = 256) -> int:
    """Pad batch sizes to power-of-two buckets so XLA compiles per bucket."""
    b = minimum
    while b < n:
        b *= 2
    return b


import functools as _functools


# Key-chunk size for the streamed obj_feat builder: the feature hook is
# called on bounded slices and rows land straight in the preallocated
# final block, so host peak stays O(n_pad x d) + one chunk instead of the
# 3x the old build-pull-concat pipeline materialized at 10M+ rows.
_OBJ_FEAT_STREAM_ROWS = int(
    os.environ.get("RIO_TPU_OBJ_FEAT_STREAM_ROWS") or 262_144
)


def _hier_feature_dtype() -> np.dtype:
    """Host dtype for the streamed feature block.

    ``RIO_TPU_HIER_FEAT_BF16=1`` stores features as bfloat16 (``ml_dtypes``
    ships with jax) — half the host memory and half the host->device bytes
    at 10M+ rows. The solve upcasts to fp32 on device, so only feature
    PRECISION is traded (8-bit mantissa): fine for the default hashed
    identity features (quality parity pinned in tests), but keep fp32 for
    custom feature hooks that encode small learned differences.
    """
    if os.environ.get("RIO_TPU_HIER_FEAT_BF16", "0") not in ("", "0"):
        try:
            import ml_dtypes

            return np.dtype(ml_dtypes.bfloat16)
        except Exception:  # pragma: no cover - ml_dtypes rides with jax
            pass
    return np.dtype(np.float32)


@_functools.lru_cache(maxsize=8)
def _pad_feature_block(pad: int, dim: int) -> np.ndarray:
    """Deterministic features for the hierarchical solve's pad rows.

    Cached per (pad count, dim): pad row i's feature never changes, and at
    large directories rebuilding up to bucket_n-n synthetic keys + crc32
    hashes per rebalance on the solver thread would be pure waste. Callers
    only read/concatenate the returned block (never mutate)."""
    if pad == 0:
        return np.zeros((0, dim), np.float32)
    return np.asarray(
        _hash_features([f"\x00pad:{i}" for i in range(pad)], dim), np.float32
    )


def _least_loaded_spread(load, alive, cap, n_real: int, count: int) -> np.ndarray:
    """Deterministic seats when the solver can't provide them: REAL
    nodes only, schedulable (alive AND capacity > 0) nodes before the
    rest, least-loaded first — and round-robin over ONLY the
    schedulable prefix when one exists (seating overflow on a dead,
    cordoned, or capacity-zero node while schedulable capacity exists
    would break cordon's no-new-seats contract and the operator's
    capacity=0 don't-place-here signal). When NO node is schedulable
    (the all-dead blip) every real node cycles — any real seat beats a
    pad index, and an alive-but-zero-capacity node must not absorb the
    whole cluster's overflow alone. (Load alone can't order this:
    ``clean_server`` zeroes a dead node's load, ranking fresh corpses
    first.)"""
    if n_real <= 0:
        raise NoSchedulableCapacity(
            "placement solve with no registered nodes: register_node/"
            "sync_members must run before any placement is requested"
        )
    a = np.asarray(alive)[:n_real]
    c = np.asarray(cap)[:n_real]
    sched = (a > 0) & (c > 0)
    order = np.lexsort((np.asarray(load)[:n_real], ~sched))
    n_sched = int(sched.sum())
    cycle = order[:n_sched] if n_sched > 0 else order
    return cycle[np.arange(count) % len(cycle)].astype(np.int32)


def _route_unseatable(
    assignment: np.ndarray, n_real: int, load: np.ndarray, alive, cap
) -> np.ndarray:
    """Defensive clamp: solver output must index the REAL node axis.

    Solvers run over the padded power-of-two node axis; pad slots carry
    zero capacity and are normally unreachable, and the zero-schedulable-
    capacity snapshot that CAN reach them (every node dead at once) is
    short-circuited before any solve (see ``_solve_chunk`` /
    ``rebalance``). This guard is the belt-and-braces behind that: if any
    other degenerate numerical case ever clips a row onto a pad slot, a
    pad index entering the directory would blow up every later
    ``_node_order[idx]`` resolution (lookup, persistence marks, load
    recount) — route such rows through the shared spread instead.
    """
    bad = assignment >= n_real
    if not bad.any():
        return assignment  # load/alive stay un-pulled (device arrays on TPU)
    out = assignment.copy()
    out[bad] = _least_loaded_spread(
        load, alive, cap, n_real, int(bad.sum())
    ).astype(assignment.dtype)
    return out


def _guard_sentinel_spill(repaired, real, m_axis: int, cap_alive):
    """Shared guard (see :func:`rio_tpu.ops.sinkhorn.route_sentinel_spill`);
    r4 trigger here: 10M objects, bucket 16,777,216 = exactly the fp32
    integer-precision boundary, lookup IndexError."""
    from ..ops.sinkhorn import route_sentinel_spill

    return route_sentinel_spill(repaired, real, m_axis, cap_alive)


@_functools.partial(
    jax.jit, static_argnames=("mode", "move_cost", "eps", "n_iters")
)
def _class_refresh_device(base, counts, cap_alive, g_seed, *, mode, move_cost, eps, n_iters):
    """Warm M x M class potential refresh, one jitted pipeline.

    The solvers are eager ``lax.scan`` builders — each un-jitted call
    re-traces the scan body (~160 ms of pure tracing at M=64, dwarfing
    the microseconds of device math). The jit wrapper is cached per
    (mode, shapes, config floats), so a delta event's refresh is
    sub-millisecond after the first churn event pays the compile. The
    config floats are STATIC on purpose: they change only with provider
    construction, and keeping them out of the traced arguments lets XLA
    fold the stay-put diagonal."""
    m = base.shape[0]
    ccost = jnp.broadcast_to(base[None, :], (m, m)) - (
        move_cost * jnp.eye(m, dtype=jnp.float32)
    )
    solver = scaling_sinkhorn if mode == "scaling" else sinkhorn
    _f, g, err = solver(
        ccost, counts, cap_alive, eps=eps, n_iters=n_iters, g_init=g_seed
    )
    return g, err


# -- solver convergence telemetry helpers (PR 11) ----------------------------

# Cumulative backend-compile seconds seen by this process's jax, fed by a
# jax.monitoring duration listener. Registered lazily on first use and
# gated defensively: the listener API has moved across jax versions, and
# telemetry must never break a solve — when unavailable, compile_ms stays
# -1 (unobserved) rather than lying with 0.
_COMPILE_WATCH: dict = {"total_s": 0.0, "ok": None}


def _compile_seconds() -> float:
    """Backend-compile seconds accumulated so far, or -1 if unobservable.

    Snapshot before and after a solve window to split ``solve_ms`` into
    compile vs execute — the signal the r5 TPU rounds needed (compile_s
    66→106 across "healthy" runs was the wedge precursor). Process-global
    on purpose: solves run one at a time in the provider's solver thread.
    """
    if _COMPILE_WATCH["ok"] is None:
        try:
            from jax import monitoring as _monitoring

            def _on_duration(event: str, duration: float, **_kw) -> None:
                if "compil" in event:
                    _COMPILE_WATCH["total_s"] += duration

            _monitoring.register_event_duration_secs_listener(_on_duration)
            _COMPILE_WATCH["ok"] = True
        except Exception:  # noqa: BLE001 - older/newer jax: no listener API
            _COMPILE_WATCH["ok"] = False
    return _COMPILE_WATCH["total_s"] if _COMPILE_WATCH["ok"] else -1.0


def _seed_warm_ratio(seed) -> float:
    """Warm fraction of a potential seed: finite entries / total.

    The solvers cold-fill non-finite seed entries to zero, so the finite
    fraction IS the warm-start hit ratio. No seed at all reads as 0.0
    (fully cold); callers pass -1 themselves for solves that take no seed.
    """
    if seed is None:
        return 0.0
    arr = np.asarray(seed)
    if arr.size == 0:
        return 0.0
    return float(np.mean(np.isfinite(arr)))


def _conv_fields(conv: dict | None) -> dict:
    """Normalize a solve's convergence record into SolveStats kwargs."""
    conv = conv or {}
    return {
        "solver_iters": int(conv.get("solver_iters", 0)),
        "residual": float(conv.get("residual", -1.0)),
        "warm_ratio": float(conv.get("warm_ratio", -1.0)),
        "compile_ms": float(conv.get("compile_ms", -1.0)),
        "exec_ms": float(conv.get("exec_ms", -1.0)),
        "chunks": int(conv.get("chunks", 0)),
        "chunk_ms": [float(x) for x in conv.get("chunk_ms", ())],
        "devices": int(conv.get("devices", 0)),
    }


def _conv_timing(conv: dict, t0: float, c0: float) -> tuple[float, dict]:
    """Close a solve window: wall ms plus the compile/execute split."""
    ms = (time.perf_counter() - t0) * 1e3
    c1 = _compile_seconds()
    if c0 >= 0.0 and c1 >= 0.0:
        conv["compile_ms"] = round((c1 - c0) * 1e3, 3)
        conv["exec_ms"] = round(max(ms - conv["compile_ms"], 0.0), 3)
    return ms, conv


def _apply_class_quotas(quotas: np.ndarray, cur_idx: np.ndarray) -> np.ndarray:
    """Expand (M x M) class quotas into a per-object assignment, O(N + M^2).

    Objects within a class (= current seat) are interchangeable, so laying
    each class's own column FIRST keeps ``quotas[k, k]`` objects exactly
    where they are — the move-minimal application of the collapsed solve
    (``rio_tpu.ops.structured.class_quotas``).
    """
    m = quotas.shape[0]
    out = np.empty(cur_idx.shape[0], np.int32)
    order = np.argsort(cur_idx, kind="stable")
    counts = np.bincount(cur_idx, minlength=m)
    start = np.concatenate([[0], np.cumsum(counts)[:-1]])
    all_cols = np.arange(m)
    for k in range(m):
        c = int(counts[k])
        if c == 0:
            continue
        cols = np.concatenate([[k], np.delete(all_cols, k)])
        targets = np.repeat(cols, quotas[k][cols])
        if targets.shape[0] < c:  # belt-and-braces vs float drift upstream
            targets = np.concatenate(
                [targets, np.full(c - targets.shape[0], k, np.int32)]
            )
        out[order[start[k] : start[k] + c]] = targets[:c]
    return out


# Anti-affinity penalty for the multi-seat (replica) solve. Relative to the
# default eps this puts cost-range/eps far beyond the exp underflow knee
# (~88) — exactly the wide-cost-range regime the PER-ROW gauge shift exists
# for (CLAUDE.md; test_scaling_survives_wide_cost_ranges). The log-domain
# sinkhorn used below is stable at any range.
_ANTI_AFFINITY_COST = 1e4


def multi_seat_plan(
    primary_idx: np.ndarray,
    k: int,
    load: np.ndarray,
    cap: np.ndarray,
    alive: np.ndarray,
    *,
    eps: float = 0.05,
    n_iters: int = 30,
) -> np.ndarray:
    """K standby seats per object under hard anti-affinity.

    The multi-seat problem collapses the same way the flat rebalance does:
    every object with the same *forbidden set* (primary + seats chosen in
    earlier rounds) has an identical cost row, so each of the K rounds is a
    class-collapsed ``(C x M)`` solve — ``C <= M`` on the first round, the
    uniform case the O(M^2) path covers — not an ``(N x M)`` one. Each
    round runs the log-domain Sinkhorn (:func:`rio_tpu.ops.sinkhorn.sinkhorn`,
    the per-row gauge-shifted semantic reference) over the class cost with
    ``_ANTI_AFFINITY_COST`` on forbidden columns, then rounds each class's
    soft plan row to integer seat quotas by largest remainder. Forbidden
    columns are zeroed before rounding, so a primary and its standbys can
    NEVER co-locate; classes with no schedulable allowed column get their
    seat back as -1 (degraded replication, never a violation).

    Returns an ``(n, k)`` int32 array of node indices, -1 for unfillable
    seats. Pure function of its snapshot inputs — safe to run in a solver
    thread (the loop-side-snapshot rule) and to property-test directly.
    """
    primary_idx = np.asarray(primary_idx, np.int64)
    n = int(primary_idx.shape[0])
    m = int(cap.shape[0])
    seats = np.full((n, k), -1, np.int32)
    if n == 0 or k <= 0:
        return seats
    load = np.asarray(load, np.float32).copy()
    cap_alive = np.asarray(cap, np.float32) * (np.asarray(alive, np.float32) > 0)
    taken = np.zeros((n, m), bool)
    has_primary = (primary_idx >= 0) & (primary_idx < m)
    taken[np.arange(n)[has_primary], primary_idx[has_primary]] = True
    for r in range(k):
        classes, inverse = np.unique(taken, axis=0, return_inverse=True)
        counts = np.bincount(inverse, minlength=classes.shape[0]).astype(
            np.float32
        )
        allowed = (~classes) & (cap_alive > 0)[None, :]
        solvable = allowed.any(axis=1)
        if not solvable.any():
            break
        # Load-aware base cost (fill ratio) + the anti-affinity wall.
        fill = load / np.maximum(cap_alive, 1e-6)
        cost = np.where(allowed, fill[None, :], _ANTI_AFFINITY_COST).astype(
            np.float32
        )
        res = sinkhorn(
            jnp.asarray(cost),
            jnp.asarray(counts * solvable),
            jnp.asarray(cap_alive),
            eps=eps,
            n_iters=n_iters,
        )
        f = np.asarray(res.f, np.float64)[:, None]
        g = np.asarray(res.g, np.float64)[None, :]
        with np.errstate(invalid="ignore"):
            expo = np.where(
                np.isfinite(f) & np.isfinite(g), f + g - cost, -np.inf
            )
        weights = np.exp(np.clip(expo / eps, -80.0, 80.0)) * allowed
        for c in np.nonzero(solvable)[0]:
            rows_c = np.nonzero(inverse == c)[0]
            w = weights[c]
            if w.sum() <= 0:
                w = allowed[c].astype(np.float64)
            share = w / w.sum() * rows_c.shape[0]
            quota = np.floor(share).astype(np.int64)
            short = rows_c.shape[0] - int(quota.sum())
            if short > 0:
                rem_order = np.argsort(-(share - quota), kind="stable")
                quota[rem_order[:short]] += 1
            targets = np.repeat(np.arange(m), quota)[: rows_c.shape[0]]
            seats[rows_c, r] = targets
            taken[rows_c, targets] = True
            np.add.at(load, targets, 1.0)
    return seats


@dataclass
class _NodeSlot:
    address: str
    capacity: float = 1.0
    alive: bool = True
    cordoned: bool = False  # drained: serving, but priced out of the solver
    load: float = 0.0
    index: int = 0
    # Measured-load capacity multiplier from sync_load (ClusterLoadView):
    # 1.0 idle, down to MIN_DERATE for an overloaded node. Quantized so
    # per-second load reports don't thrash the solve epoch.
    reported_derate: float = 1.0


@dataclass
class PlanState:
    """The previous committed solve, persisted as a first-class object.

    This is what turns the solver architecture from "re-solve the world"
    into "maintain a plan incrementally": a churn event no longer pays the
    full-directory solve — ``rebalance`` re-solves ONLY the displaced +
    new objects against the plan's residual capacity, warm-starting the
    Sinkhorn potentials from here (see ``_delta_solve``). The full solve
    remains the fallback when the displaced fraction exceeds
    ``delta_threshold``, after ``max_delta_solves`` consecutive deltas
    (staleness), or when the transport-cost audit trips (``stale``).

    Snapshot discipline: a PlanState is immutable after construction and
    atomically swapped on ``self._plan`` under the provider lock — the
    solver thread reads the snapshot it was handed, never the live field.
    """

    # (node_axis,) node potentials of the committing solve (jax array;
    # None for solves that produce none, e.g. greedy).
    g: object | None
    # (G,) coarse-stage group potentials from a hierarchical solve (numpy;
    # None for flat solves) — warm seed for the next coarse stage.
    coarse_g: object | None
    # (node_axis,) PLANNED per-node seat counts at commit. With a
    # move_sink the directory converges to this as handoffs land; delta
    # displacement is always recomputed from the live directory snapshot,
    # so this is diagnostic, not load-bearing.
    seat_counts: np.ndarray
    epoch: int  # directory epoch the plan was committed at
    liveness_fp: frozenset  # schedulable node indices at commit
    delta_solves: int = 0  # consecutive deltas since the last full solve
    stale: bool = False  # quality audit tripped: next solve goes full


@dataclass
class SolveStats:
    """Diagnostics from the last full re-solve."""

    n_objects: int = 0
    n_nodes: int = 0
    solve_ms: float = 0.0
    apply_ms: float = 0.0  # mover-only directory update (host, under lock)
    moved: int = 0
    # Objects the solve actually re-solved: the displaced set for a
    # "*+delta" solve, the whole directory for a full one.
    displaced: int = 0
    epoch: int = 0
    mode: str = "none"
    discarded: bool = False
    # -- per-solve convergence record (PR 11) --------------------------------
    # Scalars flow into `rio.placement_solve.*` gauges automatically via
    # otel.stats_gauges; -1 means "not applicable / unobserved" (greedy has
    # no residual, an old jax has no compile listener) — never 0, which
    # would read as a perfect value.
    solver_iters: int = 0  # configured iterations (fixed-length scans)
    residual: float = -1.0  # final L1 column-marginal violation
    warm_ratio: float = -1.0  # finite fraction of the warm-start seed
    compile_ms: float = -1.0  # backend-compile share of solve_ms
    exec_ms: float = -1.0  # solve_ms minus compile_ms
    chunks: int = 0  # chunked-hierarchical chunk count (0 = unchunked)
    chunk_ms: list = field(default_factory=list)  # per-chunk wall ms
    # Mesh devices the solve sharded over: 1 = single-chip hierarchical,
    # 0 = not a hierarchical solve. chunks x devices is the cell count of
    # a mesh x chunk composed solve (mode suffix "+mesh_chunk").
    devices: int = 0
    # Bounded record of prior completed solves (most recent last, each with
    # an empty history of its own) — lets the daemon/operators see churn
    # cadence and whether solve/apply cost or move counts drift over time.
    history: list = field(default_factory=list)

    HISTORY_LIMIT = 32

    def history_gauges(self) -> dict[str, float]:
        """Rolling solve-history summary, scrape-ready.

        ``stats_gauges`` flattens only the last solve's scalar fields (and
        skips ``history`` — it's a list); this folds the retained window
        into trend gauges so a dashboard sees churn cadence without
        shipping the whole ring over the wire.
        """
        window = [*self.history, self] if self.mode != "none" else list(self.history)
        out = {"rio.placement_solve.history.len": float(len(window))}
        if not window:
            return out
        solves = [float(s.solve_ms) for s in window]
        out["rio.placement_solve.history.solve_ms_last"] = solves[-1]
        out["rio.placement_solve.history.solve_ms_mean"] = sum(solves) / len(solves)
        out["rio.placement_solve.history.solve_ms_max"] = max(solves)
        out["rio.placement_solve.history.moved_total"] = float(
            sum(int(s.moved) for s in window)
        )
        out["rio.placement_solve.history.delta_fraction"] = sum(
            1.0 for s in window if "delta" in str(s.mode)
        ) / len(window)
        out["rio.placement_solve.history.discarded_total"] = float(
            sum(1 for s in window if s.discarded)
        )
        # Convergence trend: last/worst residual over solves that HAVE one
        # (-1 = n/a is excluded so a greedy solve can't mask divergence),
        # plus the cumulative compile cost — the r5 "compile_s rising"
        # wedge precursor, now a scrapeable counter.
        residuals = [float(s.residual) for s in window if s.residual >= 0.0]
        if residuals:
            out["rio.placement_solve.history.residual_last"] = residuals[-1]
            out["rio.placement_solve.history.residual_max"] = max(residuals)
        compiles = [float(s.compile_ms) for s in window if s.compile_ms >= 0.0]
        if compiles:
            out["rio.placement_solve.history.compile_ms_total"] = sum(compiles)
        # Composed-path attribution (mesh x chunk): how wide the last
        # hierarchical solves ran, and the first-chunk dispatch cost — the
        # first chunk carries any fresh compile, so a FLAT first_chunk_ms
        # across growing directories is the compile-pinning invariant made
        # scrapeable (rising = the jit cache stopped covering the shape).
        chunked = [s for s in window if int(s.chunks) > 0]
        if chunked:
            out["rio.placement_solve.history.chunks_last"] = float(
                chunked[-1].chunks
            )
            out["rio.placement_solve.history.chunks_max"] = float(
                max(int(s.chunks) for s in chunked)
            )
        meshed = [s for s in window if int(getattr(s, "devices", 0)) > 0]
        if meshed:
            out["rio.placement_solve.history.devices_last"] = float(
                meshed[-1].devices
            )
        first_chunks = [float(s.chunk_ms[0]) for s in window if s.chunk_ms]
        if first_chunks:
            out["rio.placement_solve.history.first_chunk_ms_last"] = (
                first_chunks[-1]
            )
            out["rio.placement_solve.history.first_chunk_ms_max"] = max(
                first_chunks
            )
        return out


class JaxObjectPlacement(ObjectPlacement):
    """Batched, device-solved object directory (drop-in ObjectPlacement)."""

    def __init__(
        self,
        *,
        eps: float = 0.05,
        n_iters: int = 30,
        mode: str = "auto",
        mesh=None,
        node_axis_size: int = 64,
        move_cost: float = 0.5,
        obj_features=None,
        node_features=None,
        affinity_tracker: "AffinityTracker | None" = None,
        object_costs=None,
        delta_threshold: float = 0.25,
        max_delta_solves: int = 8,
        delta_audit_ratio: float = 1.05,
        affinity_weight: float = 0.0,
        affinity_passes: int = 3,
        affinity_host_factor: float = 0.5,
        affinity_slack: float = 1.25,
    ) -> None:
        self._eps = eps
        self._n_iters = n_iters
        # Incremental (delta) rebalance knobs: a churn re-solve goes
        # through the delta path while the displaced fraction stays at or
        # below delta_threshold (0 disables deltas entirely), falls back
        # to a full solve after max_delta_solves consecutive deltas
        # (staleness bound on the warm potentials), and whenever the
        # transport-cost audit finds the delta plan worse than
        # delta_audit_ratio x the ideal quota cost.
        self._delta_threshold = delta_threshold
        self._max_delta_solves = max_delta_solves
        self._delta_audit_ratio = delta_audit_ratio
        # "auto" resolves LAZILY at the first solve: jax.default_backend()
        # initializes the jax backend, and constructing a provider must
        # never block on that — against a wedged TPU relay a backend init
        # can hang indefinitely (observed r3: it froze the whole bench
        # orchestrator), while the first actual solve initializes the
        # backend anyway.
        self._mode = mode
        self._mesh = mesh
        # Stay-put discount applied to each object's CURRENT seat during a
        # full re-solve: a move costs a state reload + cold cache at the
        # application layer, so the objective must price it. With
        # move_cost/eps >> 1 the soft plan concentrates on the current seat
        # unless capacity (dead nodes, skew) forces a move — a churn
        # re-solve then moves ~the displaced share, not a global reshuffle.
        self._move_cost = move_cost
        # Hierarchical-mode feature hooks: callables (keys/addresses ->
        # (n, d) ndarray). Default is hashed identity — a deterministic
        # balancing proxy; plug an AffinityTracker (or anything encoding
        # state size / cache warmth / request rate) to make the OT affinity
        # term carry real locality signal.
        has_affinity = bool(obj_features or node_features or affinity_tracker)
        if has_affinity and mode not in ("hierarchical", "auto"):
            # Flat modes build per-node costs only and would silently
            # ignore the hooks — fail at construction, not at solve time.
            # mode="auto" is allowed: with a locality signal present it
            # resolves to "hierarchical" (see _solver_mode).
            raise ValueError(
                "obj_features/node_features/affinity_tracker are only consumed "
                f'by mode="hierarchical" (got mode={mode!r})'
            )
        self._has_affinity = has_affinity
        # Carrying the tracker on the provider lets the Server auto-wire
        # AffinityTracker.observe into the dispatch path (every served
        # request updates the object's locality feature — no app code).
        self.affinity_tracker = affinity_tracker
        if affinity_tracker is not None:
            obj_features = obj_features or affinity_tracker.obj_features
            node_features = node_features or affinity_tracker.node_features
        self._obj_features = obj_features or _hash_features
        self._node_features = node_features or _hash_features
        # Per-object move prices (keys -> (n,) weights, 1.0 = baseline):
        # scales the stay-put discount so hot/heavy actors cost more to
        # relocate than cold ones (rio_tpu/load). Works with EVERY mode
        # that prices moves (the OT solves); defaults to the tracker's
        # measured move_weights when one is wired. Uniform output is
        # equivalent to the classic scalar move_cost and keeps the
        # collapsed O(M^2) fast path; non-uniform weights route flat
        # solves through the dense (or at scale, hierarchical) pipeline.
        if object_costs is None and affinity_tracker is not None:
            object_costs = affinity_tracker.move_weights
        self._object_costs = object_costs
        # Communication-graph refinement (rio_tpu/affinity): after every
        # FULL solve, `affinity_passes` alternating linearized OT passes
        # fold the current assignment's neighbor attraction into per-object
        # cost rows and re-run the unchanged Sinkhorn core over the
        # edge-touching subset. weight 0.0 (the default) disables the term
        # entirely; the delta path never refines (its warm potentials
        # assume the pure balance objective).
        self._affinity_weight = float(affinity_weight)
        self._affinity_passes = max(1, int(affinity_passes))
        # Attraction credit for landing on a DIFFERENT worker shard of the
        # same host (same address up to the ":port"): 0 = only exact
        # co-seating counts, 1 = any same-host seat is as good as local.
        # Intermediate values make the refine optimize at two
        # granularities at once — node first, host second.
        self._affinity_host_factor = min(1.0, max(0.0, affinity_host_factor))
        # Column-capacity slack for the refine's subset solve. Strictly
        # balanced capacities provably block the simplest win (two chatty
        # objects on two equal nodes can never co-locate — either move
        # overflows a node by one), so the refine may overfill a node by
        # this factor; the acceptance check still rejects passes whose
        # total objective (balance overflow + weighted cut) regresses.
        self._affinity_slack = max(1.0, float(affinity_slack))
        # (src, dst) -> normalized byte-rate weight, undirected keys with
        # src < dst. Atomic-swap discipline: set_edge_graph builds a fresh
        # dict, the solver thread snapshots the reference.
        self._edge_graph: dict[tuple[str, str], float] = {}
        # Per-refine pass history ([{pass, cut, total, accepted}, ...]) —
        # the monotonicity evidence tests and telemetry read.
        self._affinity_history: list[dict] = []
        # Host-mirrored directory: "{type}.{id}" -> node index.
        self._placements: dict[str, int] = {}
        # Replica rows: "{type}.{id}" -> (standby addresses, epoch). Kept by
        # address (not node index) so a standby row survives node-axis
        # growth and mirrors the durable backends' schema 1:1.
        self._standby_rows: dict[str, tuple[list[str], int]] = {}
        # Per-node key index (node index -> keys): keeps clean_server and
        # load recounts O(objects-on-node), the same reason the Redis
        # backend keeps a per-server set (object_placement/redis.py).
        self._by_node: dict[int, set[str]] = {}
        self._nodes: dict[str, _NodeSlot] = {}
        self._node_order: list[str] = []  # index -> address (never shrinks)
        self._node_axis = node_axis_size  # static node axis (padded)
        self._epoch = 0
        self._g: jax.Array | None = None  # cached node potentials (padded axis)
        # Liveness fingerprint the cached potentials were solved over: the
        # schedulable node indices at commit. Potentials stay valid while
        # every one of those nodes REMAINS schedulable (churn on unrelated
        # nodes — registrations, dead->alive flips — never touches them);
        # a solved-over node leaving the set drops the cache (its finite g
        # entry would keep attracting the warm assign_batch path).
        self._g_fp: frozenset | None = None
        # Previous committed solve (potentials + seat counts + epoch) —
        # the incremental-rebalance state. See PlanState.
        self._plan: PlanState | None = None
        # Liveness-flip subscribers (the placement daemon's event kick).
        self._churn_listeners: list = []
        self._lock = asyncio.Lock()
        self.stats = SolveStats()

    def _solver_mode(self) -> str:
        """Resolve ``mode="auto"`` on first use (first backend touch).

        The rule (measured; see ``tests/test_affinity_payoff.py`` and
        BENCH_DETAIL.json):

        * **locality signal present** (an ``AffinityTracker`` or feature
          hooks were wired) → ``hierarchical``: it is the only mode that
          consumes per-object affinity, its payoff is large (~4x fewer
          state reloads after churn on a warm-traffic workload), and its
          O(N*(G+S+d)) cost is accelerator-independent — cheaper than the
          dense solve everywhere.
        * otherwise, per-node costs only: the dense OT solve wins on an
          accelerator (measured 35x the SQL baseline on TPU v5e) but loses
          to the thing it replaces on host CPUs, where the O(N log M)
          greedy waterfill is the right default (measured ~26x the
          baseline). Flat OT rebalances additionally collapse to O(M^2)
          either way (see ``rebalance``).
        """
        if self._mode == "auto":
            if self._has_affinity:
                self._mode = "hierarchical"
            else:
                self._mode = (
                    "sinkhorn" if jax.default_backend() == "tpu" else "greedy"
                )
        return self._mode

    def _archived_history(self) -> list:
        """Current stats (if any solve/attempt happened) appended to its
        own history, flattened and bounded — the record the NEXT stats
        object carries. Lock held by callers."""
        prior = self.stats
        if not prior.epoch:  # the never-solved default carries no event
            return []
        return (prior.history + [replace(prior, history=[])])[
            -SolveStats.HISTORY_LIMIT:
        ]

    # -------------------------------------------- potentials / churn events
    def _sched_fp(self) -> frozenset:
        """Schedulable-node fingerprint: indices of nodes that can take
        NEW seats right now (alive, not cordoned, capacity > 0)."""
        return frozenset(
            s.index
            for s in self._nodes.values()
            if s.alive and not s.cordoned and s.capacity > 0
        )

    def _invalidate_potentials(self) -> None:
        """Version the cached potentials by liveness fingerprint instead
        of nulling them on every membership event: ``_g`` survives churn
        on UNRELATED nodes (new registrations, dead->alive recoveries,
        uncordons — their g entries are -inf, so the warm ``assign_batch``
        path never seats there until the next solve refreshes them, which
        is merely conservative). Only a solved-over node LEAVING the
        schedulable set (death, cordon, capacity loss) drops the cache:
        its finite potential would keep pulling new placements onto a node
        that must not take them."""
        if self._g is None:
            return
        if self._g_fp is None or not (self._g_fp <= self._sched_fp()):
            self._g = None
            self._g_fp = None

    def add_churn_listener(self, cb) -> None:
        """Register a zero-arg callable fired after every liveness-affecting
        change (``sync_members`` flips, ``cordon``/``uncordon``,
        ``clean_server``). Fired on the event loop, synchronously with the
        mutation — listeners must only flag/schedule (the placement
        daemon's event kick sets an ``asyncio.Event``), never block."""
        self._churn_listeners.append(cb)

    def _notify_churn(self) -> None:
        for cb in list(self._churn_listeners):
            try:
                cb()
            except Exception:  # noqa: BLE001 - listeners never break liveness
                pass

    # ------------------------------------------------- directory internals
    def _set_placement(self, key: str, idx: int) -> bool:
        """Point ``key`` at node ``idx`` keeping the per-node index in sync.

        Returns True when the placement actually changed (lock held).
        """
        old = self._placements.get(key)
        if old == idx:
            return False
        if old is not None:
            self._by_node.get(old, set()).discard(key)
        self._placements[key] = idx
        self._by_node.setdefault(idx, set()).add(key)
        return True

    def _drop_placement(self, key: str) -> int | None:
        idx = self._placements.pop(key, None)
        if idx is not None:
            self._by_node.get(idx, set()).discard(key)
        return idx

    def _set_standby_row(self, key: str, addresses: list[str], epoch: int) -> None:
        """Single mutation seam for replica rows (lock held) — like
        ``_set_placement``, so write-behind subclasses see every change."""
        self._standby_rows[key] = (list(addresses), epoch)

    def _drop_standby_row(self, key: str) -> None:
        self._standby_rows.pop(key, None)

    # ---------------------------------------------------------------- nodes
    def _node_index(self, address: str) -> int:
        slot = self._nodes.get(address)
        if slot is None:
            idx = len(self._node_order)
            if idx >= self._node_axis:
                # Grow the static node axis (rare; forces one recompile
                # tier). Cached potentials AND the incremental plan carry
                # old-axis shapes — both must go.
                self._node_axis *= 2
                self._g = None
                self._g_fp = None
                self._plan = None
            slot = _NodeSlot(address=address, index=idx)
            self._nodes[address] = slot
            self._node_order.append(address)
            self._epoch += 1
        return slot.index

    def register_node(self, address: str, *, capacity: float = 1.0) -> None:
        idx = self._node_index(address)
        self._nodes[address].capacity = capacity
        self._nodes[address].alive = True

    def sync_members(self, members) -> None:
        """Feed gossip liveness into the cost model.

        ``members`` is an iterable with ``address()``/``active`` (the shape of
        ``rio_tpu.cluster.storage.Member``). Unknown members are registered;
        known members get their liveness updated. Dead nodes keep their index
        (static shapes) but are priced out of the cost matrix.
        """
        seen = set()
        changed = False
        for m in members:
            addr = getattr(m, "address", None)
            if callable(addr):
                addr = addr()
            if addr is None:
                addr = str(m)
            active = bool(getattr(m, "active", True))
            seen.add(addr)
            if addr not in self._nodes:
                self._node_index(addr)
                changed = True
            slot = self._nodes[addr]
            if slot.alive != active:
                slot.alive = active
                changed = True
        for addr, slot in self._nodes.items():
            if addr not in seen and slot.alive:
                slot.alive = False
                changed = True
        if changed:
            self._epoch += 1
            # Fingerprint-versioned, NOT nulled: churn on unrelated nodes
            # (new members, dead->alive recoveries) keeps the warm cache;
            # only a solved-over node leaving the schedulable set drops it.
            self._invalidate_potentials()
            self._notify_churn()

    # Derates quantize to 1/8 steps: sync_load runs every monitor tick
    # (~seconds), and an un-quantized float would change on every call,
    # bumping the epoch each time — which would discard every in-flight
    # solve longer than a tick (the big ones are minutes). A bucket flip
    # is a real regime change and worth the re-solve.
    _DERATE_STEP = 8.0

    def sync_load(self, view) -> None:
        """Feed measured cluster load (``rio_tpu.load.ClusterLoadView``)
        into the cost model: each node's solver capacity column becomes
        ``capacity * derate``. Loop-side and lock-free, exactly like
        ``sync_members`` (snapshot-solve-apply covers concurrent solves);
        called by the LoadMonitor's view refresh and the placement
        daemon's poll. ``view=None`` (or an unknown/stale entry) resets a
        node to its full capacity."""
        changed = False
        for addr, slot in self._nodes.items():
            d = 1.0 if view is None else float(view.derate(addr))
            if not (d == d):  # NaN guard (view sanitizes; belt-and-braces)
                d = 1.0
            d = min(1.0, max(0.1, d))
            q = round(d * self._DERATE_STEP) / self._DERATE_STEP
            if q != slot.reported_derate:
                slot.reported_derate = q
                changed = True
        if changed:
            self._epoch += 1
            # Derates floor at 0.1 and never zero a capacity column, so no
            # node LEAVES the schedulable set here — the fingerprint check
            # keeps the potentials (they merely under-react to the new
            # derate until the next solve refreshes them). No churn
            # notification: load drift is the daemon's normal poll work.
            self._invalidate_potentials()

    # --------------------------------------------------------------- drain
    def cordon(self, address: str) -> None:
        """Drain a node gracefully (the kubectl-cordon analog; no reference
        counterpart — its only exit is death + lazy re-allocation).

        The node keeps serving its current objects, but the solver prices
        it like a dead node: no NEW allocations land there, and the next
        ``rebalance()`` re-seats its population onto the remaining nodes —
        moving exactly that share, per the stay-put discount. Then stop the
        server with nothing displaced. Loop-side and lock-free, like
        ``sync_members`` (the snapshot-solve-apply discipline covers it).
        """
        slot = self._nodes.get(address)
        if slot is None:
            raise KeyError(f"unknown node {address!r}")
        if slot.cordoned:
            return
        others = any(
            s.alive and not s.cordoned and s.capacity > 0
            for a, s in self._nodes.items()
            if a != address
        )
        if not others:
            raise RuntimeError(
                f"refusing to cordon {address!r}: no other schedulable "
                f"node would remain"
            )
        slot.cordoned = True
        self._epoch += 1
        self._invalidate_potentials()
        self._notify_churn()

    def uncordon(self, address: str) -> None:
        slot = self._nodes.get(address)
        if slot is None:
            raise KeyError(f"unknown node {address!r}")
        if slot.cordoned:
            slot.cordoned = False
            self._epoch += 1
            self._invalidate_potentials()
            self._notify_churn()

    @property
    def cordoned(self) -> set[str]:
        return {a for a, s in self._nodes.items() if s.cordoned}

    # ------------------------------------------------------- device vectors
    def _node_vectors(self) -> tuple[jax.Array, jax.Array, jax.Array]:
        n = self._node_axis
        load = np.zeros((n,), np.float32)
        cap = np.zeros((n,), np.float32)
        alive = np.zeros((n,), np.float32)
        for addr in self._node_order:
            s = self._nodes[addr]
            load[s.index] = s.load
            # Measured load shrinks the capacity column (sync_load): a hot
            # node takes proportionally fewer new/rebalanced seats, with a
            # floor so it never vanishes from the solve entirely.
            cap[s.index] = s.capacity * s.reported_derate
            # Cordoned nodes price exactly like dead ones (no NEW seats; a
            # rebalance drains them) — but their directory rows stand and
            # they keep serving until the operator stops them.
            alive[s.index] = 1.0 if (s.alive and not s.cordoned) else 0.0
        return jnp.asarray(load), jnp.asarray(cap), jnp.asarray(alive)

    def _no_schedulable_capacity_host(self) -> bool:
        """Loop-side zero-capacity predicate over HOST node state, taken at
        the same moment as the ``_node_vectors`` snapshot. Never reads the
        device arrays: an eager device->host pull per placement chunk costs
        ~300 ms through the TPU tunnel, and this predicate runs on every
        chunk and every rebalance."""
        return not any(
            s.alive and not s.cordoned and s.capacity > 0
            for s in self._nodes.values()
        )

    def _recount_loads(self) -> None:
        for s in self._nodes.values():
            s.load = float(len(self._by_node.get(s.index, ())))

    # ------------------------------------------------------ trait: lookups
    async def update(self, item: ObjectPlacementItem) -> None:
        key = str(item.object_id)
        async with self._lock:
            if item.server_address is None:
                self._drop_placement(key)
            else:
                self._set_placement(key, self._node_index(item.server_address))
            self._epoch += 1

    async def lookup(self, object_id: ObjectId) -> str | None:
        idx = self._placements.get(str(object_id))
        if idx is None:
            return None
        addr = self._node_order[idx]
        return addr

    async def clean_server(self, address: str) -> None:
        async with self._lock:
            slot = self._nodes.get(address)
            if slot is None:
                return
            slot.alive = False
            slot.load = 0.0  # its placements are gone; keep fair-share math honest
            # O(objects-on-node) via the per-node index — a full-directory
            # scan here would be a multi-second GIL stall at the 10M tier.
            # Dropped through _drop_placement (the single mirror-mutation
            # seam) so subclasses tracking writes see every key.
            for k in list(self._by_node.get(slot.index, ())):
                self._drop_placement(k)
            self._by_node.pop(slot.index, None)
            self._epoch += 1
            self._invalidate_potentials()
            self._notify_churn()

    async def remove(self, object_id: ObjectId) -> None:
        async with self._lock:
            key = str(object_id)
            if key in self._standby_rows:
                self._drop_standby_row(key)
            if self._drop_placement(key) is not None:
                self._epoch += 1

    def count(self) -> int:
        return len(self._placements)

    # ------------------------------------------------------- replica rows
    async def set_standbys(self, object_id: ObjectId, addresses: list[str]) -> int:
        key = str(object_id)
        async with self._lock:
            _, epoch = self._standby_rows.get(key, ([], 0))
            if addresses or epoch:
                self._set_standby_row(key, list(addresses), epoch)
            elif key in self._standby_rows:
                self._drop_standby_row(key)
            return epoch

    async def standbys(self, object_id: ObjectId) -> tuple[list[str], int]:
        # Lock-free read, like lookup(): single-assignment snapshot of an
        # immutable (list, epoch) tuple.
        held, epoch = self._standby_rows.get(str(object_id), ([], 0))
        return sanitize_standby_row(held, epoch)

    async def promote_standby(
        self, object_id: ObjectId, address: str, expected_epoch: int
    ) -> int | None:
        key = str(object_id)
        async with self._lock:
            held, epoch = self._standby_rows.get(key, ([], 0))
            if epoch != expected_epoch or address not in held:
                return None
            self._set_standby_row(
                key, [a for a in held if a != address], epoch + 1
            )
            self._set_placement(key, self._node_index(address))
            self._epoch += 1
            return epoch + 1

    async def assign_standbys(
        self, object_ids: list[ObjectId], k: int = 1
    ) -> list[list[str]]:
        """Compute K anti-affinity standby seats per object (compute only —
        the caller persists the choice through :meth:`set_standbys`, so the
        epoch fence stays in one place).

        Snapshot-solve discipline as everywhere else: node vectors and
        primary seats are snapshotted under the lock on the loop, the
        class-collapsed :func:`multi_seat_plan` runs in a thread, and no
        live provider state is read from that thread.
        """
        if not object_ids or k <= 0:
            return [[] for _ in object_ids]
        async with self._lock:
            keys = [str(o) for o in object_ids]
            primary = np.asarray(
                [self._placements.get(key, -1) for key in keys], np.int64
            )
            load, cap, alive = self._node_vectors()
            node_order = list(self._node_order)
            no_capacity = self._no_schedulable_capacity_host()
        if no_capacity:
            return [[] for _ in object_ids]
        eps, n_iters = self._eps, self._n_iters

        def _solve() -> np.ndarray:
            return multi_seat_plan(
                primary,
                k,
                np.asarray(load),
                np.asarray(cap),
                np.asarray(alive),
                eps=eps,
                n_iters=n_iters,
            )

        seats = await asyncio.to_thread(_solve)
        n_real = len(node_order)
        return [
            [node_order[j] for j in row if 0 <= j < n_real]
            for row in seats
        ]

    # ------------------------------------------------------- batched solve
    async def lookup_batch(self, object_ids: list[ObjectId]) -> list[str | None]:
        out: list[str | None] = []
        for oid in object_ids:
            idx = self._placements.get(str(oid))
            out.append(None if idx is None else self._node_order[idx])
        return out

    async def assign_batch(self, object_ids: list[ObjectId]) -> list[str]:
        """Place a batch of (possibly new) objects in one device call.

        Already-placed objects keep their seat; unplaced ones are assigned via
        the cached node potentials when available (incremental fast path),
        falling back to a greedy balanced solve. This is the replacement for
        the reference's one-SQL-roundtrip-per-object allocate
        (``service.rs:241-253``).

        The lock is taken PER CHUNK, not across the whole batch (ADVICE r4):
        a 10M-key batch solves for ~46 s, and holding ``self._lock`` across
        it starved ``update``/``remove``/``clean_server``/``rebalance`` and
        every other ``assign_batch`` caller for the duration. Each chunk
        re-checks membership under its lock hold (two callers racing on
        overlapping keys place each key once), and the final address
        resolution re-validates: a concurrent ``remove``/``clean_server``
        between chunks may have dropped keys placed earlier, so stragglers
        are re-placed under one last lock hold — no unlocked await separates
        that re-place from the read, so the resolution cannot miss.

        Raises :class:`rio_tpu.errors.NoSchedulableCapacity` (a
        ``ValueError`` subclass) when no node has registered yet — the
        batch cannot be seated anywhere, and silently parking it would
        strand every key.
        """
        keys = [str(o) for o in object_ids]
        for start in range(0, len(keys), self._MAX_PLACE_CHUNK):
            chunk = keys[start : start + self._MAX_PLACE_CHUNK]
            async with self._lock:
                unplaced = [k for k in chunk if k not in self._placements]
                if unplaced:
                    await self._place_chunk_locked(unplaced)
        async with self._lock:
            missing = [k for k in keys if k not in self._placements]
            if missing:
                await self._place_keys_async(missing)
            return [self._node_order[self._placements[k]] for k in keys]

    # Bounds the (bucket x node_axis) working set of one placement solve:
    # 262,144 x 1,024 fp32 is ~1 GB of sort/cumsum temps. A single
    # unchunked 10M-key batch padded its bucket to 16.7M rows and
    # materialized ~100 GB of temps on the CPU backend (r4) — chunking
    # keeps any batch size at a constant footprint, and the waterfill
    # carries the updated node load into the next chunk so balance holds
    # across the whole batch.
    _MAX_PLACE_CHUNK = 262_144

    async def _place_keys_async(self, keys: list[str]) -> None:
        """Chunked placement under a CALLER-held lock (straggler path)."""
        for start in range(0, len(keys), self._MAX_PLACE_CHUNK):
            await self._place_chunk_locked(keys[start : start + self._MAX_PLACE_CHUNK])

    async def _place_chunk_locked(self, chunk: list[str]) -> None:
        """One chunk's placement with the device solve OFF the event loop.

        Snapshot-solve-apply, the same discipline as ``rebalance``: the
        node vectors and cached potentials are snapshotted ON the event
        loop (so lock-free mutators like ``sync_members``/``register_node``,
        which run on the loop, can never tear them mid-read), the solve
        runs in a thread against only those snapshots, and the cheap host
        apply runs back on the loop. The caller holds ``self._lock`` across
        the awaits, so no other locked mutator interleaves within a chunk;
        lock-free dict reads (``lookup``) stay live throughout.
        """
        # Snapshot here, not at batch start: the previous chunk's apply
        # (and, between lock holds, any interleaved mutator) changed load.
        load, cap, alive = self._node_vectors()
        g = self._g
        n_real = len(self._node_order)  # snapshot: the thread reads no live state
        no_capacity = self._no_schedulable_capacity_host()
        assignment = await asyncio.to_thread(
            self._solve_chunk, chunk, load, cap, alive, g, n_real, no_capacity
        )
        self._apply_chunk(chunk, assignment)

    def _solve_chunk(
        self, keys, load, cap, alive, g, n_real, no_capacity=False
    ) -> np.ndarray:
        """Device solve for one placement chunk over loop-side snapshots;
        reads NO live provider state, mutates nothing (thread-safe)."""
        n = len(keys)
        if no_capacity:
            # Every node dead (or cordoned) at once, e.g. a clean_server
            # storm or a gossip blip marking the whole cluster inactive
            # between ticks (found by the 80-wave soak at wave 46). The
            # waterfill degenerates here (all-zero widths clip every row
            # onto one worst-scored slot, real or pad), so don't solve:
            # seat deterministically via the shared spread. Reference
            # semantics: placement rows outlive their owner
            # (rio-rs/src/service.rs:213-238 re-seats on the next
            # request); the next liveness change re-solves.
            return _least_loaded_spread(load, alive, cap, n_real, n)
        cost = build_cost_matrix(load, cap, alive)  # (1, n_nodes)
        if g is not None:
            # Warm path: bias the score by the cached node potentials from the
            # last OT solve, then waterfill (balance even under cost ties).
            g = jnp.where(jnp.isfinite(g), g, -1e9)
            cost = cost - g[None, :]
        bucket = _next_bucket(n)
        rows = jnp.broadcast_to(cost, (bucket, cost.shape[1]))
        mass = jnp.concatenate(
            [jnp.ones((n,), jnp.float32), jnp.zeros((bucket - n,), jnp.float32)]
        )
        return _route_unseatable(
            np.asarray(greedy_balanced_assign(rows, mass, cap * alive, load))[:n],
            n_real,
            load,
            alive,
            cap,
        )

    def _apply_chunk(self, keys: list[str], assignment: np.ndarray) -> None:
        for k, idx in zip(keys, assignment.tolist()):
            self._set_placement(k, int(idx))
            self._nodes[self._node_order[idx]].load += 1.0
        self._epoch += 1

    def _build_obj_feat(
        self, keys: list[str], n_pad: int, node_order: list[str],
        cur_idx, move_cost: float, move_w,
    ) -> np.ndarray:
        """Streamed (n_pad, d) object-feature block for a hierarchical solve.

        The old pipeline materialized three full-size intermediates at once
        (raw features, the stay-put pull, and the padded concat) — 1.9 GB
        of throwaway peak at 10M x 16 fp32. This builder preallocates the
        FINAL block once and fills it in bounded key-chunks
        (``_OBJ_FEAT_STREAM_ROWS``): per chunk it calls the feature hook,
        sanitizes, applies the stay-put pull, and writes rows in place —
        peak is the output plus one chunk. ``RIO_TPU_HIER_FEAT_BF16=1``
        stores the block in bfloat16 (:func:`_hier_feature_dtype`).

        Sanitize is load-bearing, not belt-and-braces: measured load
        vectors reach the solver only through ``ClusterLoadView``'s
        sanitization, but feature hooks are user code with no such gate —
        one NaN row would propagate through the coarse cost std and
        poison EVERY object's normalized cost. Non-finite entries become
        0.0 (a zero feature row still spreads correctly under the
        capacity marginals; copy-on-write, since the hook may hand us its
        internal buffer).

        Pad rows (``n_pad - n``: po2 bucket padding plus the mesh's
        shard-multiple round-up) come from the cached deterministic block
        — they ride the solve as ordinary rows and are sliced off by the
        caller's ``[:n]``.
        """
        n = len(keys)
        dtype = _hier_feature_dtype()
        node_emb = None
        seat = None
        if move_cost > 0.0 and cur_idx is not None and node_order:
            # Stay-put pull for routed flat-mode solves (see
            # _hierarchical_solve docstring). Node embeddings are unit
            # vectors; cross-affinities of random unit vectors are
            # ~1/sqrt(d) noise, so adding move_cost of the current seat's
            # embedding raises the seat's affinity by ~move_cost relative
            # to everywhere else — the feature-space analog of the flat
            # path's stay-put diagonal discount.
            node_emb = np.asarray(self._node_features(node_order), np.float32)
            seat = np.asarray(cur_idx, np.int64)
        out: np.ndarray | None = None
        step = max(1, _OBJ_FEAT_STREAM_ROWS)
        for start in range(0, n, step):
            chunk_keys = keys[start : start + step]
            feats = np.asarray(self._obj_features(chunk_keys), np.float32)
            if not np.isfinite(feats).all():
                feats = np.nan_to_num(feats, nan=0.0, posinf=0.0, neginf=0.0)
            if out is None:
                out = np.empty((n_pad, feats.shape[1]), dtype)
            if node_emb is not None:
                s = seat[start : start + len(chunk_keys)]
                seated = (s >= 0) & (s < len(node_order))
                pull = np.zeros_like(feats)
                pull[seated] = node_emb[s[seated]]
                if move_w is not None:
                    # Per-object move prices (object_costs): a hot/heavy
                    # actor's pull toward its current seat scales with its
                    # measured weight, mirroring the dense path's scaled
                    # stay-put discount.
                    pull *= np.asarray(
                        move_w[start : start + len(chunk_keys)], np.float32
                    )[:, None]
                feats = feats + np.float32(move_cost) * pull
            out[start : start + len(chunk_keys)] = feats
        if out is None:  # empty directory: shape from the hook's contract
            probe = np.asarray(self._obj_features([]), np.float32)
            d = probe.shape[1] if probe.ndim == 2 else _FEAT_DIM
            out = np.empty((n_pad, d), dtype)
        if n_pad > n:
            out[n:] = _pad_feature_block(n_pad - n, out.shape[1])
        return out

    def _hierarchical_solve(
        self, keys: list[str], node_order: list[str], cap, alive,
        cur_idx=None, move_cost: float = 0.0, move_w=None,
        coarse_g_init=None,
    ):
        """Two-level OT re-solve over hashed identity features.

        The flat-cost modes materialize (bucket x node_axis); this one stays
        O(n x (groups + group_size + feat)) so it scales past HBM limits
        (see :mod:`rio_tpu.parallel.hierarchical`). Reads ONLY the
        lock-snapshotted ``node_order``/``cap``/``alive`` — it runs in the
        solver thread, concurrent with directory mutations.

        ``cur_idx``/``move_cost`` carry the flat modes' stay-put semantics
        into feature space when a sinkhorn/scaling rebalance is routed here
        at scale: each seated object's feature is pulled ``move_cost``
        toward its current node's embedding (the same cache-warmth encoding
        AffinityTracker learns from traffic), so only capacity pressure —
        dead nodes, skew — moves anything, instead of every quota ripple
        reshuffling millions of actors. Native ``mode="hierarchical"``
        solves don't use it: there the tracker's learned features are the
        stickiness mechanism and double-counting would over-stick.

        ``coarse_g_init`` warm-starts the coarse group solve from a prior
        plan's potentials (delta path); used only when its length matches
        this solve's group count. Returns ``(assignment, g, coarse_g,
        conv)``: the flat node potentials are always None here (the
        two-level solve produces group potentials instead), ``coarse_g``
        is the coarse stage's (n_groups,) potentials — on the mesh paths
        the pmean across shards, replicated (each shard solves the same
        capacity proportions, so the mean is a valid seed) — and ``conv``
        is the convergence record (iterations, residual, warm ratio,
        chunk/device fan-out, per-chunk timings) SolveStats surfaces.
        Dispatch composes both scale mechanisms: mesh devices divide the
        rows first, then per-device chunking bounds what one body
        compiles (conv gains ``mode_suffix="+mesh_chunk"`` when both are
        active, surfaced in ``SolveStats.mode``).
        """
        from ..parallel.hierarchical import hierarchical_assign

        # Solve over a COMPACT node axis (real nodes padded to a group
        # multiple), not the full static axis: trailing all-dead groups
        # would concentrate coarse quotas into the few live groups and
        # overflow their buckets.
        m_real = max(1, len(node_order))
        group_size = 8
        m = -(-m_real // group_size) * group_size
        n_groups = m // group_size
        cap_full = np.asarray(cap, np.float32)
        alive_full = np.asarray(alive, np.float32)
        cap_np = np.zeros((m,), np.float32)
        alive_np = np.zeros((m,), np.float32)
        cap_np[:m_real] = cap_full[:m_real]
        alive_np[:m_real] = alive_full[:m_real]
        # PAD THE OBJECT AXIS to a power-of-two bucket: every static shape
        # fed to the jitted solve must be drawn from a bounded set, or a
        # steadily-allocating cluster compiles a FRESH executable per
        # rebalance and the jit cache grows without bound (found by the r5
        # endurance soak: ~25 MB of retained lowering/executable per new
        # directory size; ~1 GB/hour under continuous allocation). Pad
        # rows ride the solve as ordinary rows — they spread ~evenly under
        # the capacity marginals, costing only rounding-noise balance (the
        # real rows' per-node counts stay proportional) — and are sliced
        # off before the result leaves this function. The feature hook is
        # given ONLY real directory keys (its documented contract); pad
        # features come from a cached internal block.
        n = len(keys)
        bucket_n = _next_bucket(n)
        # Bucket from the fullest group's capacity share (host-side, static
        # per solve): uniform N/G sizing under-provisions skewed clusters.
        # Quantized to a power of two for the same bounded-compile reason
        # as the object axis (a continuous float share would otherwise
        # mint a fresh static `bucket` per capacity/liveness change).
        live_cap = (cap_np * alive_np).reshape(n_groups, group_size).sum(axis=1)
        share = live_cap.max() / max(live_cap.sum(), 1e-9)
        # Chunk the object axis above _HIER_CHUNK_ROWS — on BOTH paths.
        # The TPU backend's compile is superlinear in the flat row count
        # (v5e: 50 s at 655k, 599 s at 2.6M), and a mesh only divides the
        # rows by the device count before each shard compiles its flat
        # body, hitting the same wall one octave later. So devices divide
        # first (n_pad -> per_dev), then lax.map chunking bounds what one
        # body actually compiles at: mesh and chunks COMPOSE
        # (mesh_chunked_hierarchical_assign) instead of excluding each
        # other. Doubling n_chunks while halves stay exact keeps every
        # shape static for any po2 bucket and chunk-row override.
        n_shards = 1 if self._mesh is None else int(self._mesh.devices.size)
        n_pad = -(-bucket_n // n_shards) * n_shards
        per_dev = n_pad // n_shards
        n_chunks = 1
        while (
            per_dev // n_chunks > _HIER_CHUNK_ROWS
            and (per_dev // n_chunks) % 2 == 0
        ):
            n_chunks *= 2
        # Fine-stage bucket sized from PER-CELL rows: each (device, chunk)
        # cell solves 1/(n_shards*n_chunks) of the population against the
        # same fraction of every node's capacity.
        rows_cell = per_dev // n_chunks
        bucket_sz = _next_bucket(
            max(8, int(1.3 * rows_cell * float(share))), minimum=8
        )

        obj_feat = self._build_obj_feat(
            keys, n_pad, node_order, cur_idx, move_cost, move_w
        )
        d_feat = obj_feat.shape[1]
        node_feat = np.zeros((d_feat, m), np.float32)
        if node_order:
            nf = np.asarray(self._node_features(node_order), np.float32)
            assert nf.shape[1] == d_feat, (
                f"node feature dim {nf.shape[1]} != object feature dim {d_feat}"
            )
            if not np.isfinite(nf).all():
                # Same defensive sanitize as the object side: a garbage
                # embedding must never poison the cost (copy-on-write —
                # the hook may have handed us its internal buffer).
                nf = np.nan_to_num(nf, nan=0.0, posinf=0.0, neginf=0.0)
            node_feat[:, : len(node_order)] = nf.T
        kw = dict(
            n_groups=n_groups,
            bucket=min(bucket_sz, rows_cell),
            eps=self._eps,
            coarse_iters=self._n_iters,
            fine_iters=self._n_iters,
        )
        # Warm coarse seed from the previous plan — only when the group
        # axis still matches (axis growth / group-count drift means the
        # cached potentials describe a different problem: cold-start).
        # Cold start IS the zero seed (g0 = 0 in both solver forms), so
        # always pass an array: a None-vs-array flip would otherwise mint
        # a second jit trace for the exact same computation.
        if coarse_g_init is None or (
            np.asarray(coarse_g_init).shape != (n_groups,)
        ):
            warm_ratio = 0.0  # cold start (no / mismatched prior seed)
            coarse_g_init = np.zeros((n_groups,), np.float32)
        else:
            warm_ratio = _seed_warm_ratio(coarse_g_init)
        conv: dict = {
            "solver_iters": 2 * self._n_iters,  # coarse + fine stages
            "warm_ratio": warm_ratio,
            "chunks": n_chunks,
            "devices": n_shards,
        }
        if self._mesh is not None:
            # Shard the object axis across the mesh (the tier this mode is
            # for); obj_feat was built at n_pad (a shard multiple) so every
            # device gets per_dev rows, and the caller's [:n] slice drops
            # the pad. The warm seed threads through shard_map (it used to
            # be dropped here — PlanState potentials on the mesh path were
            # write-only) and comes back pmean'd for the next plan.
            from ..parallel import hierarchical as _hier

            if n_chunks > 1:
                # The composed path: lax.map-chunked body INSIDE each
                # shard, one compile at the (rows_cell, d) cell shape.
                conv["mode_suffix"] = "+mesh_chunk"
                if os.environ.get("RIO_TPU_CHUNK_TIMING", "1") != "0":
                    res, chunk_ms = _hier.mesh_chunked_hierarchical_assign_timed(
                        self._mesh, jnp.asarray(obj_feat),
                        jnp.asarray(node_feat),
                        jnp.asarray(cap_np), jnp.asarray(alive_np),
                        n_chunks=n_chunks,
                        coarse_g_init=jnp.asarray(coarse_g_init),
                        **kw,
                    )
                    conv["chunk_ms"] = chunk_ms
                else:
                    res = _hier.mesh_chunked_hierarchical_assign(
                        self._mesh, jnp.asarray(obj_feat),
                        jnp.asarray(node_feat),
                        jnp.asarray(cap_np), jnp.asarray(alive_np),
                        n_chunks=n_chunks,
                        coarse_g_init=jnp.asarray(coarse_g_init),
                        **kw,
                    )
            else:
                res = _hier.sharded_hierarchical_assign(
                    self._mesh, jnp.asarray(obj_feat), jnp.asarray(node_feat),
                    jnp.asarray(cap_np), jnp.asarray(alive_np),
                    coarse_g_init=jnp.asarray(coarse_g_init),
                    **kw,
                )
        elif n_chunks > 1:
            from ..parallel import hierarchical as _hier

            if os.environ.get("RIO_TPU_CHUNK_TIMING", "1") != "0":
                # Host-looped twin: same jitted chunk body (compile stays
                # pinned to the chunk shape), but each chunk's
                # dispatch+block cycle is timed — the per-chunk signal
                # the hierarchical-ladder telemetry needs. Set
                # RIO_TPU_CHUNK_TIMING=0 to keep the single-executable
                # lax.map form instead.
                res, chunk_ms = _hier.chunked_hierarchical_assign_timed(
                    obj_feat, jnp.asarray(node_feat),
                    jnp.asarray(cap_np), jnp.asarray(alive_np),
                    n_chunks=n_chunks,
                    coarse_g_init=jnp.asarray(coarse_g_init),
                    **kw,
                )
                conv["chunk_ms"] = chunk_ms
            else:
                res = _hier.chunked_hierarchical_assign(
                    obj_feat, jnp.asarray(node_feat),
                    jnp.asarray(cap_np), jnp.asarray(alive_np),
                    n_chunks=n_chunks,
                    coarse_g_init=jnp.asarray(coarse_g_init),
                    **kw,
                )
        else:
            res = hierarchical_assign(
                obj_feat, jnp.asarray(node_feat),
                jnp.asarray(cap_np), jnp.asarray(alive_np),
                coarse_g_init=jnp.asarray(coarse_g_init),
                **kw,
            )
        coarse_g = (
            None if res.coarse_g is None else np.asarray(res.coarse_g, np.float32)
        )
        if res.coarse_err is not None:
            # Scalar pull AFTER the solve, never per iteration (CLAUDE.md
            # r4: value pulls ride the post-timing path).
            conv["residual"] = float(np.asarray(res.coarse_err))
        return res.assignment[:n], None, coarse_g, conv

    # ---------------------------------------------------- incremental solve
    def _delta_gates_ok(self, plan: PlanState | None, force: bool) -> bool:
        """Delta-eligibility gates shared by both delta paths: a plan must
        exist; ``force`` overrides everything else (threshold disabled,
        plan marked stale by the transport-cost audit, staleness bound of
        ``max_delta_solves`` consecutive deltas)."""
        if plan is None:
            return False
        if force:
            return True
        if self._delta_threshold <= 0.0 or plan.stale:
            return False
        return plan.delta_solves < self._max_delta_solves

    def _class_refresh(self, load, cap, alive, counts_np, cap_alive, mode, plan):
        """Warm potential refresh at the STATIC class shape (M x M): the
        same collapse the full path exploits, seeded with the plan's
        potentials so a handful of iterations re-converges after one
        liveness flip. No N dependence -> no per-event recompile, one
        cached executable per node axis (see ``_class_refresh_device``).
        Returns ``(g, score, err)`` — the new column potentials, the
        per-node host fill score, and the refresh's scalar convergence
        residual. A missing seed is passed as zeros, not
        None: cold start IS the zero seed in both solver forms, and a
        None-vs-array flip would mint a second trace."""
        base = build_cost_matrix(jnp.zeros_like(load), cap, alive)[0]
        g_seed = (
            jnp.zeros((base.shape[0],), jnp.float32)
            if plan.g is None
            else jnp.asarray(plan.g)
        )
        g_r, err = _class_refresh_device(
            base,
            jnp.asarray(np.asarray(counts_np, np.float32)),
            jnp.asarray(cap_alive.astype(np.float32)),
            g_seed,
            mode=mode,
            move_cost=self._move_cost,
            eps=min(
                self._eps,
                self._move_cost / 25.0 if self._move_cost > 0 else self._eps,
            ),
            n_iters=max(4, min(8, self._n_iters)),
        )
        g_np = np.asarray(g_r, np.float64)
        score = np.asarray(base, np.float64) - np.where(
            np.isfinite(g_np), g_np, -1e30
        )
        return g_r, score, float(np.asarray(err))

    def _delta_fast_snapshot(self, plan, n, cap, alive, force):
        """O(displaced) delta snapshot, taken under the provider lock.

        The dominant per-event host cost of a churn rebalance at directory
        scale is not the solve — it is materializing the O(N) key/seat
        array snapshot (~0.35 s per million objects). For the dominant
        churn shape — nodes LEAVING the schedulable set with every
        survivor at or under its integer fair quota — the displaced set is
        exactly the departed nodes' seats, which ``_by_node`` already
        holds. This helper detects that shape in O(M) and snapshots just
        the displaced ``(key, old_index)`` pairs, so the whole event costs
        O(displaced + M^2) instead of O(N).

        Returns None whenever per-seat decisions could matter — a survivor
        over its integer quota needs rank-based eviction (honoring
        ``object_costs`` prices); the array-snapshot delta / full pipeline
        handles those. Per-object prices are irrelevant HERE by
        construction: with no survivor over quota there are no evictions,
        so prices cannot change which objects move, and the flat cost
        model prices every destination identically for all objects.
        """
        if not self._delta_gates_ok(plan, force):
            return None
        cap_np = np.asarray(cap, np.float64)
        alive_np = np.asarray(alive, np.float64)
        cap_alive = cap_np * (alive_np > 0)
        m = cap_alive.shape[0]
        sched = cap_alive > 0.0
        counts = np.zeros(m, np.int64)
        for j, seats in self._by_node.items():
            if j < m:
                counts[j] = len(seats)
        quota = integer_fair_quotas(cap_alive, n)
        if np.any(sched & (counts > quota)):
            return None  # over-quota eviction: needs per-seat ranks
        disp_nodes = np.nonzero(~sched & (counts > 0))[0]
        d = int(counts[disp_nodes].sum())
        if not force and d > self._delta_threshold * n:
            return None
        disp: list[tuple[str, int]] = []
        for j in disp_nodes.tolist():
            disp.extend((k, j) for k in self._by_node.get(j, ()))
        retained = np.where(sched, counts, 0)
        residual = quota - retained
        return {
            "disp": disp,
            "counts": counts,
            "cap_alive": cap_alive,
            "quota": quota,
            "retained": retained,
            "residual": residual,
            "d": d,
        }

    async def _delta_fast_rebalance(
        self, fast, *, n, mode, move_sink, load, cap, alive,
        node_order, plan, snapshot_epoch,
    ) -> int:
        """Solve + commit an O(displaced) fast delta (see
        :meth:`_delta_fast_snapshot`). Same thread/epoch discipline as the
        array pipeline: device work off the event loop, epoch re-checked
        under the lock before apply, discarded attempts recorded."""
        from ..tracing import span

        solved_as = f"{mode}+delta"
        disp = fast["disp"]
        d = fast["d"]
        residual = fast["residual"]
        cap_alive = fast["cap_alive"]
        quota = fast["quota"]
        retained = fast["retained"]
        m = cap_alive.shape[0]
        sched = cap_alive > 0.0

        def _solve():
            t0 = time.perf_counter()
            c0 = _compile_seconds()
            with span("placement_solve", mode=solved_as, n=n):
                g_new = None
                coarse_new = None
                conv: dict = {}
                if d == 0:
                    # Nothing displaced (pure load jitter): the plan stands.
                    fill = np.zeros((0,), np.int32)
                elif mode == "hierarchical":
                    # Displaced keys through the two-level solve against
                    # the residual columns (chunk-shape compile bound).
                    res_cap = residual.astype(np.float32)
                    res_alive = (residual > 0).astype(np.float32)
                    fill, _, coarse_new, conv = self._hierarchical_solve(
                        [k for k, _ in disp], node_order, res_cap,
                        res_alive, coarse_g_init=plan.coarse_g,
                    )
                    fill = _route_unseatable(
                        np.asarray(fill, np.int32), len(node_order), load,
                        res_alive, res_cap,
                    )
                else:
                    if mode in ("sinkhorn", "scaling"):
                        g_new, score, ref_err = self._class_refresh(
                            load, cap, alive, fast["counts"], cap_alive,
                            mode, plan,
                        )
                        conv = {
                            "solver_iters": max(4, min(8, self._n_iters)),
                            "residual": ref_err,
                            "warm_ratio": _seed_warm_ratio(plan.g),
                        }
                    else:
                        score = np.where(
                            sched, retained / np.maximum(quota, 1), 1e18
                        )
                    fill = residual_capacity_assign(score, residual)
                # Transport-cost audit (see _delta_solve): achieved
                # seating vs the integer-quota ideal; a tripped audit
                # marks the plan stale so the NEXT solve goes full.
                counts_after = (
                    retained + np.bincount(fill, minlength=m)
                ).astype(np.float64)
                safe_cap = np.maximum(cap_alive, 1e-9)
                num = float(np.sum(counts_after**2 / safe_cap))
                den = float(np.sum(quota.astype(np.float64) ** 2 / safe_cap))
                stale = bool(
                    den > 0.0 and num > self._delta_audit_ratio * den
                )
                solve_ms, conv = _conv_timing(conv, t0, c0)
                return fill, g_new, coarse_new, solve_ms, stale, counts_after, conv

        fill, g, coarse_g, solve_ms, stale, counts_after, conv = (
            await asyncio.to_thread(_solve)
        )

        async with self._lock:
            if self._epoch != snapshot_epoch:
                self.stats = SolveStats(
                    n_objects=n,
                    n_nodes=len(self._node_order),
                    solve_ms=solve_ms,
                    displaced=d,
                    epoch=self._epoch,
                    mode=solved_as,
                    discarded=True,
                    history=self._archived_history(),
                    **_conv_fields(conv),
                )
                return 0
            hist = self._archived_history()
            t_apply = time.perf_counter()
            moved = 0
            planned: list[tuple[str, str, str]] = []
            for (key, old_idx), new_idx in zip(disp, fill.tolist()):
                if move_sink is not None:
                    planned.append(
                        (key, node_order[old_idx], node_order[int(new_idx)])
                    )
                elif self._set_placement(key, int(new_idx)):
                    moved += 1
            if move_sink is not None:
                moved = len(planned)
            if g is not None:
                self._g = g
                self._g_fp = self._sched_fp()
            self._recount_loads()
            self._epoch += 1
            self._plan = PlanState(
                g=g if g is not None else plan.g,
                coarse_g=coarse_g if coarse_g is not None else plan.coarse_g,
                seat_counts=np.asarray(counts_after, np.int64),
                epoch=self._epoch,
                liveness_fp=self._sched_fp(),
                delta_solves=plan.delta_solves + 1,
                stale=stale,
            )
            self.stats = SolveStats(
                n_objects=n,
                n_nodes=len(self._node_order),
                solve_ms=solve_ms,
                apply_ms=(time.perf_counter() - t_apply) * 1e3,
                moved=moved,
                displaced=d,
                epoch=self._epoch,
                mode=solved_as,
                discarded=False,
                history=hist,
                **_conv_fields(conv),
            )
        if planned:
            planned.sort(key=lambda mv: (mv[1], mv[2]))
            # Outside the lock on purpose: handoffs call back into
            # update()/lookup(), which take it.
            await move_sink(planned)
        return moved

    def _delta_solve(
        self, keys, cur_idx, load, cap, alive, n_real, node_order,
        plan: PlanState, mode: str, obj_w, force: bool,
    ):
        """Delta rebalance: re-solve ONLY the displaced objects against
        residual capacity, warm-starting from the previous plan.

        The displaced set is (a) every seat on a node that left the
        schedulable set (dead / cordoned / capacity-zero) plus (b) the
        over-quota overflow on surviving nodes (per-seat rank beyond the
        node's integer fair quota). Undisplaced objects keep their seats
        BY CONSTRUCTION — they are never re-solved — and the displaced
        fill targets each node's residual quota (quota minus retained
        seats), so the result lands on exactly the same integer per-node
        counts a full quota-repaired solve would produce. One churn event
        then costs O(N) host work + an O(M^2) warm potential refresh,
        not an O(N x M) directory solve.

        Runs in the solver thread over loop-side snapshots only (the
        provider's standard discipline); reads nothing live but immutable
        config. Returns ``(assignment, g, coarse_g, displaced, stale,
        conv)`` — ``conv`` is the convergence record SolveStats surfaces —
        or None when a gate says this event needs the full solve:
        no plan / plan marked stale / ``max_delta_solves`` consecutive
        deltas exceeded / displaced fraction above ``delta_threshold``
        (``force`` overrides every gate except a missing plan).
        """
        n = len(keys)
        if n == 0 or not self._delta_gates_ok(plan, force):
            return None
        cap_np = np.asarray(cap, np.float64)
        alive_np = np.asarray(alive, np.float64)
        cap_alive = cap_np * (alive_np > 0)
        m = cap_alive.shape[0]
        sched = cap_alive > 0.0
        quota = integer_fair_quotas(cap_alive, n)  # (m,), sums to n exactly
        cur = np.asarray(cur_idx, np.int64)
        # Rank each object within its current seat's population (one
        # stable sort — the host analog of ops.assignment.rank_within_group).
        # With per-object move prices the heavy/hot objects rank first and
        # are kept, so quota pressure evicts cold objects — mirroring the
        # dense path's scaled stay-put discount.
        if obj_w is not None:
            order = np.lexsort((-np.asarray(obj_w, np.float64), cur))
        else:
            order = np.argsort(cur, kind="stable")
        sorted_seats = cur[order]
        starts = np.searchsorted(sorted_seats, np.arange(m))
        rank = np.empty(n, np.int64)
        rank[order] = np.arange(n) - starts[sorted_seats]
        keep = sched[cur] & (rank < quota[cur])
        disp_pos = np.nonzero(~keep)[0]
        d = int(disp_pos.shape[0])
        if d == 0:
            # Nothing displaced (e.g. a node RETURNED): the plan stands.
            return cur.astype(np.int32), None, None, 0, False, {}
        if not force and d > self._delta_threshold * n:
            return None
        # retained[j] = min(counts[j], quota[j]) on schedulable nodes, 0
        # elsewhere; residual >= 0 and sums to d exactly (quota sums to n,
        # retained to n - d).
        retained = np.bincount(cur[keep], minlength=m)
        residual = quota - retained

        g_new = None
        coarse_new = None
        conv: dict = {}
        if mode == "hierarchical":
            # Route the displaced keys through the two-level solve against
            # the residual capacity columns — the chunked dispatch inside
            # keeps any displaced count compile-bounded at the chunk shape.
            disp_keys = [keys[i] for i in disp_pos.tolist()]
            res_cap = residual.astype(np.float32)
            res_alive = (residual > 0).astype(np.float32)
            fill, _, coarse_new, conv = self._hierarchical_solve(
                disp_keys, node_order, res_cap, res_alive,
                coarse_g_init=plan.coarse_g,
            )
            fill = _route_unseatable(
                np.asarray(fill, np.int32), n_real, load, res_alive, res_cap
            )
        else:
            if mode in ("sinkhorn", "scaling"):
                # Warm M x M potential refresh (see _class_refresh).
                g_new, score, ref_err = self._class_refresh(
                    load, cap, alive, np.bincount(cur, minlength=m),
                    cap_alive, mode, plan,
                )
                conv = {
                    "solver_iters": max(4, min(8, self._n_iters)),
                    "residual": ref_err,
                    "warm_ratio": _seed_warm_ratio(plan.g),
                }
            else:
                # Greedy has no potentials: order nodes by how full their
                # retained population already is. Every feasible fill hits
                # the same per-node counts (residual is integer-exact), so
                # the score only decides WHICH interchangeable seat runs
                # land where.
                score = np.where(
                    sched, retained / np.maximum(quota, 1), 1e18
                )
            fill = residual_capacity_assign(score, residual)
        out = cur.astype(np.int32).copy()
        out[disp_pos] = fill

        # Transport-cost audit (quadratic congestion proxy): compare the
        # achieved per-node seating against the ideal integer quotas. The
        # flat fills hit the quotas exactly (ratio 1.0 by construction);
        # the hierarchical fill is capacity-proportional per group, and
        # repeated deltas can drift — a tripped audit marks the plan stale
        # so the NEXT solve goes full. Unschedulable nodes get a tiny
        # capacity floor, so any stray seat there blows the ratio up and
        # forces the full solve — exactly the right reaction.
        counts_after = np.bincount(out, minlength=m).astype(np.float64)
        safe_cap = np.maximum(cap_alive, 1e-9)
        num = float(np.sum(counts_after**2 / safe_cap))
        den = float(np.sum(quota.astype(np.float64) ** 2 / safe_cap))
        stale = bool(den > 0.0 and num > self._delta_audit_ratio * den)
        return out, g_new, coarse_new, d, stale, conv

    # ------------------------------------------------ communication graph
    def set_edge_graph(self, rows) -> int:
        """Install the cluster-merged communication graph.

        ``rows`` is the ``merge_edges`` shape (``[src, dst, bytes_per_s,
        calls_per_s, local_frac]``, extra columns optional). Edges from
        external clients (``src == "client"``) are dropped — a client
        cannot be co-located, so attraction toward its traffic is
        meaningless — as are self-edges and zero-rate rows. The remaining
        edges are symmetrized (undirected key, rates summed), weighted as
        bytes/s plus a per-call framing overhead, and normalized so the
        heaviest edge is 1.0: the affinity_weight knob then has one unit
        regardless of absolute traffic volume. Returns the edge count;
        atomic swap, safe against a concurrent solver-thread read."""
        edges: dict[tuple[str, str], float] = {}
        for r in rows or ():
            src, dst = str(r[0]), str(r[1])
            if src == "client" or src == dst:
                continue
            bps = max(0.0, float(r[2]))
            cps = max(0.0, float(r[3])) if len(r) > 3 else 0.0
            # ~64 B of frame/header cost per call keeps pure-call-count
            # chatter (tiny payloads, high rate) visible in the weight.
            w = bps + 64.0 * cps
            if w <= 0.0:
                continue
            key = (src, dst) if src < dst else (dst, src)
            edges[key] = edges.get(key, 0.0) + w
        if edges:
            top = max(edges.values())
            edges = {k: v / top for k, v in edges.items()}
        self._edge_graph = edges
        return len(edges)

    def _affinity_refine(self, keys, assignment, node_order, cap, alive):
        """Alternating linearized OT refinement over the edge graph.

        Runs in the solver thread after a FULL solve. Each pass linearizes
        the quadratic co-location objective around the current assignment:
        an object's attraction to node ``a`` is the edge-weighted sum of
        ``Hfac[a, seat(neighbor)]`` (1.0 same worker, host_factor same
        host, 0.0 cross-host), folded into the per-object cost row as a
        discount — so the unchanged Sinkhorn core (per-row gauge shift,
        warm starts) solves it like any other dense problem. Only the
        edge-touching subset is re-solved (capped at
        ``_AFFINITY_MAX_ROWS`` heaviest, padded to a power-of-2 bucket for
        compile reuse); everything else keeps its balance-optimal seat. A
        pass is accepted only if BOTH the edge-cut transport cost and the
        total objective (capacity overflow + weighted cut) are
        non-increasing — the monotonicity the invariant tests pin.

        Returns the refined assignment (np.int32, length n) or None when
        the graph doesn't touch this directory / no pass was accepted.
        """
        edges = self._edge_graph  # atomic snapshot
        w_aff = self._affinity_weight
        n = len(keys)
        key_ix = {k: i for i, k in enumerate(keys)}
        ei: list[int] = []
        ej: list[int] = []
        ew: list[float] = []
        for (a, b), w in edges.items():
            ia = key_ix.get(a)
            ib = key_ix.get(b)
            if ia is None or ib is None:
                continue
            ei.append(ia)
            ej.append(ib)
            ew.append(w)
        if not ei:
            return None
        # Symmetrize into directed arrays (each undirected edge twice) so
        # one scatter-add accumulates every object's full neighborhood.
        e_src = np.asarray(ei + ej, np.int64)
        e_dst = np.asarray(ej + ei, np.int64)
        e_w = np.asarray(ew + ew, np.float32)

        cap_np = np.asarray(cap, np.float32)
        alive_np = np.asarray(alive, np.float32)
        m = cap_np.shape[0]
        # Same-host structure: address up to the ":port" suffix identifies
        # the host; padded (unregistered) columns get unique tokens so the
        # host mask degenerates to the identity there.
        hosts = [
            node_order[i].rsplit(":", 1)[0] if i < len(node_order) else f"\x00pad{i}"
            for i in range(m)
        ]
        host_id = np.asarray(
            [list(dict.fromkeys(hosts)).index(h) for h in hosts], np.int64
        )
        hf = self._affinity_host_factor
        same_host = (host_id[:, None] == host_id[None, :]).astype(np.float32)
        hfac = hf * same_host
        np.fill_diagonal(hfac, 1.0)
        dist = 1.0 - hfac  # 0 same worker / (1-hf) same host / 1 cross

        # Edge-touching subset, heaviest first when over the row cap.
        deg = np.zeros((n,), np.float32)
        np.add.at(deg, e_src, e_w)
        sub = np.nonzero(deg > 0.0)[0]
        if sub.size > _AFFINITY_MAX_ROWS:
            sub = sub[np.argsort(-deg[sub], kind="stable")[:_AFFINITY_MAX_ROWS]]
            sub = np.sort(sub)
        s = int(sub.size)
        pos = np.full((n,), -1, np.int64)
        pos[sub] = np.arange(s)
        in_sub = pos[e_src] >= 0
        # Per-pass edge orientation. A simultaneous (Jacobi) update lets a
        # chatty pair SWAP seats forever — each endpoint chases the
        # other's pre-pass seat — so every pass anchors one endpoint per
        # edge: even passes move the lighter-degree endpoint toward the
        # heavier one (satellites join planets), odd passes reverse the
        # orientation so anchors catch up to moved satellites. Degree
        # ties break by index, keeping the orientation a strict total
        # order per edge.
        lighter = (deg[e_src] < deg[e_dst]) | (
            (deg[e_src] == deg[e_dst]) & (e_src < e_dst)
        )

        # Balance base row (identical for every object, exactly the dense
        # solve's cost model) and fair shares: each pass gives the mobile
        # half a slackened residual capacity per node — what the slack-
        # padded fair share leaves after every frozen seat is counted.
        # The +1 covers integer granularity at small fair shares (with 2
        # objects per node a 1.25x slack is less than one whole object,
        # and no pair could ever co-locate).
        base = np.asarray(
            build_cost_matrix(jnp.zeros((m,), jnp.float32), cap, alive),
            np.float32,
        ).reshape(-1, m)[0]
        cap_alive = cap_np * alive_np
        fair = cap_alive / max(float(np.sum(cap_alive)), 1e-30) * n
        slack_cap = fair * self._affinity_slack + 1.0
        schedulable = (cap_alive > 0.0).astype(np.float64)

        total_w = float(np.sum(e_w))

        def _cut(seats: np.ndarray) -> float:
            return float(np.sum(e_w * dist[seats[e_src], seats[e_dst]])) / max(
                total_w, 1e-30
            )

        def _total(seats: np.ndarray) -> float:
            counts = np.bincount(seats, minlength=m)
            overflow = float(np.sum(np.maximum(counts - slack_cap, 0.0))) / n
            return overflow + w_aff * _cut(seats)

        seats = np.asarray(assignment, np.int32).copy()
        history = [
            {"pass": 0, "cut": _cut(seats), "total": _total(seats), "accepted": True}
        ]
        g_warm = None
        accepted_any = False
        for p in range(self._affinity_passes):
            mask = in_sub & (lighter if p % 2 == 0 else ~lighter)
            if not np.any(mask):
                continue
            # Only the mobile endpoints are re-solved this pass; anchors
            # and everything outside the subset are frozen — their seats
            # consume capacity but cannot be displaced (the failure mode
            # of re-solving anchors is capacity pressure pushing them off
            # the very seats their satellites are converging toward).
            mobile = np.unique(e_src[mask])
            sp = int(mobile.size)
            pos_p = np.full((n,), -1, np.int64)
            pos_p[mobile] = np.arange(sp)
            attract = np.zeros((sp, m), np.float32)
            np.add.at(
                attract,
                pos_p[e_src[mask]],
                e_w[mask, None] * hfac[seats[e_dst[mask]]],
            )
            cost = np.broadcast_to(base, (sp, m)).copy()
            cost -= w_aff * attract
            # Stay-put discount at the object's current seat: a refine
            # move still pays the state handoff.
            cost[np.arange(sp), seats[mobile]] -= self._move_cost
            frozen = np.bincount(seats, minlength=m).astype(np.float64)
            frozen -= np.bincount(seats[mobile], minlength=m)
            col_cap = np.maximum(slack_cap - frozen, 0.0) * schedulable
            bucket = _next_bucket(sp)
            mass = np.zeros((bucket,), np.float32)
            mass[:sp] = 1.0
            cost_p = np.zeros((bucket, m), np.float32)
            cost_p[:sp] = cost
            cost_j = jnp.asarray(cost_p)
            f, g, _err = sinkhorn(
                cost_j,
                jnp.asarray(mass),
                jnp.asarray(col_cap, jnp.float32),
                eps=self._eps,
                n_iters=self._n_iters,
                g_init=g_warm,
            )
            g_warm = g  # warm-start the next linearization
            new_seats = np.asarray(plan_rounded_assign(cost_j, f, g, self._eps))[
                :sp
            ]
            # Any row the rounded plan could not seat on a live column
            # keeps its current seat (mirrors _route_unseatable's intent
            # without re-pricing the frozen rows).
            old = seats[mobile]
            bad = (
                (new_seats < 0)
                | (new_seats >= m)
                | (alive_np[new_seats % m] <= 0.0)
            )
            new_seats = np.where(bad, old, new_seats).astype(np.int32)
            # Integer capacity enforcement: the rounded plan's per-row
            # argmax can overshoot a column (that's what _repair_exact
            # fixes on the main path). Movers INTO each node are ranked
            # by cost gain and truncated to the whole seats the residual
            # actually has; the rest keep their current seat.
            gain = (
                cost[np.arange(sp), old] - cost[np.arange(sp), new_seats]
            )
            stayers = np.bincount(old[new_seats == old], minlength=m)
            for c in np.unique(new_seats[new_seats != old]):
                movers = np.nonzero((new_seats == c) & (old != c))[0]
                allowed = int(max(0.0, np.floor(col_cap[c] - stayers[c])))
                if movers.size > allowed:
                    ranked = movers[np.argsort(-gain[movers], kind="stable")]
                    new_seats[ranked[allowed:]] = old[ranked[allowed:]]
            cand = seats.copy()
            cand[mobile] = new_seats
            c_cut, c_tot = _cut(cand), _total(cand)
            ok = (
                c_cut <= history[-1]["cut"] + 1e-9
                and c_tot <= history[-1]["total"] + 1e-9
            )
            history.append(
                {"pass": p + 1, "cut": c_cut, "total": c_tot, "accepted": ok}
            )
            if not ok:
                break
            if not np.array_equal(cand, seats):
                accepted_any = True
            seats = cand
        self._affinity_history = history  # atomic swap (tests/telemetry)
        return seats if accepted_any else None

    async def rebalance(
        self,
        *,
        mode: str | None = None,
        move_sink=None,
        delta: bool | None = None,
    ) -> int:
        """Re-solve the directory; returns number of moves.

        By default (``delta=None``) a churn event first attempts the
        incremental **delta** path (:meth:`_delta_solve`): only displaced +
        new objects are re-solved against residual capacity with
        warm-started potentials, and the full-directory solve runs only
        when a delta gate trips (no/stale plan, displaced fraction over
        ``delta_threshold``, ``max_delta_solves`` staleness bound, or the
        transport-cost audit). ``delta=False`` forces the full solve;
        ``delta=True`` forces the delta path whenever a plan exists
        (overriding threshold and staleness). ``stats.mode`` reports which
        path ran (``"<mode>+delta"`` for an incremental solve).

        Snapshots the epoch before the (async-yielding) device solve and
        discards the result if the directory changed underneath — the
        single-writer/versioned-epoch consistency design from ``SURVEY.md``
        §7 "hard parts".

        ``move_sink`` (``async (list[(key, from_addr, to_addr)]) -> int``)
        turns the apply phase from raw directory writes into *planned*
        moves: the solve commits (epoch bump, so sibling solves discard)
        but rows are left standing, and the sink — the migration
        coordinator — actuates each move as a coordinated handoff whose
        own ``update()`` flips the row. The sink runs OUTSIDE the
        provider lock: handoffs call back into ``update``/``lookup``.
        """
        # An explicit mode="auto" resolves exactly like the constructor
        # default (it would otherwise fall through every dispatch check
        # and silently run the greedy branch).
        mode = self._solver_mode() if mode in (None, "auto") else mode
        async with self._lock:
            n = len(self._placements)
            snapshot_epoch = self._epoch
            self._recount_loads()
            load, cap, alive = self._node_vectors()
            node_order = list(self._node_order)  # snapshot for off-lock use
            no_capacity = self._no_schedulable_capacity_host()
            plan = self._plan  # immutable snapshot (atomic-swap field)
            # O(displaced) fast path FIRST: for pure node-departure churn
            # the displaced keys come straight from _by_node and the O(N)
            # key/seat snapshot below — the dominant per-event host cost
            # at directory scale — is skipped entirely.
            fast = None
            if delta is not False and n and not no_capacity:
                fast = self._delta_fast_snapshot(
                    plan, n, cap, alive, force=(delta is True)
                )
            if fast is None and n:
                keys = list(self._placements.keys())
                # values() iterates in keys() order (insertion order) and
                # skips the per-key hash lookup a genexpr would pay — the
                # snapshot was ~0.35 s/1M objects as a genexpr.
                cur_idx = np.fromiter(
                    self._placements.values(), np.int32, count=n
                )
        if not n:
            return 0
        if fast is not None:
            return await self._delta_fast_rebalance(
                fast, n=n, mode=mode, move_sink=move_sink, load=load,
                cap=cap, alive=alive, node_order=node_order, plan=plan,
                snapshot_epoch=snapshot_epoch,
            )

        bucket = _next_bucket(n)
        def _solve() -> tuple:
            """Device solve off the event loop: np.asarray blocks until the
            TPU finishes, so running it in a thread keeps lookups/gossip/RPCs
            live — and makes the epoch-discard check below load-bearing.
            Only the snapshots taken under the lock are read here."""
            t0 = time.perf_counter()
            c0 = _compile_seconds()
            from ..tracing import span

            if no_capacity:
                # Zero schedulable capacity (all nodes dead/cordoned at
                # once): reshuffling seats among dead nodes is pure churn
                # and the degenerate waterfill/OT outputs are meaningless —
                # stay put until liveness returns, recorded as its own
                # mode (span included, so trace tooling sees the outage
                # mode next to its SolveStats entry).
                solved_as = f"{mode}+no_capacity"
                with span("placement_solve", mode=solved_as, n=n):
                    return cur_idx.copy(), None, None, (
                        time.perf_counter() - t0
                    ) * 1e3, solved_as, 0, False, {}
            # Per-object move prices (object_costs hook; tracker-measured
            # request rates + snapshot bytes by default). Evaluated in the
            # solver thread — hooks must read only atomically-swapped
            # state, the contract AffinityTracker already follows. Any
            # hook failure or shape mismatch degrades to uniform pricing:
            # load telemetry must never break a rebalance.
            obj_w = None
            if self._object_costs is not None:
                try:
                    w = np.asarray(self._object_costs(keys), np.float32)
                except Exception:  # noqa: BLE001
                    w = None
                if w is not None and w.shape == (n,):
                    w = np.clip(np.nan_to_num(w, nan=1.0, posinf=1.0), 0.0, 1e6)
                    if n and float(np.ptp(w)) > 0.0:
                        obj_w = w
                    # Uniform weights are the scalar move_cost case:
                    # leave obj_w None and keep the collapsed fast path.
            # Incremental attempt FIRST: with a prior plan and bounded
            # displacement, the delta path replaces the whole directory
            # solve below. Falls through to the full solve (returning
            # None) when any gate trips.
            if delta is not False and plan is not None:
                with span("placement_solve", mode=f"{mode}+delta", n=n):
                    d_res = self._delta_solve(
                        keys, cur_idx, load, cap, alive,
                        len(node_order), node_order, plan, mode, obj_w,
                        force=(delta is True),
                    )
                    if d_res is not None:
                        out_d, g_d, coarse_d, displaced, stale, conv = d_res
                        out_d = _route_unseatable(
                            out_d, len(node_order), load, alive, cap
                        )
                        solve_ms, conv = _conv_timing(conv, t0, c0)
                        return (
                            out_d, g_d, coarse_d, solve_ms,
                            f"{mode}+delta", displaced, stale, conv,
                        )
            # Decide the actual code path up front so traces, profiler
            # labels, and SolveStats.mode all agree on what ran.
            # Non-uniform per-object prices break the identical-cost-rows
            # precondition of the O(M^2) class collapse, so priced solves
            # take the dense (or at scale, hierarchical) pipeline.
            collapse = (
                mode in ("sinkhorn", "scaling")
                and self._mesh is None
                and obj_w is None
            )
            # Above _FLAT_REBALANCE_MAX_ROWS the flat collapsed pipeline is
            # compile-infeasible on the TPU backend (superlinear compile:
            # the 10.5M-row expansion never finished a 900 s budget on
            # v5e, while 1M compiles in ~80 s) — route the re-solve
            # through the two-level solve, whose chunked form pins compile
            # to the 655k chunk shape (measured 48 s at 10.5M, 2.6 s
            # chained execution). Hashed-identity features are the
            # default, so this needs no user hooks; balance/liveness
            # quality parity is pinned by tests/test_hierarchical.py.
            # Per-shard rows are what the backend actually compiles: a
            # mesh divides the flat shape across devices, a single chip
            # does not. On a mesh the routed solve lands on the composed
            # mesh x chunk dispatch inside _hierarchical_solve — devices
            # divide the rows, then per-device chunking re-bounds the
            # compile — so routing never trades the flat wall for a
            # per-shard one.
            flat_rows = bucket if self._mesh is None else (
                -(-bucket // int(self._mesh.devices.size))
            )
            route_hier = (
                mode in ("sinkhorn", "scaling")
                and flat_rows > _FLAT_REBALANCE_MAX_ROWS
            )
            if route_hier:
                collapse = False
            solved_as = (
                f"{mode}+hier_at_scale"
                if route_hier
                else f"{mode}+collapsed" if collapse else mode
            )
            with span("placement_solve", mode=solved_as, n=n), _profiler_trace(
                f"rio_tpu.solve.{solved_as}"
            ):
                def _repair_exact(assignment_padded):
                    """Exact integer quotas at bucket shape (trace reuse);
                    movers evicted first so repair adds ~zero churn."""
                    from ..ops import exact_quota_repair

                    cap_alive = cap * alive
                    m_axis = cap_alive.shape[0]
                    real = jnp.arange(bucket) < n
                    idx_full = jnp.where(real, assignment_padded, m_axis)
                    expected = jnp.concatenate(
                        [
                            cap_alive
                            / jnp.maximum(jnp.sum(cap_alive), 1e-30)
                            * n,
                            jnp.asarray([bucket - n], jnp.float32),
                        ]
                    )
                    cur_full = jnp.zeros((bucket,), jnp.int32).at[:n].set(
                        jnp.asarray(cur_idx)
                    )
                    repaired = exact_quota_repair(
                        idx_full,
                        expected,
                        prefer_keep=jnp.where(real, idx_full == cur_full, True),
                    )
                    return _guard_sentinel_spill(
                        repaired, real, m_axis, cap_alive
                    )

                coarse_g = None
                conv = {}
                if mode == "hierarchical" or route_hier:
                    # Never materializes the flat (bucket x node_axis) cost.
                    assignment, g, coarse_g, conv = self._hierarchical_solve(
                        keys, node_order, cap, alive,
                        cur_idx=cur_idx if route_hier else None,
                        move_cost=self._move_cost if route_hier else 0.0,
                        move_w=obj_w if route_hier else None,
                        coarse_g_init=plan.coarse_g if plan is not None else None,
                    )
                    # Mesh x chunk composed dispatch stamps its suffix so
                    # SolveStats.mode attributes the actual executable
                    # shape (the span above keeps the base mode label).
                    solved_as = solved_as + conv.pop("mode_suffix", "")
                elif collapse:
                    # CLASS-COLLAPSED exact solve (ops/structured.py): the
                    # flat cost model is a per-node vector plus a stay-put
                    # diagonal, so every object with the same current seat
                    # has an identical cost row and the (N x M) problem
                    # collapses EXACTLY to (M x M) — N drops out of the
                    # device solve entirely (<50 ms class at ANY N). The
                    # dense path below remains for mesh-sharded solves
                    # (per-shard capacity splits break the pure-class
                    # structure) and anything with per-object costs.
                    from ..ops.structured import class_quotas

                    base_cost = build_cost_matrix(
                        jnp.zeros_like(load), cap, alive
                    )[0]
                    counts = jnp.bincount(
                        jnp.asarray(cur_idx), length=base_cost.shape[0]
                    )
                    # The class problem is tiny (M x M), so sharpen eps
                    # until off-diagonal leakage is negligible: soft-plan
                    # off-diag mass scales like M * exp(-move_cost/eps),
                    # and at the default eps (0.05, ratio 10) that is ~5%
                    # of all objects moved for no reason. Ratio >= 25 puts
                    # the leak below 1e-8; the log-domain solver is stable
                    # at any eps.
                    class_eps = min(
                        self._eps, self._move_cost / 25.0 if self._move_cost > 0 else self._eps
                    )
                    quotas, g, cls_err = class_quotas(
                        base_cost,
                        counts,
                        cap * alive,
                        move_cost=self._move_cost,
                        eps=class_eps,
                        n_iters=self._n_iters,
                        # Warm-start even the FULL collapsed solve from the
                        # previous plan's potentials: converged-from-warm
                        # matches converged-from-cold within tolerance and
                        # shaves iterations when liveness barely moved.
                        g_init=(
                            jnp.asarray(plan.g)
                            if plan is not None and plan.g is not None
                            else None
                        ),
                    )
                    # Device expansion (exact parity with the host
                    # _apply_class_quotas, tested): the whole decision —
                    # counts -> class solve -> expansion -> exact repair —
                    # stays one device pipeline; the only host pull is the
                    # final int32 assignment below. Padding rows expand to
                    # garbage and are overridden by the repair's sentinel.
                    from ..ops.structured import expand_class_quotas

                    cur_padded = jnp.zeros((bucket,), jnp.int32).at[:n].set(
                        jnp.asarray(cur_idx)
                    )
                    expanded = expand_class_quotas(quotas, cur_padded)
                    # Column sums of per-row-rounded quotas are only
                    # approximately capacity; the shared repair makes node
                    # loads exactly integer-quota (still O(N log N)).
                    assignment = _repair_exact(expanded)
                    conv = {
                        "solver_iters": self._n_iters,
                        "residual": float(np.asarray(cls_err)),
                        "warm_ratio": _seed_warm_ratio(
                            plan.g if plan is not None else None
                        ),
                    }
                else:
                    base_cost = build_cost_matrix(jnp.zeros_like(load), cap, alive)
                    cost = jnp.broadcast_to(base_cost, (bucket, base_cost.shape[1]))
                    if self._move_cost > 0:
                        # Stay-put discount on each object's current seat: a
                        # re-solve must pay move_cost to relocate an object,
                        # so only capacity pressure (dead nodes, skew) moves
                        # anything. Discounts on dead seats are inert — the
                        # dead column is already priced at DEAD_NODE_COST.
                        # With per-object prices (obj_w) a hot/heavy actor's
                        # seat is discounted MORE, so when capacity forces
                        # some share to move the solver evicts cold objects
                        # first.
                        stay = (
                            self._move_cost
                            if obj_w is None
                            else self._move_cost * jnp.asarray(obj_w)
                        )
                        cost = cost.at[jnp.arange(n), jnp.asarray(cur_idx)].add(
                            -stay
                        )
                    mass = jnp.concatenate(
                        [jnp.ones((n,), jnp.float32), jnp.zeros((bucket - n,), jnp.float32)]
                    )
                    if mode in ("sinkhorn", "scaling"):
                        # Reachable with a mesh (shard-local capacity
                        # splits break the pure-class structure) or with
                        # per-object prices (obj_w makes cost rows
                        # distinct, so the class collapse is off and the
                        # dense single-chip solvers run).
                        if self._mesh is not None:
                            from ..parallel import (
                                shard_cost,
                                sharded_scaling_sinkhorn,
                                sharded_sinkhorn,
                            )

                            cost = shard_cost(self._mesh, cost)
                            sharded = (
                                sharded_scaling_sinkhorn
                                if mode == "scaling"
                                else sharded_sinkhorn
                            )
                            f, g = sharded(
                                self._mesh, cost, mass, cap * alive,
                                eps=self._eps, n_iters=self._n_iters,
                            )
                            # The sharded solvers return no residual (a
                            # collective just for telemetry isn't worth
                            # it) and take no warm seed: cold by design.
                            conv = {
                                "solver_iters": self._n_iters,
                                "warm_ratio": 0.0,
                            }
                        else:
                            dense = (
                                scaling_sinkhorn
                                if mode == "scaling"
                                else sinkhorn
                            )
                            f, g, _err = dense(
                                cost, mass, cap * alive,
                                eps=self._eps, n_iters=self._n_iters,
                                g_init=(
                                    jnp.asarray(plan.g)
                                    if plan is not None and plan.g is not None
                                    else None
                                ),
                            )
                            conv = {
                                "solver_iters": self._n_iters,
                                "residual": float(np.asarray(_err)),
                                "warm_ratio": _seed_warm_ratio(
                                    plan.g if plan is not None else None
                                ),
                            }
                        assignment = plan_rounded_assign(cost, f, g, self._eps)
                        # Exact-capacity repair (bucket-shaped for trace
                        # reuse; padding rows ride a sentinel column; see
                        # _repair_exact above).
                        assignment = _repair_exact(assignment)
                    else:
                        # Churn-aware greedy: waterfilling lays *all* mass
                        # out by cumulative position, so a naive full
                        # re-solve would reshuffle boundary objects that
                        # didn't need to move. Instead each object KEEPS its
                        # seat iff the seat is alive and the object is
                        # within its node's capacity-fair share (per-node
                        # rank < fair); everything else — dead seats and
                        # over-fair overflow — is waterfilled into the
                        # survivors' remaining headroom. Churn then moves
                        # exactly the displaced share, and pure load skew
                        # moves only the overflow, mirroring what the
                        # move-cost discount does for the OT modes.
                        from ..ops.assignment import rank_within_group

                        cur = jnp.zeros((bucket,), jnp.int32).at[:n].set(
                            jnp.asarray(cur_idx)
                        )
                        # Rank of each object among its node's objects.
                        # Stable sort keeps padding rows (mass 0, cur 0)
                        # after the real rows of node 0, so real ranks are
                        # unaffected.
                        order, _, rank_sorted = rank_within_group(cur)
                        rank = jnp.zeros((bucket,), jnp.int32).at[order].set(
                            rank_sorted
                        )
                        cap_alive = cap * alive
                        fair = (
                            jnp.sum(mass)
                            * cap_alive
                            / jnp.maximum(jnp.sum(cap_alive), 1e-30)
                        )
                        keep = (alive[cur] > 0) & (mass > 0) & (rank < fair[cur])
                        kept_load = jnp.zeros_like(cap).at[cur].add(
                            jnp.where(keep, mass, 0.0)
                        )
                        refill = greedy_balanced_assign(
                            cost,
                            jnp.where(keep, 0.0, mass),
                            cap_alive,
                            node_load=kept_load,
                        )
                        assignment = jnp.where(keep, cur, refill)
                        g = None
            out = _route_unseatable(
                np.asarray(assignment)[:n], len(node_order), load, alive, cap
            )
            # Communication-graph refinement (full solves only: the delta
            # path returned above, and its warm potentials price pure
            # balance). Runs on the already-routed assignment so the
            # refine's stay-put baseline is a feasible seating.
            if self._affinity_weight > 0.0 and self._edge_graph:
                try:
                    refined = self._affinity_refine(
                        keys, out, node_order, cap, alive
                    )
                except Exception:  # noqa: BLE001 - refine must never kill a solve
                    log.exception("affinity refine failed; keeping base solve")
                    refined = None
                if refined is not None:
                    out = _route_unseatable(
                        refined, len(node_order), load, alive, cap
                    )
                    solved_as = f"{solved_as}+affinity"
            solve_ms, conv = _conv_timing(conv, t0, c0)
            return out, g, coarse_g, solve_ms, solved_as, n, False, conv

        (
            assignment, g, coarse_g, solve_ms, solved_as, displaced, stale, conv
        ) = await asyncio.to_thread(_solve)

        async with self._lock:
            if self._epoch != snapshot_epoch:
                # Record the discarded ATTEMPT as its own stats event (the
                # next completed solve archives it into history like any
                # other) instead of mutating the prior completed solve's
                # record in place — that flag-flip misrepresented a
                # finished solve as discarded once history kept it.
                self.stats = SolveStats(
                    n_objects=n,
                    n_nodes=len(self._node_order),
                    solve_ms=solve_ms,
                    displaced=displaced,
                    epoch=self._epoch,
                    mode=solved_as,
                    discarded=True,
                    history=self._archived_history(),
                    **_conv_fields(conv),
                )
                return 0
            # Touch only the movers: non-movers are _set_placement no-ops
            # by definition (epoch unchanged => directory equals the
            # cur_idx snapshot), and the vectorized compare turns the
            # apply from an O(N) Python loop under the lock (~0.3 s/1M,
            # the dominant host cost of a churn rebalance) into
            # O(movers) — typically the displaced few percent.
            hist = self._archived_history()
            t_apply = time.perf_counter()
            mover_pos = np.nonzero(assignment != cur_idx)[0]
            moved = 0
            planned: list[tuple[str, str, str]] = []
            for p in mover_pos.tolist():
                if move_sink is not None:
                    # Plan, don't apply: the row flips when the sink's
                    # handoff commits (or never, if it aborts — the lazy
                    # request path and the next churn solve cover it).
                    planned.append(
                        (
                            keys[p],
                            node_order[int(cur_idx[p])],
                            node_order[int(assignment[p])],
                        )
                    )
                elif self._set_placement(keys[p], int(assignment[p])):
                    moved += 1
            if move_sink is not None:
                moved = len(planned)
            if g is not None:
                self._g = g
                self._g_fp = self._sched_fp()
            self._recount_loads()
            self._epoch += 1
            if not solved_as.endswith("+no_capacity"):
                # Commit the plan the NEXT churn event deltas against. A
                # delta that produced no fresh potentials (greedy fill,
                # hierarchical, empty displaced set) carries the previous
                # seeds forward; a full solve resets the staleness counter.
                delta_used = solved_as.endswith("+delta")
                self._plan = PlanState(
                    g=(
                        g
                        if g is not None
                        else (plan.g if delta_used and plan is not None else None)
                    ),
                    coarse_g=(
                        coarse_g
                        if coarse_g is not None
                        else (
                            plan.coarse_g
                            if delta_used and plan is not None
                            else None
                        )
                    ),
                    seat_counts=np.bincount(
                        assignment, minlength=self._node_axis
                    ),
                    epoch=self._epoch,
                    liveness_fp=self._sched_fp(),
                    delta_solves=(
                        plan.delta_solves + 1
                        if delta_used and plan is not None
                        else 0
                    ),
                    stale=stale,
                )
            self.stats = SolveStats(
                n_objects=n,
                n_nodes=len(self._node_order),
                solve_ms=solve_ms,
                apply_ms=(time.perf_counter() - t_apply) * 1e3,
                moved=moved,
                displaced=displaced,
                epoch=self._epoch,
                mode=solved_as,
                discarded=False,
                history=hist,
                **_conv_fields(conv),
            )
        if planned:
            # Grouped emission: the migration engine batches one burst per
            # (source, target) pair, so hand it the plan already ordered by
            # that pair — contiguous runs become whole MigrateBatch frames.
            planned.sort(key=lambda m: (m[1], m[2]))
            # Outside the lock on purpose: each handoff calls back into
            # update()/lookup(), which take it.
            await move_sink(planned)
        return moved
