"""Type-keyed shared state container.

Reference: ``rio-rs/src/app_data.rs:27-48`` — a ``Send+Sync`` type map used
to inject state providers, the internal-client/admin channels, the
``MessageRouter``, and app singletons into handlers.
"""

from __future__ import annotations

from typing import Any, Callable, TypeVar

T = TypeVar("T")


class AppData:
    """One value per type; handlers receive this as their context argument."""

    def __init__(self) -> None:
        self._values: dict[type, Any] = {}

    def set(self, value: Any, *, as_type: type | None = None) -> "AppData":
        self._values[as_type or type(value)] = value
        return self

    def get(self, ty: type[T]) -> T:
        try:
            return self._values[ty]
        except KeyError:
            raise KeyError(f"AppData has no value of type {ty.__name__}") from None

    def try_get(self, ty: type[T]) -> T | None:
        return self._values.get(ty)

    def get_or_default(self, ty: type[T], factory: Callable[[], T] | None = None) -> T:
        """Reference ``app_data.rs:37-48``: fetch or insert a default."""
        if ty not in self._values:
            self._values[ty] = (factory or ty)()
        return self._values[ty]

    def __contains__(self, ty: type) -> bool:
        return ty in self._values
