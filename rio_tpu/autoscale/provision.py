"""NodeProvisioner implementations: where elastic nodes actually come from.

Two real backends ship (both used by the tests, the ``--demo`` smoke, and
the ramp soak):

* :class:`InProcessProvisioner` — new nodes are :class:`~rio_tpu.server.
  Server` instances run as tasks on the calling loop, joining the shared
  membership/placement storages. Zero-process, deterministic, fast: the
  unit/integration tier and the bench A/B use it.
* :class:`SubprocessProvisioner` — new nodes are real OS processes
  (``python -m rio_tpu.autoscale --node``) joining shared sqlite
  storages, the :mod:`rio_tpu.sharded` worker discipline (clean child
  env, JSON spec on stdin, READY line, death-monitor thread marking the
  member inactive). The ramp soak SIGKILLs these mid-drain — the chaos
  case the scale-in state machine must absorb.

A cloud provisioner (ASG/MIG/k8s) implements the same trait; nothing in
the controller knows the difference.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Any, Callable

from ..sharded import _load_factory, _reserve_port
from . import NodeProvisioner


class InProcessProvisioner(NodeProvisioner):
    """Elastic nodes as server tasks in the current event loop.

    Every provisioned node shares the caller's membership + placement
    storages (the in-process cluster shape of ``tests/server_utils.py``),
    so churn, rebalance, and drain all behave exactly as they do across
    real processes — minus the process boundary.
    """

    def __init__(
        self,
        members_storage: Any,
        placement: Any,
        *,
        registry_builder: Callable[[], Any],
        server_kwargs: dict | None = None,
        app_data_builder: Callable[[], Any] | None = None,
    ) -> None:
        self._members = members_storage
        self._placement = placement
        self._registry_builder = registry_builder
        self._server_kwargs = dict(server_kwargs or {})
        # One AppData per server (a shared instance would collide on the
        # per-node senders the server registers into it); the builder is
        # how chaos tests seat a SHARED state provider on every node.
        self._app_data_builder = app_data_builder
        self._nodes: dict[str, tuple[Any, asyncio.Task]] = {}
        self.provisioned_total = 0
        self.retired_total = 0

    async def provision(self) -> str:
        from ..cluster.membership_protocol import LocalClusterProvider
        from ..server import Server

        kwargs = dict(self._server_kwargs)
        if self._app_data_builder is not None:
            kwargs["app_data"] = self._app_data_builder()
        server = Server(
            address="127.0.0.1:0",
            registry=self._registry_builder(),
            cluster_provider=LocalClusterProvider(self._members),
            object_placement_provider=self._placement,
            **kwargs,
        )
        await server.prepare()
        address = await server.bind()
        task = asyncio.ensure_future(server.run())
        self._nodes[address] = (server, task)
        self.provisioned_total += 1
        return address

    async def retire(self, address: str, *, force: bool = False) -> None:
        server, task = self._nodes.pop(address, (None, None))
        if server is None:
            return
        self.retired_total += 1
        if not task.done():
            if force:
                # Forced retire (drain timed out / victim unresponsive):
                # cut the task — run()'s finally still marks the member
                # inactive and closes the listener.
                task.cancel()
            else:
                # Normally the drain already stopped the node; a straggler
                # gets the graceful path rather than a cancel.
                from ..commands import AdminCommand

                server.admin_sender().send(AdminCommand.drain())
        # A forced retire cancelled the task above — shield re-raises that
        # CancelledError here, so it must be suppressed alongside Exception.
        with contextlib.suppress(Exception, asyncio.CancelledError):
            await asyncio.wait_for(asyncio.shield(task), timeout=10.0)
        if not task.done():
            task.cancel()
        with contextlib.suppress(Exception):
            await asyncio.gather(task, return_exceptions=True)
        # Converge membership (the SubprocessProvisioner monitor-thread
        # contract): the node's own teardown set_inactive may have failed —
        # e.g. killed during a storage outage — and a retired-but-"active"
        # member pins its directory rows to a dead address until the
        # heartbeat TTL ages out.
        host, _, port = address.rpartition(":")
        with contextlib.suppress(Exception):
            await self._members.set_inactive(host, int(port))

    def managed(self) -> list[str]:
        return list(self._nodes)

    def server(self, address: str) -> Any:
        """Test hook: the live Server behind a managed address."""
        entry = self._nodes.get(address)
        return entry[0] if entry else None

    def kill(self, address: str) -> None:
        """Chaos hook: abrupt death (the in-process analogue of SIGKILL) —
        cancel the serve task with no drain; the run() teardown marks the
        member inactive just as the sharded monitor thread would."""
        entry = self._nodes.get(address)
        if entry is not None:
            entry[1].cancel()


class SubprocessProvisioner(NodeProvisioner):
    """Elastic nodes as real worker processes over shared sqlite storage.

    The :mod:`rio_tpu.sharded` worker discipline, minus the fixed-width
    shard map: reserve an ephemeral identity port, spawn ``python -m
    rio_tpu.autoscale --node`` with a clean environment and a JSON spec on
    stdin, wait for the address to turn active in shared membership, and
    run a monitor thread that marks the member inactive the moment the
    process dies (the supervisor half of crash reseat — and what turns a
    mid-drain SIGKILL into the dead-owner branch on the survivors).
    """

    def __init__(
        self,
        data_dir: str,
        *,
        registry: str = "rio_tpu.utils.routing_live:build_echo_registry",
        members: str = "rio_tpu.sharded:sqlite_members",
        placement: str = "rio_tpu.sharded:sqlite_placement",
        state: str = "",
        host: str = "127.0.0.1",
        server_kwargs: dict | None = None,
        python: str = sys.executable,
        ready_timeout: float = 60.0,
    ) -> None:
        self.data_dir = data_dir
        self.registry_spec = registry
        self.members_spec = members
        self.placement_spec = placement
        # Optional shared StateProvider factory ("module:callable" over
        # data_dir): with it, acked writes survive a SIGKILLed node — the
        # reseated actor reloads at activation (the soak's zero-loss bar).
        self.state_spec = state
        self.host = host
        self.server_kwargs = dict(server_kwargs or {})
        self.python = python
        self.ready_timeout = ready_timeout
        self._procs: dict[str, subprocess.Popen] = {}
        self._logs: dict[str, Any] = {}
        self._reservations: dict[str, Any] = {}
        self._retiring: set[str] = set()
        self.provisioned_total = 0
        self.retired_total = 0

    def _child_env(self) -> dict:
        # Clean environment, the multihost-test discipline: an ambient
        # sitecustomize (accelerator plugin registration) must not leak
        # into elastic workers; they pin CPU unless told otherwise.
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        return {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": repo_root,
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        }

    async def provision(self) -> str:
        reservation, port = _reserve_port(self.host)
        address = f"{self.host}:{port}"
        spec = {
            "bind_host": self.host,
            "identity_port": port,
            "advertise": address,
            "reuse_port": reservation is not None,
            "registry": self.registry_spec,
            "members": self.members_spec,
            "placement": self.placement_spec,
            "state": self.state_spec,
            "data_dir": self.data_dir,
            "server_kwargs": self.server_kwargs,
        }
        log_f = open(
            os.path.join(self.data_dir, f"autoscale-node-{port}.log"), "wb"
        )
        proc = subprocess.Popen(
            [self.python, "-m", "rio_tpu.autoscale", "--node"],
            stdin=subprocess.PIPE,
            stdout=log_f,
            stderr=subprocess.STDOUT,
            env=self._child_env(),
            close_fds=True,
        )
        assert proc.stdin is not None
        proc.stdin.write(json.dumps(spec).encode())
        proc.stdin.close()
        self._procs[address] = proc
        self._logs[address] = log_f
        if reservation is not None:
            self._reservations[address] = reservation
        try:
            await self._wait_active(address, proc)
        except Exception:
            with contextlib.suppress(Exception):
                proc.kill()
            self._drop(address)
            raise
        threading.Thread(
            target=self._monitor, args=(address, proc), daemon=True
        ).start()
        self.provisioned_total += 1
        return address

    async def _wait_active(self, address: str, proc: subprocess.Popen) -> None:
        members = _load_factory(self.members_spec)(self.data_dir)
        try:
            await members.prepare()
            deadline = time.monotonic() + self.ready_timeout
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"autoscale node {address} died during boot "
                        f"(rc={proc.returncode}); see its log in {self.data_dir}"
                    )
                active = {m.address for m in await members.active_members()}
                if address in active:
                    return
                await asyncio.sleep(0.05)
            raise TimeoutError(
                f"autoscale node {address} not active within "
                f"{self.ready_timeout}s"
            )
        finally:
            with contextlib.suppress(Exception):
                members.close()

    def _monitor(self, address: str, proc: subprocess.Popen) -> None:
        """Mark a dead node inactive in membership (supervisor half of the
        crash-reseat story; idempotent beside a graceful self-mark)."""
        proc.wait()
        if address in self._retiring:
            return
        with contextlib.suppress(Exception):
            asyncio.run(self._mark_inactive(address))

    async def _mark_inactive(self, address: str) -> None:
        members = _load_factory(self.members_spec)(self.data_dir)
        try:
            host, _, port = address.rpartition(":")
            await members.set_inactive(host, int(port))
        finally:
            with contextlib.suppress(Exception):
                members.close()

    def terminate(self, address: str, sig: int = signal.SIGKILL) -> None:
        """Chaos hook: kill a managed node (default SIGKILL — the monitor
        thread records the death in membership as for a real crash)."""
        proc = self._procs.get(address)
        if proc is not None:
            with contextlib.suppress(ProcessLookupError):
                proc.send_signal(sig)

    async def retire(self, address: str, *, force: bool = False) -> None:
        proc = self._procs.get(address)
        if proc is None:
            return
        self._retiring.add(address)
        self.retired_total += 1
        try:
            if force and proc.poll() is None:
                with contextlib.suppress(ProcessLookupError):
                    proc.send_signal(signal.SIGTERM)
            # A drained node exits by itself; give it (or the SIGTERM
            # drain handler) a bounded window, then escalate.
            deadline = time.monotonic() + 10.0
            while proc.poll() is None and time.monotonic() < deadline:
                await asyncio.sleep(0.05)
            if proc.poll() is None:
                with contextlib.suppress(ProcessLookupError):
                    proc.kill()
                while proc.poll() is None:
                    await asyncio.sleep(0.05)
            await self._mark_inactive(address)
        finally:
            self._drop(address)

    def _drop(self, address: str) -> None:
        self._procs.pop(address, None)
        log_f = self._logs.pop(address, None)
        if log_f is not None:
            with contextlib.suppress(OSError):
                log_f.close()
        res = self._reservations.pop(address, None)
        if res is not None:
            with contextlib.suppress(OSError):
                res.close()

    def managed(self) -> list[str]:
        return list(self._procs)

    def node_log(self, address: str) -> str:
        _, _, port = address.rpartition(":")
        path = os.path.join(self.data_dir, f"autoscale-node-{port}.log")
        try:
            with open(path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""
