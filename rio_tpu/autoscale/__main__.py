"""``python -m rio_tpu.autoscale`` — elastic-node worker entry + demo smoke.

Two modes:

* ``--node`` — the :class:`~rio_tpu.autoscale.provision.
  SubprocessProvisioner` child: read a JSON spec from stdin, join the
  shared storages, serve until drained (SIGTERM/SIGINT run the graceful
  drain exactly like a :mod:`rio_tpu.sharded` worker).
* ``--demo`` — self-checking CI smoke: boot a one-node in-process
  cluster with autoscaling enabled, ramp synthetic load up and back
  down, and assert the full causal chain — sustained-overload HEALTH
  alarm → ``scale_out`` SCALE decision → (load off) → ``scale_in`` →
  drain → clean ``retired``. Prints one JSON line + ``OK`` and exits 0;
  any missing link exits 2 with the journal tail for diagnosis.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import signal
import sys
import time


# -- elastic-node worker entry (SubprocessProvisioner child) ------------------


async def _run_node(spec: dict) -> None:
    from .. import Server
    from ..cluster.membership_protocol import LocalClusterProvider
    from ..commands import AdminCommand
    from ..sharded import _load_factory

    members = _load_factory(spec["members"])(spec["data_dir"])
    placement = _load_factory(spec["placement"])(spec["data_dir"])
    registry = _load_factory(spec["registry"])()

    app_data = None
    if spec.get("state"):
        # Shared durable state provider: what lets this node die (even by
        # SIGKILL) without losing a single acked write — survivors reload
        # the state at reseat-activation.
        from ..app_data import AppData
        from ..state import StateProvider

        provider = _load_factory(spec["state"])(spec["data_dir"])
        await provider.prepare()
        app_data = AppData()
        app_data.set(provider, as_type=StateProvider)

    server = Server(
        address=f"{spec['bind_host']}:{spec['identity_port']}",
        advertise_address=spec["advertise"],
        registry=registry,
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=placement,
        app_data=app_data,
        reuse_port=bool(spec.get("reuse_port")),
        **spec.get("server_kwargs", {}),
    )
    await server.prepare()
    await server.bind()
    # Drain-then-exit on supervisor (or operator) signals: the admin queue
    # runs the full graceful path — cordon, lifecycle shutdown for seated
    # objects, release of local directory rows, membership set_inactive.
    loop = asyncio.get_running_loop()
    admin = server.admin_sender()
    for signum in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(
            signum, lambda: admin.send(AdminCommand.drain())
        )
    print(f"READY {server.local_address}", flush=True)
    await server.run()


def _node_main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    spec = json.loads(sys.stdin.read())
    asyncio.run(_run_node(spec))
    return 0


# -- the self-checking demo smoke ---------------------------------------------


async def _run_demo() -> dict:
    from .. import Client, LocalObjectPlacement, LocalStorage, Server
    from ..cluster.membership_protocol import LocalClusterProvider
    from ..commands import AdminCommand
    from ..journal import HEALTH, SCALE
    from ..utils.routing_live import Echo, EchoActor, build_echo_registry
    from . import AutoscaleConfig, ScalePolicy
    from .provision import InProcessProvisioner

    members = LocalStorage()
    placement = LocalObjectPlacement()
    provisioner = InProcessProvisioner(
        members,
        placement,
        registry_builder=build_echo_registry,
        server_kwargs={"load_interval": 0.1},
    )
    # Pure request-rate pressure: deterministic on any CI box (loop lag
    # and inflight snapshots are scheduler-dependent; req/s under a
    # steady driver is not).
    # Bands sized against the demo driver (~2000 req/s up, ~0 down) with
    # the low band well clear of the controller's own poke traffic —
    # ticks and heartbeats are requests too (~3 req/s of floor).
    policy = ScalePolicy(
        min_nodes=1,
        max_nodes=2,
        high_pressure=50.0,
        low_pressure=8.0,
        sustain=2,
        ema_alpha=0.7,
        inflight_weight=0.0,
        lag_weight=0.0,
        rate_weight=1.0,
        shed_weight=0.0,
        out_cooldown_s=0.5,
        in_cooldown_s=0.5,
        cooldown_max_s=2.0,
        drain_timeout_s=30.0,
    )
    supervisor = Server(
        address="127.0.0.1:0",
        registry=build_echo_registry(),
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=placement,
        load_interval=0.1,
        autoscale_config=AutoscaleConfig(
            provisioner=provisioner, policy=policy, interval=0.2
        ),
    )
    await supervisor.prepare()
    await supervisor.bind()
    serve = asyncio.ensure_future(supervisor.run())
    runtime = supervisor.autoscale
    client = Client(members)
    stop_load = asyncio.Event()

    async def writer(i: int) -> None:
        while not stop_load.is_set():
            with contextlib.suppress(Exception):
                await client.send(EchoActor, f"demo-{i % 16}", Echo(value=i))
            await asyncio.sleep(0.005)

    async def wait_for(pred, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            await asyncio.sleep(0.1)
        raise TimeoutError(f"demo: no {what} within {timeout:.0f}s")

    writers: list[asyncio.Task] = []
    try:
        # Ramp up: sustained load must produce exactly one scale-out
        # (max_nodes caps further growth).
        writers = [asyncio.ensure_future(writer(i)) for i in range(24)]
        await wait_for(
            lambda: runtime.scale_outs >= 1, 45.0, "scale-out decision"
        )
        # Ramp down: rate decays under the low band → scale-in → drain →
        # clean retire of the provisioned node.
        stop_load.set()
        for w in writers:
            w.cancel()
        await asyncio.gather(*writers, return_exceptions=True)
        writers = []
        await wait_for(
            lambda: runtime.scale_ins >= 1, 60.0, "completed scale-in"
        )
    finally:
        stop_load.set()
        for w in writers:
            w.cancel()
        await asyncio.gather(*writers, return_exceptions=True)
        with contextlib.suppress(Exception):
            client.close()

    # The causal chain, from the supervisor's journal: the sustained
    # alarm precedes the decision, the decision precedes the retire.
    assert supervisor.journal is not None
    events = supervisor.journal.events(kinds=[HEALTH, SCALE])
    labels = [
        (ev.kind, ev.attrs.get("action", "") or ev.key) for ev in events
    ]

    def index_of(kind: str, name: str) -> int:
        for i, (k, n) in enumerate(labels):
            if k == kind and n == name:
                return i
        raise AssertionError(
            f"demo: no {kind}/{name} event in journal: {labels}"
        )

    alarm_i = index_of(HEALTH, "scale_out_sustained")
    out_i = index_of(SCALE, "scale_out")
    in_i = index_of(SCALE, "scale_in")
    retired_i = index_of(SCALE, "retired")
    assert alarm_i < out_i < in_i < retired_i, (
        f"demo: causal chain out of order: {labels}"
    )
    retired_ev = events[retired_i]
    assert not retired_ev.attrs.get("forced"), (
        f"demo: scale-in was forced, not a clean drain: {retired_ev.attrs}"
    )
    result = {
        "scale_outs": runtime.scale_outs,
        "scale_ins": runtime.scale_ins,
        "final_nodes": runtime.last_nodes,
        "pressure": round(runtime.pressure, 3),
        "chain": [f"{k}:{n}" for k, n in labels],
    }

    supervisor.admin_sender().send(AdminCommand.server_exit())
    with contextlib.suppress(Exception):
        await asyncio.wait_for(serve, timeout=10.0)
    await provisioner.close()
    await runtime.close()
    return result


def _demo_main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        result = asyncio.run(asyncio.wait_for(_run_demo(), timeout=150.0))
    except BaseException as e:  # noqa: BLE001 — smoke must exit nonzero, loudly
        print(f"DEMO FAILED: {e!r}", file=sys.stderr)
        return 2
    print(json.dumps(result))
    print("OK")
    return 0


def _main() -> int:
    argv = sys.argv[1:]
    if "--node" in argv:
        return _node_main()
    if "--demo" in argv:
        return _demo_main()
    print(
        "usage: python -m rio_tpu.autoscale (--demo | --node < spec.json)",
        file=sys.stderr,
    )
    return 2


if __name__ == "__main__":
    sys.exit(_main())
