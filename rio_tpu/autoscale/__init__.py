"""Elastic autoscaling: the cluster that sizes itself (ISSUE 19).

The serving plane already *measures* everything this needs — per-node
:class:`~rio_tpu.load.LoadVector` heartbeats, the cluster-aggregate
``rio.cluster.*`` gauges, :class:`~rio_tpu.health.HealthWatch` trend
rules over the gauge time-series — and already *actuates* everything this
needs: drain (cordon + reminder handoff + coordinated move-out), the
churn-kicked delta re-solve in the placement daemon, membership
liveness. This module closes the loop: an :class:`AutoscaleRuntime`
behind a directory-seated singleton actor (``rio.Autoscale``) that turns
sustained load *trends* into node provision/retire decisions.

Design rules (each one an operational lesson from the TPU rounds):

- **Scale on the trend, never the instant gauge.** Every decision is
  gated on a :class:`~rio_tpu.health.TrendRule` alert over the
  controller's own gauge series (``rio.autoscale.overload`` /
  ``rio.autoscale.underload`` rise one step per consecutive
  out-of-band tick) — a single spiky sample can never resize the
  cluster, and every decision has a journaled ``HEALTH`` alarm as its
  cause ("no decision without a journaled trigger").
- **Hysteresis + decorrelated cooldowns.** Separate high/low pressure
  bands keep the controller quiet in between;
  :class:`~rio_tpu.utils.backoff.DecorrelatedJitter` cooldowns after
  each decision stop resize oscillation (and decorrelate multiple
  clusters sharing one provisioning backend).
- **One controller, seated like any actor.** ``rio.Autoscale`` is a
  normal placement-directory singleton: every autoscale-enabled node
  pokes it each interval through its own dispatch path
  (:meth:`~rio_tpu.service_object.ServiceObject.send`); the owner's poke
  ticks it, non-owners' pokes are redirected away, and when the owner
  dies the survivors' pokes reseat it through the standard dead-owner
  branch — the controller inherits the framework's own failover.
- **Actuate through existing machinery.** Scale-out asks the pluggable
  :class:`NodeProvisioner` for a node and lets membership churn kick the
  placement daemon's delta re-solve; scale-in cordons + drains the
  victim through the stock ``rio.Admin`` ``drain_server`` flow (reminder
  handoff, coordinated handoffs, directory release) and only then
  retires the process.

Every decision and actuation edge is a ``SCALE`` journal event carrying
the trigger rule, the gauge evidence, and the chosen node — ``python -m
rio_tpu.admin scale`` renders policy state, cooldowns, and the recent
decision log; ``python -m rio_tpu.autoscale --demo`` is the self-checking
smoke (one scale-out, one clean scale-in, causal journal).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import time
from dataclasses import dataclass, field
from typing import Any

from ..app_data import AppData
from ..health import HealthWatch, TrendRule
from ..journal import SCALE, Journal
from ..load import ClusterLoadView
from ..registry import handler, message, type_name
from ..service_object import ServiceObject
from ..timeseries import GaugeSeries
from ..utils.backoff import DecorrelatedJitter

__all__ = [
    "AUTOSCALE_TYPE",
    "AUTOSCALE_ID",
    "ScalePolicy",
    "AutoscaleConfig",
    "NodeProvisioner",
    "AutoscaleRuntime",
    "AutoscaleControl",
    "ScaleTick",
    "ScaleTickAck",
    "ScaleStatus",
    "ScaleSnapshot",
]

log = logging.getLogger("rio_tpu.autoscale")

#: Wire type-name of the singleton controller actor.
AUTOSCALE_TYPE = "rio.Autoscale"
#: The singleton's object id (one controller per cluster).
AUTOSCALE_ID = "controller"


# -- wire messages ------------------------------------------------------------


@message(name="rio.ScaleTick")
@dataclass
class ScaleTick:
    """Periodic poke from every autoscale-enabled node's loop."""

    source: str = ""  # poking node's address (observability only)


@message(name="rio.ScaleTickAck")
@dataclass
class ScaleTickAck:
    acted: bool = False
    action: str = ""  # scale_out | scale_in | "" (no decision this tick)
    detail: str = ""


@message(name="rio.ScaleStatus")
@dataclass
class ScaleStatus:
    """Ask the controller for its policy/decision state (CLI ``scale``)."""

    limit: int = 32  # newest decision rows returned


@message(name="rio.ScaleSnapshot")
@dataclass
class ScaleSnapshot:
    """Controller state for operators; ``decisions`` rows are positional
    ``[wall_ts, action, node, rule, pressure, nodes, detail]`` and may only
    ever grow by appending trailing fields."""

    address: str = ""  # node currently hosting the controller
    pressure: float = 0.0
    nodes: int = 0
    over_streak: int = 0
    under_streak: int = 0
    cooldown_s: float = 0.0
    pending: str = ""  # victim address mid-drain ("" when idle)
    scale_outs: int = 0
    scale_ins: int = 0
    ticks: int = 0
    alerts: list = field(default_factory=list)
    policy: dict = field(default_factory=dict)
    decisions: list = field(default_factory=list)


# -- policy -------------------------------------------------------------------


@dataclass(frozen=True)
class ScalePolicy:
    """Target-band policy over a blended cluster pressure signal.

    ``pressure = inflight/node·w_inflight + loop_lag_mean_ms·w_lag +
    req_rate/node·w_rate + shed_rate/node·w_shed``, EMA-smoothed, then
    compared against a hysteresis band: above ``high_pressure`` for
    ``sustain`` consecutive ticks → scale out (until ``max_nodes``);
    below ``low_pressure`` for ``sustain`` ticks → scale in (until
    ``min_nodes``). The sustain requirement is enforced *as a trend
    rule* over the controller's own series (see :meth:`rules`), so the
    journaled ``HEALTH`` alarm is the decision's recorded cause.
    """

    min_nodes: int = 1
    max_nodes: int = 8
    high_pressure: float = 50.0
    low_pressure: float = 5.0
    sustain: int = 3  # consecutive out-of-band ticks before acting
    ema_alpha: float = 0.5  # pressure smoothing (1.0 = raw signal)
    inflight_weight: float = 1.0
    lag_weight: float = 1.0
    rate_weight: float = 0.0  # opt-in: req_rate/node term (demo/soak use it)
    shed_weight: float = 10.0  # sheds are the loudest overload signal
    # Opt-in (like rate_weight): interactive-class QoS pain — priority>0
    # admission sheds + deadline drops per node per second, from the
    # heartbeat vector's qos_interactive counter. Weighting it makes the
    # cluster grow for the latency-sensitive class specifically, even
    # while bulk-tenant throughput looks healthy.
    interactive_weight: float = 0.0
    out_cooldown_s: float = 5.0  # jitter base after a scale-out
    in_cooldown_s: float = 15.0  # jitter base after a completed scale-in
    cooldown_max_s: float = 120.0  # jitter cap, both directions
    drain_timeout_s: float = 60.0  # victim grace before forced retire

    def pressure_of(
        self,
        agg: dict[str, float],
        shed_rate_per_node: float = 0.0,
        interactive_rate_per_node: float = 0.0,
    ) -> float:
        """Blend one ``ClusterLoadView.aggregate_gauges()`` snapshot."""
        nodes = max(1.0, agg.get("rio.cluster.nodes", 0.0))
        return (
            agg.get("rio.cluster.inflight_total", 0.0) / nodes * self.inflight_weight
            + agg.get("rio.cluster.loop_lag_mean_ms", 0.0) * self.lag_weight
            + agg.get("rio.cluster.req_rate_total", 0.0) / nodes * self.rate_weight
            + shed_rate_per_node * self.shed_weight
            + interactive_rate_per_node * self.interactive_weight
        )

    def rules(self) -> list[TrendRule]:
        """The controller's alarm set: decisions are gated on the first
        two (``*_sustained`` — the streak gauges rise one step per
        consecutive out-of-band tick, so "rose K consecutive windows"
        IS "out of band for K ticks"); the ``pressure_*`` pair is
        informational trend context in the same journal."""
        k = max(1, int(self.sustain))
        return [
            TrendRule(
                name="scale_out_sustained",
                gauge="rio.autoscale.overload",
                kind="rising",
                windows=k,
                cooldown=k,
            ),
            TrendRule(
                name="scale_in_sustained",
                gauge="rio.autoscale.underload",
                kind="rising",
                windows=k,
                cooldown=k,
            ),
            TrendRule(
                name="pressure_rising",
                gauge="rio.autoscale.pressure",
                kind="rising",
                windows=k,
                cooldown=max(k, 10),
            ),
            TrendRule(
                name="pressure_falling",
                gauge="rio.autoscale.pressure",
                kind="falling",
                windows=k,
                cooldown=max(k, 10),
            ),
        ]

    def as_dict(self) -> dict[str, float]:
        return {
            "min_nodes": float(self.min_nodes),
            "max_nodes": float(self.max_nodes),
            "high_pressure": float(self.high_pressure),
            "low_pressure": float(self.low_pressure),
            "sustain": float(self.sustain),
            "out_cooldown_s": float(self.out_cooldown_s),
            "in_cooldown_s": float(self.in_cooldown_s),
            "cooldown_max_s": float(self.cooldown_max_s),
            "drain_timeout_s": float(self.drain_timeout_s),
        }


# -- provisioner trait --------------------------------------------------------


class NodeProvisioner:
    """Actuation backend: where nodes come from and go to.

    Implementations: :class:`~rio_tpu.autoscale.provision.
    InProcessProvisioner` (servers as tasks in this loop — tests, the
    ``--demo`` smoke) and :class:`~rio_tpu.autoscale.provision.
    SubprocessProvisioner` (real OS processes joining shared storage —
    soaks, chaos). A cloud backend implements the same four methods.
    """

    async def provision(self) -> str:
        """Boot one node into the cluster; return its advertised address
        (the node must already be registering itself in membership)."""
        raise NotImplementedError

    async def retire(self, address: str, *, force: bool = False) -> None:
        """Reclaim a node this provisioner booted. Called after the drain
        completed (the address left the membership view) — or with
        ``force=True`` when the drain blew its timeout."""
        raise NotImplementedError

    def managed(self) -> list[str]:
        """Addresses this provisioner booted and still owns. The victim
        picker only retires managed nodes (never the seed nodes an
        operator booted by hand); empty means "anything but me"."""
        return []

    async def close(self) -> None:
        """Force-retire everything still managed (test/soak teardown)."""
        for address in list(self.managed()):
            with contextlib.suppress(Exception):
                await self.retire(address, force=True)


@dataclass
class AutoscaleConfig:
    """``Server(autoscale_config=...)`` knob bundle."""

    provisioner: NodeProvisioner
    policy: ScalePolicy = field(default_factory=ScalePolicy)
    interval: float = 1.0  # poke cadence per enabled node
    series_capacity: int = 240  # controller gauge-series ring


# -- the controller runtime ---------------------------------------------------


class AutoscaleRuntime:
    """Per-node autoscale state; *acts* only on the node that currently
    owns the ``rio.Autoscale`` seat.

    Created at ``Server.bind()`` on every node constructed with an
    :class:`AutoscaleConfig` and injected into AppData; the actor handler
    resolves it there, so whichever enabled node the directory seats the
    controller on ticks with its own membership view, journal, and
    provisioner handle. Single-ticker by construction: ticks arrive
    through the actor's per-object lock, plus a reentrancy flag for
    belt-and-braces.
    """

    def __init__(
        self,
        *,
        address: str,
        members_storage: Any,
        config: AutoscaleConfig,
        app_data: AppData,
        journal: Journal | None = None,
    ) -> None:
        self.address = address
        self.policy = config.policy
        self.provisioner = config.provisioner
        self.interval = max(0.05, float(config.interval))
        self.app_data = app_data
        self.journal = journal
        self._members = members_storage
        # The controller's own trend memory: pressure + streak gauges per
        # tick, evaluated by a private HealthWatch running policy.rules()
        # (sampled manually — the cadence is the tick, not wall time).
        self.series = GaugeSeries(
            capacity=config.series_capacity, node=address, interval=0.01
        )
        self.watch = HealthWatch(
            self.series, journal=journal, rules=self.policy.rules()
        )
        self.pressure = 0.0
        self.over_streak = 0
        self.under_streak = 0
        self.last_nodes = 0
        self.scale_outs = 0
        self.scale_ins = 0
        self.ticks = 0
        self.decisions: list[list[Any]] = []  # ScaleSnapshot wire rows
        self._out_jitter = DecorrelatedJitter(
            base=self.policy.out_cooldown_s, cap=self.policy.cooldown_max_s
        )
        self._in_jitter = DecorrelatedJitter(
            base=self.policy.in_cooldown_s, cap=self.policy.cooldown_max_s
        )
        self._cooldown_until = 0.0  # monotonic
        self._pending: dict[str, Any] | None = None  # scale-in in flight
        self._prev_sheds: float | None = None
        self._prev_mono: float | None = None
        self._shed_rate = 0.0
        self._prev_interactive: float | None = None
        self._interactive_rate = 0.0
        self._ticking = False
        self._client = None  # lazy rio_tpu.Client for drain requests

    # -- the tick (runs on the owning node, under the actor lock) ------------

    async def tick(self) -> ScaleTickAck:
        if self._ticking:
            return ScaleTickAck(detail="reentrant tick dropped")
        self._ticking = True
        try:
            return await self._tick_inner()
        finally:
            self._ticking = False

    async def _tick_inner(self) -> ScaleTickAck:
        now = time.monotonic()
        members = await self._members.active_members()
        addrs = {m.address for m in members}
        view = ClusterLoadView.from_members(members)
        agg = view.aggregate_gauges()
        nodes = len(addrs)
        self.last_nodes = nodes

        # Shed *rate* from the monotonic cluster total (the gauge itself
        # only ever rises; the policy wants pressure, not history).
        sheds = agg.get("rio.cluster.sheds_total", 0.0)
        interactive = agg.get("rio.cluster.qos_interactive_total", 0.0)
        if self._prev_mono is not None and now > self._prev_mono:
            delta = max(0.0, sheds - self._prev_sheds)
            self._shed_rate = delta / (now - self._prev_mono)
            idelta = max(0.0, interactive - (self._prev_interactive or 0.0))
            self._interactive_rate = idelta / (now - self._prev_mono)
        self._prev_sheds, self._prev_mono = sheds, now
        self._prev_interactive = interactive

        raw = self.policy.pressure_of(
            agg,
            shed_rate_per_node=self._shed_rate / max(1, nodes),
            interactive_rate_per_node=self._interactive_rate / max(1, nodes),
        )
        alpha = min(1.0, max(0.01, self.policy.ema_alpha))
        self.pressure = (
            raw if self.ticks == 0 else alpha * raw + (1 - alpha) * self.pressure
        )
        self.ticks += 1

        # Hysteresis band → monotone streak counters. The streaks (not the
        # EMA) feed the sustain rules: they keep strictly rising while the
        # gauge sits out of band, so the alert stays derivable even after
        # the EMA flattens at its asymptote.
        if self.pressure > self.policy.high_pressure:
            self.over_streak += 1
            self.under_streak = 0
        elif self.pressure < self.policy.low_pressure:
            self.under_streak += 1
            self.over_streak = 0
        else:
            self.over_streak = 0
            self.under_streak = 0

        sample = dict(agg)
        sample.update(
            {
                "rio.autoscale.pressure": self.pressure,
                "rio.autoscale.overload": float(self.over_streak),
                "rio.autoscale.underload": float(self.under_streak),
                "rio.autoscale.nodes": float(nodes),
            }
        )
        self.series.sample(sample)
        alerts = {a.rule for a in self.watch.tick()}

        # A scale-in mid-flight owns the controller until the victim is
        # gone (or the drain times out) — no overlapping decisions.
        if self._pending is not None:
            return await self._advance_pending(addrs, now)
        if now < self._cooldown_until:
            return ScaleTickAck(
                detail=f"cooldown {self._cooldown_until - now:.1f}s"
            )

        if (
            "scale_out_sustained" in alerts
            and self.over_streak >= self.policy.sustain
            and nodes < self.policy.max_nodes
        ):
            return await self._scale_out(agg, nodes)
        if (
            "scale_in_sustained" in alerts
            and self.under_streak >= self.policy.sustain
            and nodes > self.policy.min_nodes
        ):
            return await self._begin_scale_in(view, addrs, agg, nodes, now)
        return ScaleTickAck()

    # -- actuation ------------------------------------------------------------

    def _evidence(self, agg: dict[str, float]) -> dict[str, float]:
        """The gauge evidence journaled with every decision."""
        return {
            "pressure": round(self.pressure, 4),
            "loop_lag_mean_ms": round(
                agg.get("rio.cluster.loop_lag_mean_ms", 0.0), 3
            ),
            "inflight_total": agg.get("rio.cluster.inflight_total", 0.0),
            "req_rate_total": round(
                agg.get("rio.cluster.req_rate_total", 0.0), 2
            ),
            "shed_rate": round(self._shed_rate, 3),
        }

    def _record(
        self, action: str, node: str, rule: str, nodes: int, detail: str = ""
    ) -> None:
        row = [
            time.time(),
            action,
            node,
            rule,
            round(self.pressure, 4),
            nodes,
            detail,
        ]
        self.decisions.append(row)
        if len(self.decisions) > 256:
            del self.decisions[: len(self.decisions) - 256]

    def _journal(self, action: str, key: str, **attrs: Any) -> None:
        if self.journal is not None:
            self.journal.record(SCALE, key, action=action, **attrs)

    async def _scale_out(
        self, agg: dict[str, float], nodes: int
    ) -> ScaleTickAck:
        rule = "scale_out_sustained"
        try:
            new_addr = await self.provisioner.provision()
        except Exception as e:  # noqa: BLE001 — a dead backend must not kill ticks
            detail = repr(e)[:160]
            self._journal(
                "scale_out_failed", "", rule=rule, error=detail,
                nodes=nodes, **self._evidence(agg),
            )
            self._record("scale_out_failed", "", rule, nodes, detail)
            self._arm_cooldown(self._out_jitter)
            log.warning("%s: scale-out failed: %s", self.address, detail)
            return ScaleTickAck(action="scale_out", detail=detail)
        self.scale_outs += 1
        self._journal(
            "scale_out", new_addr, rule=rule, nodes=nodes,
            band_high=self.policy.high_pressure, **self._evidence(agg),
        )
        self._record("scale_out", new_addr, rule, nodes)
        self._arm_cooldown(self._out_jitter)
        log.info(
            "%s: scale-out -> %s (pressure %.2f over %d ticks, %d nodes)",
            self.address, new_addr, self.pressure, self.over_streak, nodes,
        )
        # The new member registering itself is the churn that kicks the
        # placement daemon's delta re-solve — load spreads from there.
        return ScaleTickAck(acted=True, action="scale_out", detail=new_addr)

    async def _begin_scale_in(
        self,
        view: ClusterLoadView,
        addrs: set[str],
        agg: dict[str, float],
        nodes: int,
        now: float,
    ) -> ScaleTickAck:
        rule = "scale_in_sustained"
        victim = self._pick_victim(view, addrs)
        if victim is None:
            return ScaleTickAck(detail="no eligible victim")
        self._journal(
            "scale_in", victim, rule=rule, nodes=nodes,
            band_low=self.policy.low_pressure, **self._evidence(agg),
        )
        self._record("scale_in", victim, rule, nodes)
        self._pending = {
            "victim": victim,
            "deadline": now + self.policy.drain_timeout_s,
            "rule": rule,
        }
        log.info(
            "%s: scale-in victim %s (pressure %.2f under %d ticks, %d nodes)",
            self.address, victim, self.pressure, self.under_streak, nodes,
        )
        await self._request_drain(victim)
        return ScaleTickAck(acted=True, action="scale_in", detail=victim)

    def _pick_victim(
        self, view: ClusterLoadView, addrs: set[str]
    ) -> str | None:
        """Lowest-load live node, never self, managed-only when the
        provisioner owns any. ``req_rate`` is the affinity-aware tiebreak:
        between equally idle nodes, retire the one serving the least
        traffic — its population's communication edges are the cheapest
        to re-home through the drain's coordinated handoffs."""
        managed = set(self.provisioner.managed())
        candidates = [
            e
            for e in view.entries.values()
            if e.address in addrs
            and e.address != self.address
            and not e.stale
            and (not managed or e.address in managed)
        ]
        if not candidates:
            return None
        best = min(
            candidates,
            key=lambda e: (
                e.load.inflight + e.load.loop_lag_ms / 100.0,
                e.load.req_rate,
                e.load.registry_objects,
                e.address,
            ),
        )
        return best.address

    async def _request_drain(self, victim: str) -> None:
        """The stock graceful-exit flow, over the wire: ``rio.Admin`` on
        the victim enqueues ``AdminCommand.drain()`` — cordon + journal
        ``MEMBER_CORDON``, reminder-shard handoff, coordinated move-out,
        directory release, membership ``set_inactive``. A failed request
        is journaled but keeps the pending state: the drain deadline
        converts it into a forced retire (the victim may already be dead,
        which is exactly the mid-scale-in SIGKILL chaos case)."""
        from ..admin import ADMIN_TYPE, AdminAck, AdminRequest

        try:
            client = self._get_client()
            ack = await client.send(
                ADMIN_TYPE,
                victim,
                AdminRequest(kind="drain_server"),
                returns=AdminAck,
            )
            self._journal(
                "drain_requested", victim, ok=bool(ack.ok), detail=ack.detail
            )
        except Exception as e:  # noqa: BLE001 — victim may be unreachable/dead
            self._journal("drain_request_failed", victim, error=repr(e)[:160])
            log.warning(
                "%s: drain request to %s failed: %r", self.address, victim, e
            )

    async def _advance_pending(
        self, addrs: set[str], now: float
    ) -> ScaleTickAck:
        assert self._pending is not None
        victim = self._pending["victim"]
        if victim in addrs and now <= self._pending["deadline"]:
            return ScaleTickAck(detail=f"draining {victim}")
        forced = victim in addrs  # deadline blown while still a member
        try:
            await self.provisioner.retire(victim, force=forced)
        except Exception as e:  # noqa: BLE001
            self._journal("retire_failed", victim, error=repr(e)[:160])
        self.scale_ins += 1
        self._journal(
            "retired", victim, rule=self._pending["rule"], forced=forced,
            nodes=self.last_nodes,
        )
        self._record(
            "retired", victim, self._pending["rule"], self.last_nodes,
            "forced" if forced else "",
        )
        log.info(
            "%s: retired %s%s", self.address, victim,
            " (forced: drain timeout)" if forced else "",
        )
        self._pending = None
        self._arm_cooldown(self._in_jitter)
        return ScaleTickAck(acted=True, action="retired", detail=victim)

    def _arm_cooldown(self, jitter: DecorrelatedJitter) -> None:
        self._cooldown_until = time.monotonic() + jitter.next()
        self.over_streak = 0
        self.under_streak = 0

    def _get_client(self):
        if self._client is None:
            from ..client import Client

            self._client = Client(self._members)
        return self._client

    # -- the poke loop (one per enabled node, started by Server.run) ---------

    async def poke_loop(self) -> None:
        """Drive the singleton from every enabled node: the owner's poke
        dispatches locally and ticks; everyone else's raises a Redirect at
        their own service layer (internal sends never forward) and is
        dropped. When the owner dies, membership marks it inactive and the
        next surviving poke takes the dead-owner branch — clean_server +
        lazy self-assign — reseating the controller with no extra code."""
        while True:
            await asyncio.sleep(self.interval)
            try:
                await ServiceObject.send(
                    self.app_data,
                    AUTOSCALE_TYPE,
                    AUTOSCALE_ID,
                    ScaleTick(source=self.address),
                    returns=ScaleTickAck,
                )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — Redirect / transient dispatch noise
                pass

    async def close(self) -> None:
        if self._client is not None:
            with contextlib.suppress(Exception):
                self._client.close()
            self._client = None

    # -- observability --------------------------------------------------------

    @property
    def pending(self) -> str:
        """Victim address of the scale-in currently in flight ('' if none)."""
        return (self._pending or {}).get("victim", "")

    def gauges(self) -> dict[str, float]:
        """Scrape-ready controller state (``otel.server_gauges`` picks it
        up on whichever node hosts the runtime)."""
        return {
            "rio.autoscale.pressure": round(self.pressure, 4),
            "rio.autoscale.nodes": float(self.last_nodes),
            "rio.autoscale.overload": float(self.over_streak),
            "rio.autoscale.underload": float(self.under_streak),
            "rio.autoscale.cooldown_s": round(
                max(0.0, self._cooldown_until - time.monotonic()), 3
            ),
            "rio.autoscale.pending_drain": float(self._pending is not None),
            "rio.autoscale.scale_outs": float(self.scale_outs),
            "rio.autoscale.scale_ins": float(self.scale_ins),
            "rio.autoscale.ticks": float(self.ticks),
        }

    def status(self, limit: int = 32) -> dict[str, Any]:
        """CLI/snapshot view (everything msgpack/JSON-simple)."""
        return {
            "address": self.address,
            "pressure": round(self.pressure, 4),
            "nodes": self.last_nodes,
            "over_streak": self.over_streak,
            "under_streak": self.under_streak,
            "cooldown_s": round(
                max(0.0, self._cooldown_until - time.monotonic()), 3
            ),
            "pending": (self._pending or {}).get("victim", ""),
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "ticks": self.ticks,
            "alerts": sorted({a.rule for a in self.watch.active}),
            "policy": self.policy.as_dict(),
            "decisions": [list(r) for r in self.decisions[-max(0, limit):]],
        }


# -- the actor ----------------------------------------------------------------


@type_name(AUTOSCALE_TYPE)
class AutoscaleControl(ServiceObject):
    """The directory-seated singleton face of the controller.

    Deliberately stateless: all state lives in the hosting node's
    :class:`AutoscaleRuntime` (AppData), so a reseat after owner death
    loses nothing but the previous node's in-flight streaks — the new
    host re-derives them from live gauges within ``sustain`` ticks, which
    is exactly the conservatism wanted right after losing a node.
    """

    @handler
    async def tick(self, msg: ScaleTick, ctx: AppData) -> ScaleTickAck:
        runtime = ctx.try_get(AutoscaleRuntime)
        if runtime is None:
            # Seated on a node without an AutoscaleConfig (operator error
            # or a rebalance surprise): report, never crash the poke.
            return ScaleTickAck(detail="no autoscale runtime on this node")
        return await runtime.tick()

    @handler
    async def status(self, msg: ScaleStatus, ctx: AppData) -> ScaleSnapshot:
        runtime = ctx.try_get(AutoscaleRuntime)
        if runtime is None:
            return ScaleSnapshot(address="", pressure=0.0)
        s = runtime.status(limit=msg.limit)
        return ScaleSnapshot(
            address=s["address"],
            pressure=s["pressure"],
            nodes=s["nodes"],
            over_streak=s["over_streak"],
            under_streak=s["under_streak"],
            cooldown_s=s["cooldown_s"],
            pending=s["pending"],
            scale_outs=s["scale_outs"],
            scale_ins=s["scale_ins"],
            ticks=s["ticks"],
            alerts=s["alerts"],
            policy=s["policy"],
            decisions=s["decisions"],
        )
