"""Sharded multi-process data plane: one node, N worker processes.

:class:`ShardedServer` is rung 2 of the data-plane ladder (ROADMAP): a
parent supervisor spawns N worker processes that all accept on ONE
front-door address via ``SO_REUSEPORT`` (fallback where the option is
unavailable: a parent-bound listener whose fd every child inherits), while
each worker ALSO listens on a unique *identity* port. The identity address
is what enters membership and the placement directory, so the existing
directory machinery — client redirect-follow, ``ObjectPlacement`` rows,
migration, replication — routes cross-shard traffic unchanged: a request
accepted by the wrong worker is answered with the standard ``Redirect`` to
the owner's identity address and the client's placement cache converges.
No new wire values; golden-wire bytes are identical to a plain server's.

Ownership is a deterministic slice of the object space::

    shard = crc32(f"{type_name}/{id}") % n_workers      # commands.shard_of

enforced lazily by the service layer's :class:`~rio_tpu.commands.
ShardRouter` seam: an unplaced object is seated only by its preferred
worker while that worker is alive. A dead worker's slice degrades to lazy
self-assign by whichever worker is asked (after the supervisor marks the
death in membership), so availability never hinges on the hash map — and a
``MigrationManager`` move OVERRIDES the map, because seated directory rows
are honored before the router is consulted.

Workers are separate OS processes — the multi-core unlock for a Python
host (the reference's tokio worker threads, ``rio-rs/src/service.rs:
370-459``, have no GIL to design around). They are spawned with a clean
environment and joined only through shared membership/placement storage:
the same topology as a multi-host cluster, collapsed onto one box.

CLI::

    python -m rio_tpu.sharded --address 0.0.0.0:9000 --workers 4 \
        --registry myapp.actors:build_registry --data-dir /var/lib/rio
    python -m rio_tpu.sharded --smoke          # 2-worker loopback self-test
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

from .commands import ShardMap, ShardRouter, shard_of  # re-exported: the shard map

__all__ = ["ShardedServer", "ShardMap", "ShardRouter", "shard_of",
           "sqlite_members", "sqlite_placement"]

_HAS_REUSEPORT = hasattr(socket, "SO_REUSEPORT")


# ----------------------------------------------------------------------
# Storage factories (importable by worker processes by dotted name)
# ----------------------------------------------------------------------

def sqlite_members(data_dir: str):
    """Default shared membership: one sqlite file under ``data_dir``."""
    from .cluster.storage.sqlite import SqliteMembershipStorage

    return SqliteMembershipStorage(os.path.join(data_dir, "members.db"))


def sqlite_placement(data_dir: str):
    """Default shared directory: one sqlite file under ``data_dir``."""
    from .object_placement.sqlite import SqliteObjectPlacement

    return SqliteObjectPlacement(os.path.join(data_dir, "placement.db"))


def _load_factory(spec: str):
    """Resolve a ``module:callable`` factory spec."""
    import importlib

    mod, sep, attr = spec.partition(":")
    if not sep or not attr:
        raise ValueError(f"factory spec must be 'module:callable', got {spec!r}")
    obj = importlib.import_module(mod)
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _split_address(address: str) -> tuple[str, int]:
    host, sep, port = address.rpartition(":")
    if not sep:
        raise ValueError(f"address must be 'host:port', got {address!r}")
    return host or "0.0.0.0", int(port)


def _reserve_port(host: str) -> tuple[socket.socket | None, int]:
    """Reserve an ephemeral port a child can later bind.

    With ``SO_REUSEPORT`` the reservation socket stays OPEN (bound, never
    listening — the kernel only distributes connections among *listening*
    sockets, so an unlistened holder just pins the port) and the child
    re-binds the same port with the flag set. Without it, bind-then-close:
    racy against the rest of the host, but the only portable option.
    """
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    if _HAS_REUSEPORT:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, 0))
        return s, s.getsockname()[1]
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return None, port


class ShardedServer:
    """Parent supervisor for N worker processes sharing one front door.

    Parameters are JSON-able on purpose — they cross a process boundary:

    * ``registry`` / ``members`` / ``placement`` are ``module:callable``
      factory specs, resolved INSIDE each worker (a live Registry can't be
      pickled across an exec boundary; storage must be re-opened per
      process anyway). ``members``/``placement`` factories take
      ``data_dir``; the registry factory takes no arguments.
    * ``server_kwargs`` is a dict of JSON-able :class:`~rio_tpu.server.
      Server` kwargs applied to every worker (e.g. ``{"metrics": False}``).

    ``router=False`` / ``front_door=False`` disable the shard map / shared
    listener — ``workers=1`` with both off is exactly one plain server
    child, which is what ``bench.py --sharded`` pairs against to price the
    sharding machinery itself.
    """

    def __init__(
        self,
        *,
        address: str = "127.0.0.1:0",
        workers: int | None = None,
        registry: str,
        data_dir: str,
        members: str = "rio_tpu.sharded:sqlite_members",
        placement: str = "rio_tpu.sharded:sqlite_placement",
        reuseport: bool | None = None,
        router: bool = True,
        front_door: bool = True,
        server_kwargs: dict | None = None,
        env: dict | None = None,
        python: str | None = None,
    ) -> None:
        self.address = address
        self.workers = workers if workers is not None else (os.cpu_count() or 1)
        if self.workers < 1:
            raise ValueError("workers must be >= 1")
        self.registry_spec = registry
        self.data_dir = data_dir
        self.members_spec = members
        self.placement_spec = placement
        self.reuseport = _HAS_REUSEPORT if reuseport is None else reuseport
        self.router = router
        self.front_door = front_door
        self.server_kwargs = dict(server_kwargs or {})
        self.env_override = env
        self.python = python or sys.executable

        self.procs: list[subprocess.Popen] = []
        self.worker_addresses: list[str] = []
        self.map_epoch: int = 0
        self.front_address: str | None = None
        self._front_sock: socket.socket | None = None  # fd-fallback listener
        self._reservations: list[socket.socket] = []
        self._logs: list = []
        self._stopping = False
        self._monitors: list[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ShardedServer":
        """Reserve ports, spawn every worker, start the death monitor.

        Returns immediately; await :meth:`wait_ready` (or call
        :meth:`start_and_wait` from sync code) before sending traffic.
        """
        if self.procs:
            raise RuntimeError("already started")
        os.makedirs(self.data_dir, exist_ok=True)
        host, front_port = _split_address(self.address)
        from .server import _routable_host

        adv_host = host if host not in ("", "0.0.0.0", "::") else _routable_host()

        front_spec = None
        pass_fds: tuple = ()
        if self.front_door:
            if self.reuseport:
                res, front_port = self._reserve_front(host, front_port)
                self._reservations.append(res)
                front_spec = {"mode": "reuseport", "host": host,
                              "port": front_port}
            else:
                s = socket.socket()
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind((host, front_port))
                s.listen(512)
                s.set_inheritable(True)
                front_port = s.getsockname()[1]
                self._front_sock = s
                front_spec = {"mode": "fd", "fd": s.fileno()}
                pass_fds = (s.fileno(),)
        self.front_address = f"{adv_host}:{front_port}" if front_spec else None

        ports: list[int] = []
        for _ in range(self.workers):
            res, p = _reserve_port(host)
            if res is not None:
                self._reservations.append(res)
            ports.append(p)
        self.worker_addresses = [f"{adv_host}:{p}" for p in ports]

        # Map epoch: a persisted per-data_dir counter bumped every start, so
        # a supervisor restart (new worker ports, reseated slices) publishes
        # a map shard-aware clients can tell apart from the one they adopted
        # — the signal that drops their stale direct-dial state.
        use_router = self.router and self.workers > 1
        shard_map = ""
        if use_router:
            self.map_epoch = self._next_epoch()
            shard_map = ShardMap(
                epoch=self.map_epoch, slots=tuple(self.worker_addresses)
            ).encode()

        env = self._child_env()
        for i in range(self.workers):
            spec = {
                "slot": i,
                "slots": self.worker_addresses,
                "bind_host": host,
                "identity_port": ports[i],
                "advertise": self.worker_addresses[i],
                "reuse_port": self.reuseport,
                "front": front_spec,
                "registry": self.registry_spec,
                "members": self.members_spec,
                "placement": self.placement_spec,
                "data_dir": self.data_dir,
                "router": use_router,
                "shard_map": shard_map,
                "server_kwargs": self.server_kwargs,
            }
            log_f = open(os.path.join(self.data_dir, f"worker{i}.log"), "wb")
            self._logs.append(log_f)
            proc = subprocess.Popen(
                [self.python, "-m", "rio_tpu.sharded", "--worker"],
                stdin=subprocess.PIPE,
                stdout=log_f,
                stderr=subprocess.STDOUT,
                env=env,
                pass_fds=pass_fds,
                close_fds=True,
            )
            assert proc.stdin is not None
            proc.stdin.write(json.dumps(spec).encode())
            proc.stdin.close()
            self.procs.append(proc)
        for i, proc in enumerate(self.procs):
            t = threading.Thread(
                target=self._monitor, args=(i, proc), daemon=True
            )
            t.start()
            self._monitors.append(t)
        return self

    def _next_epoch(self) -> int:
        """Increment the persisted map epoch for this data_dir."""
        path = os.path.join(self.data_dir, "shard_epoch")
        try:
            with open(path) as f:
                epoch = int(f.read().strip() or 0)
        except (OSError, ValueError):
            epoch = 0
        epoch += 1
        with open(path, "w") as f:
            f.write(str(epoch))
        return epoch

    def _reserve_front(
        self, host: str, port: int
    ) -> tuple[socket.socket, int]:
        """Pin the front-door port without receiving traffic (see
        :func:`_reserve_port`); a requested port of 0 resolves here so every
        worker is told the same concrete port."""
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((host, port))
        return s, s.getsockname()[1]

    def _child_env(self) -> dict:
        if self.env_override is not None:
            return dict(self.env_override)
        # Clean environment, the multihost-test discipline: an ambient
        # sitecustomize (e.g. an accelerator plugin registration) must not
        # leak into data-plane workers; they pin CPU unless told otherwise.
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        return {
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/tmp"),
            "PYTHONPATH": repo_root,
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        }

    async def wait_ready(self, timeout: float = 60.0) -> None:
        """Poll shared membership until every worker identity is active."""
        members = _load_factory(self.members_spec)(self.data_dir)
        try:
            deadline = time.monotonic() + timeout
            want = set(self.worker_addresses)
            while time.monotonic() < deadline:
                dead = [
                    i for i, p in enumerate(self.procs) if p.poll() is not None
                ]
                if dead and not self._stopping:
                    raise RuntimeError(
                        f"worker(s) {dead} exited during bring-up; see "
                        + ", ".join(
                            os.path.join(self.data_dir, f"worker{i}.log")
                            for i in dead
                        )
                    )
                try:
                    active = {m.address for m in await members.active_members()}
                except Exception:
                    active = set()
                if want <= active:
                    return
                await asyncio.sleep(0.05)
            raise TimeoutError(
                f"workers never became active members (want {sorted(want)})"
            )
        finally:
            with contextlib.suppress(Exception):
                members.close()

    def start_and_wait(self, timeout: float = 60.0) -> "ShardedServer":
        self.start()
        asyncio.run(self.wait_ready(timeout))
        return self

    # -- death handling ------------------------------------------------

    def _monitor(self, i: int, proc: subprocess.Popen) -> None:
        """Mark a dead worker inactive in membership.

        This is the supervisor half of worker-death reseat: once the
        identity is inactive, any worker touching one of the dead slice's
        objects takes the dead-owner branch (``clean_server`` + lazy
        self-assign) and traffic converges onto the survivors. A graceful
        worker marks itself on exit; doing it again here is idempotent.
        """
        proc.wait()
        if self._stopping:
            return
        addr = self.worker_addresses[i]
        with contextlib.suppress(Exception):
            asyncio.run(self._mark_inactive(addr))

    async def _mark_inactive(self, address: str) -> None:
        members = _load_factory(self.members_spec)(self.data_dir)
        try:
            host, _, port = address.rpartition(":")
            await members.set_inactive(host, int(port))
        finally:
            with contextlib.suppress(Exception):
                members.close()

    def terminate_worker(self, i: int, sig: int = signal.SIGKILL) -> None:
        """Kill one worker (chaos / tests). The monitor thread records the
        death in membership exactly as it would for a real crash."""
        with contextlib.suppress(ProcessLookupError):
            self.procs[i].send_signal(sig)

    # -- shutdown ------------------------------------------------------

    def stop(self, graceful: bool = True, timeout: float = 20.0) -> list[int]:
        """Stop every worker; returns their exit codes.

        ``graceful`` sends SIGTERM first — each worker's signal handler
        enqueues ``AdminCommand.drain()``, so seated objects run their
        shutdown lifecycle and local directory rows are released before
        exit. Stragglers past ``timeout`` are SIGKILLed.
        """
        self._stopping = True
        sig = signal.SIGTERM if graceful else signal.SIGKILL
        for p in self.procs:
            if p.poll() is None:
                with contextlib.suppress(ProcessLookupError):
                    p.send_signal(sig)
        deadline = time.monotonic() + timeout
        codes = []
        for p in self.procs:
            left = max(0.1, deadline - time.monotonic())
            try:
                codes.append(p.wait(timeout=left))
            except subprocess.TimeoutExpired:
                p.kill()
                codes.append(p.wait())
        for s in self._reservations:
            with contextlib.suppress(OSError):
                s.close()
        self._reservations.clear()
        if self._front_sock is not None:
            with contextlib.suppress(OSError):
                self._front_sock.close()
            self._front_sock = None
        for f in self._logs:
            with contextlib.suppress(OSError):
                f.close()
        self._logs.clear()
        return codes

    def worker_log(self, i: int) -> str:
        path = os.path.join(self.data_dir, f"worker{i}.log")
        try:
            with open(path, "rb") as f:
                return f.read().decode(errors="replace")
        except OSError:
            return ""


# ----------------------------------------------------------------------
# Worker process entry
# ----------------------------------------------------------------------

async def _run_worker(spec: dict) -> None:
    from . import Server
    from .cluster.membership_protocol import LocalClusterProvider
    from .commands import AdminCommand

    members = _load_factory(spec["members"])(spec["data_dir"])
    placement = _load_factory(spec["placement"])(spec["data_dir"])
    registry = _load_factory(spec["registry"])()

    extra_socks = []
    front = spec.get("front")
    if front is not None:
        if front["mode"] == "reuseport":
            s = socket.socket()
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
            s.bind((front["host"], front["port"]))
        else:
            # Inherited parent-bound listener: every worker epolls the same
            # fd (accept herd — the portability fallback, not the fast path).
            s = socket.socket(fileno=front["fd"])
        extra_socks.append(s)

    server = Server(
        address=f"{spec['bind_host']}:{spec['identity_port']}",
        advertise_address=spec["advertise"],
        registry=registry,
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=placement,
        reuse_port=bool(spec.get("reuse_port")),
        extra_listen_socks=extra_socks,
        **spec.get("server_kwargs", {}),
    )
    if spec.get("router"):
        server.app_data.set(
            ShardRouter(
                self_address=spec["advertise"], slots=tuple(spec["slots"])
            )
        )
    if spec.get("shard_map"):
        # Publish the supervisor's map (epoch + slots) on every heartbeat
        # row, so shard-aware clients can compute crc32 % N locally and
        # dial this worker's identity address with zero redirects.
        server.cluster_provider.set_shard_map(spec["shard_map"])
    await server.prepare()
    await server.bind()

    # Drain-then-exit on supervisor (or operator) signals: the admin queue
    # runs the full graceful path — cordon, lifecycle shutdown for seated
    # objects, release of local directory rows, membership set_inactive.
    loop = asyncio.get_running_loop()
    admin = server.admin_sender()
    for signum in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(
            signum, lambda: admin.send(AdminCommand.drain())
        )
    print(f"READY {server.local_address}", flush=True)
    await server.run()


def _worker_main() -> int:
    spec = json.loads(sys.stdin.read())
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    asyncio.run(_run_worker(spec))
    return 0


# ----------------------------------------------------------------------
# Load-generator child (bench.py --sharded drives N of these)
# ----------------------------------------------------------------------

async def _run_loadgen(spec: dict) -> dict:
    """Warm the actor population, wait for GO on stdin, measure a window.

    A separate process per load generator keeps the client's CPU off the
    workers' cores on multi-core hosts; the parent starts every generator,
    waits for all WARM lines, then broadcasts GO so the measured windows
    coincide.
    """
    from .client import Client
    from .utils.routing_live import Echo, EchoActor

    members = _load_factory(spec["members"])(spec["data_dir"])
    client = Client(members, shard_aware=bool(spec.get("shard_aware")))
    try:
        n_objects = spec.get("n_objects", 256)
        n_workers = spec.get("n_workers", 32)
        per = spec.get("requests_per_worker", 200)
        prefix = spec.get("prefix", "lg")
        ids = [f"{prefix}-{i}" for i in range(n_objects)]
        for oid in ids:
            await client.send(EchoActor, oid, Echo(value=1), returns=Echo)
        print("WARM", flush=True)
        await asyncio.get_running_loop().run_in_executor(
            None, sys.stdin.readline
        )

        async def worker(w: int) -> None:
            for r in range(per):
                oid = ids[(w * per + r) % n_objects]
                await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

        t0 = time.perf_counter()
        await asyncio.gather(*[worker(w) for w in range(n_workers)])
        dt = time.perf_counter() - t0
        total = n_workers * per
        return {
            "rate": total / dt,
            "total": total,
            "secs": dt,
            "redirects": client.stats.redirects,
            "shard_routes": client.stats.shard_routes,
        }
    finally:
        client.close()
        with contextlib.suppress(Exception):
            members.close()


def _loadgen_main() -> int:
    spec = json.loads(sys.stdin.readline())
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    out = asyncio.run(_run_loadgen(spec))
    print("RESULT " + json.dumps(out), flush=True)
    return 0


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------

def _smoke_main(shard_aware: bool = False) -> int:
    """2-worker loopback self-test (the CI tier-1 sharded smoke).

    With ``shard_aware`` the client adopts the published shard map and the
    smoke additionally asserts the audit counters: every unplaced send was
    direct-dialed (``shard_routes > 0``) and none paid a redirect hop
    (``redirects == 0``).
    """
    import tempfile

    async def drive(node: ShardedServer) -> dict:
        from .client import Client
        from .registry import ObjectId, type_id
        from .utils.routing_live import Echo, EchoActor

        await node.wait_ready(45.0)
        members = _load_factory(node.members_spec)(node.data_dir)
        placement = _load_factory(node.placement_spec)(node.data_dir)
        client = Client(members, shard_aware=shard_aware)
        try:
            tname = type_id(EchoActor)
            n = 16
            for i in range(n):
                out = await client.send(
                    EchoActor, f"smoke-{i}", Echo(value=i), returns=Echo
                )
                assert out.value == i
            owners = {}
            for i in range(n):
                row = await placement.lookup(ObjectId(tname, f"smoke-{i}"))
                assert row in node.worker_addresses, row
                expect = node.worker_addresses[
                    shard_of(tname, f"smoke-{i}", len(node.worker_addresses))
                ]
                assert row == expect, (row, expect)
                owners[row] = owners.get(row, 0) + 1
            result = {"ok": True, "n": n, "spread": owners}
            if shard_aware:
                assert client.stats.redirects == 0, client.stats
                assert client.stats.shard_routes > 0, client.stats
                result["redirects"] = client.stats.redirects
                result["shard_routes"] = client.stats.shard_routes
            return result
        finally:
            client.close()
            with contextlib.suppress(Exception):
                members.close()
            with contextlib.suppress(Exception):
                placement.close()

    with tempfile.TemporaryDirectory() as tmp:
        node = ShardedServer(
            address="127.0.0.1:0",
            workers=2,
            registry="rio_tpu.utils.routing_live:build_echo_registry",
            data_dir=tmp,
        )
        node.start()
        try:
            result = asyncio.run(drive(node))
        except BaseException:
            for i in range(node.workers):
                sys.stderr.write(
                    f"--- worker{i}.log ---\n{node.worker_log(i)}\n"
                )
            raise
        finally:
            node.stop()
        print("SMOKE OK " + json.dumps(result), flush=True)
    return 0


def _supervise_main(argv: list[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="python -m rio_tpu.sharded")
    ap.add_argument("--address", default="127.0.0.1:0")
    ap.add_argument("--workers", type=int, default=None)
    ap.add_argument("--registry", required=True,
                    help="module:callable returning a Registry")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--members", default="rio_tpu.sharded:sqlite_members")
    ap.add_argument("--placement", default="rio_tpu.sharded:sqlite_placement")
    ap.add_argument("--no-reuseport", action="store_true")
    args = ap.parse_args(argv)

    node = ShardedServer(
        address=args.address,
        workers=args.workers,
        registry=args.registry,
        data_dir=args.data_dir,
        members=args.members,
        placement=args.placement,
        reuseport=False if args.no_reuseport else None,
    )
    node.start_and_wait()
    print(
        f"front={node.front_address} workers={node.worker_addresses}",
        flush=True,
    )
    done = threading.Event()
    for signum in (signal.SIGTERM, signal.SIGINT):
        signal.signal(signum, lambda *_: done.set())
    done.wait()
    node.stop(graceful=True)
    return 0


def _main() -> int:
    argv = sys.argv[1:]
    if argv[:1] == ["--worker"]:
        return _worker_main()
    if argv[:1] == ["--loadgen"]:
        return _loadgen_main()
    if argv[:1] == ["--smoke"]:
        return _smoke_main(shard_aware="--shard-aware" in argv[1:])
    return _supervise_main(argv)


if __name__ == "__main__":
    sys.exit(_main())
