"""Control-plane flight recorder: a bounded, causal cluster event journal.

PR 7 made the *data plane* observable (wire traces + RED histograms); this
module records the *control plane* — every placement / membership /
migration / replication / read-scale / reminder transition — into a
zero-dependency ring buffer so "why is actor X on node 3 and what happened
to it during the drain?" has an answer.

Design constraints (mirrors ``metrics.py``):

- **Never blocks the hot path.** ``record`` is a plain list write on the
  event loop thread: bump the per-node seq, stamp wall + mono clocks,
  overwrite the oldest slot when full and count it in ``dropped``. No
  locks, no allocation beyond the event itself, no I/O.
- **Causally mergeable.** Every event carries a per-node monotonic ``seq``
  (gap-free within a node) and the node id; ``merge_events`` orders rows
  from many nodes into one history by ``(wall_ts, node, seq)`` — per-node
  order is always preserved, cross-node order leans on the wall clock the
  same way the membership protocol does.
- **Linked to request traces.** ``record`` snapshots
  ``tracing.current_trace_id()``, so a migration driven by an admin
  request, or a promotion triggered inside a traced call, shares the
  trace id of the request spans PR 7 exports — journal rows and RED
  exemplars join on it.
- **Wire-portable.** Events round-trip through positional rows (same
  tolerant-decode style as ``metrics.hist_to_row``): decoders accept
  shorter legacy rows and ignore extra trailing fields, so the journal
  wire format can grow by appending.

The journal is populated by the subsystems (service, placement daemon,
migration, replication, readscale, reminders) and drained over the wire by
``rio.Admin``'s ``DumpEvents`` message — see ``rio_tpu/admin.py`` for the
cluster-wide ``explain`` merge and the operator CLI.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Iterable, Sequence

from .tracing import current_trace_id

# -- event kinds -------------------------------------------------------------
# Plain strings on the wire (not an enum): old readers can render kinds they
# don't know, and new kinds never need a wire-version bump.

MEMBER_UP = "member_up"  # membership liveness flip → active
MEMBER_DOWN = "member_down"  # membership liveness flip → inactive
MEMBER_CORDON = "member_cordon"  # node cordoned (drain start)

PLACE_ASSIGN = "place_assign"  # directory row written (activation seat)
PLACE_RELEASE = "place_release"  # directory row removed (panic/corrupt/teardown)
ADMIT_SHED = "admit_shed"  # new activation refused with SERVER_BUSY

MIGRATE_PIN = "migrate_pin"  # handoff phase 1: requests parked
MIGRATE_SNAPSHOT = "migrate_snapshot"  # phase 2: deactivated + state captured
MIGRATE_INSTALL = "migrate_install"  # phase 3: state seated on target
MIGRATE_FLIP = "migrate_flip"  # phase 4: directory flipped, fence armed
MIGRATE_ABORT = "migrate_abort"  # handoff failed, object restored locally
MIGRATE_BURST = "migrate_burst"  # batched (source, target) burst dispatched

REPLICA_PROMOTE = "replica_promote"  # standby promoted (epoch bumped)
REPLICA_DEPOSE = "replica_depose"  # deposed primary surrendered the key
REPLICA_RESHIP = "replica_reship"  # anti-entropy full state re-ship
REPLICA_SEAT = "replica_seat"  # standby seats (re)assigned
REPLICA_K = "replica_k"  # dynamic replica_k raised/lowered

READ_SHED = "read_shed"  # hot primary shed a read with standby seat hints
READ_PROXY = "read_proxy"  # stale standby proxied a read to the primary

REMINDER_SEAT = "reminder_seat"  # reminder shard lease claimed
REMINDER_RELEASE = "reminder_release"  # reminder shard lease released
REMINDER_HANDOFF = "reminder_handoff"  # drain handed shards to a peer

SOLVE = "solve"  # placement solve (full or delta) applied/discarded

HEALTH = "health"  # HealthWatch trend rule fired (degradation alarm)

STORAGE = "storage"  # rendezvous storage degraded / recovered (outage story)
FAULT = "fault"  # fault-injection schedule transition (scripted outage edges)

STREAM = "stream"  # durable-stream transition (publish/deliver/commit edges)
SAGA = "saga"  # saga step/compensation transition (workflow story)

SCALE = "scale"  # autoscale decision/actuation edge (resize story)

EVENT_KINDS: tuple[str, ...] = (
    MEMBER_UP,
    MEMBER_DOWN,
    MEMBER_CORDON,
    PLACE_ASSIGN,
    PLACE_RELEASE,
    ADMIT_SHED,
    MIGRATE_PIN,
    MIGRATE_SNAPSHOT,
    MIGRATE_INSTALL,
    MIGRATE_FLIP,
    MIGRATE_ABORT,
    MIGRATE_BURST,
    REPLICA_PROMOTE,
    REPLICA_DEPOSE,
    REPLICA_RESHIP,
    REPLICA_SEAT,
    REPLICA_K,
    READ_SHED,
    READ_PROXY,
    REMINDER_SEAT,
    REMINDER_RELEASE,
    REMINDER_HANDOFF,
    SOLVE,
    HEALTH,
    STORAGE,
    FAULT,
    STREAM,
    SAGA,
    SCALE,
)


@dataclass
class JournalEvent:
    """One control-plane transition; positional on the wire (``to_row``)."""

    seq: int  # per-node monotonic, gap-free
    wall_ts: float  # time.time() at record
    mono_ts: float  # time.monotonic() at record (same-node deltas)
    node: str  # recording node's address
    epoch: int  # subject epoch where meaningful (0 otherwise)
    kind: str  # one of EVENT_KINDS (or a future addition)
    key: str  # subject, usually "type/id" ("" for node-wide events)
    attrs: dict[str, Any] = field(default_factory=dict)
    trace_id: str | None = None  # active request trace at record time

    def to_row(self) -> list[Any]:
        return [
            self.seq,
            self.wall_ts,
            self.mono_ts,
            self.node,
            self.epoch,
            self.kind,
            self.key,
            self.attrs,
            self.trace_id,
        ]

    @classmethod
    def from_row(cls, row: Sequence[Any]) -> "JournalEvent":
        # Tolerant decode: short legacy rows get defaults, extra trailing
        # fields from a newer sender are ignored.
        r = list(row[:9]) + [None] * (9 - min(len(row), 9))
        attrs = r[7] if isinstance(r[7], dict) else {}
        return cls(
            seq=int(r[0] or 0),
            wall_ts=float(r[1] or 0.0),
            mono_ts=float(r[2] or 0.0),
            node=str(r[3] or ""),
            epoch=int(r[4] or 0),
            kind=str(r[5] or ""),
            key=str(r[6] or ""),
            attrs=attrs,
            trace_id=r[8] if isinstance(r[8], str) else None,
        )


def subject_key(type_name: str, object_id: str) -> str:
    """The canonical journal subject for an actor: ``type/id``."""
    return f"{type_name}/{object_id}"


class Journal:
    """Bounded ring of :class:`JournalEvent`, appended from the event loop.

    Single-writer by construction (all control-plane transitions happen on
    the server's loop), so there is no lock: ``record`` is a couple of
    attribute writes and one list store. When the ring is full the oldest
    event is overwritten and ``dropped`` incremented — recording NEVER
    blocks or fails.
    """

    def __init__(self, capacity: int = 4096, node: str = "") -> None:
        self.capacity = max(1, int(capacity))
        self.node = node
        self._ring: list[JournalEvent | None] = [None] * self.capacity
        self._head = 0  # next slot to write
        self._seq = 0  # last seq handed out (== total recorded)
        self.dropped = 0  # events overwritten before anyone read them

    # -- write side (hot-ish path: control transitions only) -----------------

    def record(
        self,
        kind: str,
        key: str = "",
        *,
        epoch: int = 0,
        **attrs: Any,
    ) -> JournalEvent:
        """Append one event; always succeeds, never blocks."""
        self._seq += 1
        ev = JournalEvent(
            seq=self._seq,
            wall_ts=time.time(),
            mono_ts=time.monotonic(),
            node=self.node,
            epoch=epoch,
            kind=kind,
            key=key,
            attrs=attrs,
            trace_id=current_trace_id(),
        )
        i = self._head
        if self._ring[i] is not None:
            self.dropped += 1
        self._ring[i] = ev
        self._head = (i + 1) % self.capacity
        return ev

    # -- read side -----------------------------------------------------------

    @property
    def recorded(self) -> int:
        """Total events ever recorded (== the last seq handed out)."""
        return self._seq

    def __len__(self) -> int:
        return min(self._seq, self.capacity)

    def events(
        self,
        *,
        kinds: Iterable[str] | None = None,
        key: str | None = None,
        since_seq: int = 0,
        limit: int | None = None,
    ) -> list[JournalEvent]:
        """Snapshot matching events, oldest → newest.

        ``kinds``/``key`` filter exactly; ``since_seq`` returns events with
        ``seq > since_seq`` (resumable tailing); ``limit`` keeps the NEWEST
        ``limit`` matches (a tail, not a head).
        """
        want = frozenset(kinds) if kinds else None
        out: list[JournalEvent] = []
        n = self.capacity
        for off in range(n):
            ev = self._ring[(self._head + off) % n]
            if ev is None or ev.seq <= since_seq:
                continue
            if want is not None and ev.kind not in want:
                continue
            if key is not None and ev.key != key:
                continue
            out.append(ev)
        if limit is not None and limit >= 0 and len(out) > limit:
            out = out[len(out) - limit :]
        return out

    def gauges(self) -> dict[str, float]:
        """Scrape-ready counters (picked up by ``otel.server_gauges``)."""
        return {
            "rio.journal.events": float(self._seq),
            "rio.journal.dropped": float(self.dropped),
            "rio.journal.ring_occupancy": float(len(self)),
            "rio.journal.ring_capacity": float(self.capacity),
        }


def merge_events(
    streams: Iterable[Iterable[JournalEvent]],
) -> list[JournalEvent]:
    """Merge per-node event streams into one causally ordered history.

    Within a node, ``seq`` is authoritative (monotonic, gap-free); across
    nodes the wall clock orders the merge — adequate for same-host tests
    and for operators reading a cluster with sane NTP. The sort key
    ``(wall_ts, node, seq)`` keeps per-node order stable under wall-clock
    ties (same node ⇒ seq decides; distinct nodes tie-break by name, which
    is arbitrary but deterministic).
    """
    merged = [ev for stream in streams for ev in stream]
    merged.sort(key=lambda e: (e.wall_ts, e.node, e.seq))
    return merged


def format_event(ev: JournalEvent) -> str:
    """One human line per event (CLI ``tail`` / ``explain`` rendering)."""
    ts = time.strftime("%H:%M:%S", time.localtime(ev.wall_ts))
    frac = f"{ev.wall_ts % 1:.3f}"[1:]
    attrs = " ".join(f"{k}={v!r}" for k, v in sorted(ev.attrs.items()))
    trace = f" trace={ev.trace_id}" if ev.trace_id else ""
    epoch = f" epoch={ev.epoch}" if ev.epoch else ""
    key = f" {ev.key}" if ev.key else ""
    return (
        f"{ts}{frac} {ev.node} #{ev.seq} {ev.kind}{key}{epoch}"
        f"{' ' + attrs if attrs else ''}{trace}"
    )
