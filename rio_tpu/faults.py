"""Deterministic fault injection for the control plane and the transport.

Every subsystem (gossip, placement daemon, reminders, migration,
replication, read scale-out) leans on the shared rendezvous — the
``MembershipStorage``/``ObjectPlacement``/``ReminderStorage`` traits — and
on the framed TCP transport. This module injects failures at exactly those
two seams, so chaos coverage is *scripted and replayable* instead of
ad-hoc per-test process kills:

* **Storage faults** — :class:`FaultyMembershipStorage`,
  :class:`FaultyObjectPlacement`, :class:`FaultyReminderStorage` and
  :class:`FaultyStreamStorage` wrap
  any concrete backend and consult one :class:`FaultSchedule` before every
  delegated call: seeded error rates, added latency, park-until-heal
  hangs, and scripted total outages (``fail_all()`` / ``heal()`` or
  elapsed-time :class:`OutageWindow` s).
* **Transport faults** — :class:`TransportFaults` drops, delays or resets
  connects and frames per ``(src, dst)`` pair. Rules are directional, so
  asymmetric partitions (A cannot reach B while B reaches A and both reach
  storage) are one ``partition(src, dst)`` call. The client and the gossip
  provider accept a ``transport_faults`` handle and route their dials and
  pings through it.

Determinism: one ``random.Random(seed)`` per schedule; the same seed and
the same call sequence replay the same fault pattern. Nothing here touches
wall clocks for decisions (outage windows run on a monotonic clock started
at first use, or on an injected ``clock`` for tests).

Observability: injections and outage edges journal ``FAULT`` events;
degraded-mode transitions in the hardened subsystems journal ``STORAGE``
events; :class:`StorageHealth` aggregates ``rio.storage.*`` error/latency
gauges picked up by ``otel.server_gauges`` and watched by the
``storage_errors`` HealthWatch default rule.

The wrappers are **pass-through at rest**: with no rules, no windows and
no scripted outage, ``perturb`` is a couple of attribute reads — measured
at parity by ``bench.py --faults`` (paired A/B, see
``rio_tpu/utils/faults_live.py``).

Demo / CI smoke::

    python -m rio_tpu.faults --demo
"""

from __future__ import annotations

import asyncio
import dataclasses
import fnmatch
import inspect
import random
import time
import weakref
from typing import Any, Callable, Iterable

from .errors import RioError
from .journal import FAULT, Journal

__all__ = [
    "InjectedFault",
    "FaultRule",
    "OutageWindow",
    "FaultSchedule",
    "StorageHealth",
    "StorageResilienceConfig",
    "FaultyMembershipStorage",
    "FaultyObjectPlacement",
    "FaultyReminderStorage",
    "LinkRule",
    "TransportFaults",
]


class InjectedFault(RioError):
    """A fault-injection layer refused the operation.

    Subclasses :class:`~rio_tpu.errors.RioError` (not the storage errors)
    on purpose: the hardened code paths must survive *any* exception from
    a storage call, so injected faults deliberately do not match the typed
    backend errors — a handler that only catches ``MembershipError`` is a
    bug this layer exists to expose.
    """

    def __init__(self, op: str, detail: str = "injected fault"):
        super().__init__(f"{detail} [{op}]")
        self.op = op


@dataclasses.dataclass(frozen=True)
class FaultRule:
    """Seeded per-operation perturbation.

    ``op`` is an ``fnmatch`` pattern over dotted operation names
    (``membership.members``, ``placement.lookup``, ``reminders.due`` …) —
    ``"membership.*"`` matches a whole trait, ``"*"`` everything.
    """

    op: str = "*"
    error_rate: float = 0.0  # P(raise InjectedFault) per call
    latency: float = 0.0  # seconds added before the call
    jitter: float = 0.0  # extra uniform(0, jitter) seconds
    hang: bool = False  # park the call until the schedule heals


@dataclasses.dataclass(frozen=True)
class OutageWindow:
    """Total outage for ``op`` between ``start`` and ``end`` seconds on the
    schedule's clock (first ``perturb``/``start()`` is t=0)."""

    start: float
    end: float
    op: str = "*"
    hang: bool = False  # park instead of raising while inside the window


class FaultSchedule:
    """One seeded, scripted source of fault decisions.

    Shared by any number of storage wrappers; ``fail_all()``/``heal()``
    script total outages from tests and soaks, :class:`FaultRule` s add
    seeded noise, :class:`OutageWindow` s script time-based outages.
    ``enabled=False`` (or ``FaultSchedule()`` with nothing configured)
    makes every gate a no-op — the disabled-overhead contract.
    """

    def __init__(
        self,
        seed: int = 0,
        rules: Iterable[FaultRule] = (),
        outages: Iterable[OutageWindow] = (),
        clock: Callable[[], float] = time.monotonic,
        journal: Journal | None = None,
    ) -> None:
        self.seed = seed
        self._rng = random.Random(seed)
        self.rules: list[FaultRule] = list(rules)
        self.outages: list[OutageWindow] = list(outages)
        self._clock = clock
        self._t0: float | None = None
        self._enabled = True
        self.journal = journal
        # Scripted total outages: op pattern -> hang?
        self._down: dict[str, bool] = {}
        self._heal_event: asyncio.Event | None = None
        # Wrappers to re-arm when `enabled` flips (weakrefs: a schedule
        # outliving its wrappers must not pin them).
        self._wrappers: list[weakref.ref] = []
        # Counters (surface through gauges()).
        self.ops = 0
        self.injected_errors = 0
        self.injected_delays = 0
        self.injected_hangs = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    @enabled.setter
    def enabled(self, value: bool) -> None:
        """Flipping ``enabled`` re-arms every attached wrapper: disabled
        wrappers swap the inner backend's bound methods onto themselves
        (zero-cost passthrough — the disabled-overhead contract that
        ``bench.py --faults`` measures), enabling restores the gates."""
        self._enabled = bool(value)
        alive: list[weakref.ref] = []
        for ref in self._wrappers:
            w = ref()
            if w is not None:
                w._rearm()
                alive.append(ref)
        self._wrappers = alive

    def _register(self, wrapper: Any) -> None:
        self._wrappers.append(weakref.ref(wrapper))

    # -- scripting -----------------------------------------------------------

    def start(self) -> None:
        """Pin t=0 for :class:`OutageWindow` matching (idempotent)."""
        if self._t0 is None:
            self._t0 = self._clock()

    @property
    def elapsed(self) -> float:
        if self._t0 is None:
            return 0.0
        return self._clock() - self._t0

    def fail_all(self, op: str = "*", *, hang: bool = False) -> None:
        """Scripted total outage for every operation matching ``op``."""
        self._down[op] = hang
        self._journal_edge("fail_all", op, hang=hang)

    def heal(self, op: str | None = None) -> None:
        """End scripted outages (all of them, or just pattern ``op``) and
        wake every parked hang."""
        if op is None:
            self._down.clear()
        else:
            self._down.pop(op, None)
        ev = self._heal_event
        if ev is not None:
            self._heal_event = None
            ev.set()
        self._journal_edge("heal", op or "*")

    def is_down(self, op: str) -> bool:
        if self._down and any(fnmatch.fnmatch(op, p) for p in self._down):
            return True
        if self.outages:
            t = self.elapsed
            return any(
                w.start <= t < w.end and fnmatch.fnmatch(op, w.op)
                for w in self.outages
            )
        return False

    def _journal_edge(self, action: str, op: str, **attrs: Any) -> None:
        if self.journal is not None:
            self.journal.record(FAULT, op, action=action, **attrs)

    # -- decisions -----------------------------------------------------------

    @property
    def idle(self) -> bool:
        """True when no rule, outage, or scripted failure could possibly
        fire — the wrappers skip the whole ``perturb`` coroutine then, so
        an installed-but-unconfigured schedule stays off the hot path (the
        service layer reads the directory per request; ``bench.py
        --faults`` prices this gate)."""
        return not self.enabled or not (self.rules or self.outages or self._down)

    def decide(self, op: str) -> tuple[float, bool, bool]:
        """``(delay_seconds, fail, hang)`` for one call — sync and seeded,
        so wire-level fakes (``tests/fake_pg.py``) share the decisions."""
        if not self.enabled:
            return (0.0, False, False)
        self.ops += 1
        if self._t0 is None and self.outages:
            self._t0 = self._clock()
        for pattern, hang in self._down.items():
            if fnmatch.fnmatch(op, pattern):
                return (0.0, not hang, hang)
        if self.outages:
            t = self.elapsed
            for w in self.outages:
                if w.start <= t < w.end and fnmatch.fnmatch(op, w.op):
                    return (0.0, not w.hang, w.hang)
        delay = 0.0
        fail = False
        hang = False
        for rule in self.rules:
            if not fnmatch.fnmatch(op, rule.op):
                continue
            if rule.latency or rule.jitter:
                delay += rule.latency
                if rule.jitter:
                    delay += self._rng.uniform(0.0, rule.jitter)
            if rule.error_rate and self._rng.random() < rule.error_rate:
                fail = True
            if rule.hang:
                hang = True
        return (delay, fail, hang)

    async def perturb(self, op: str) -> None:
        """Async gate: sleep injected latency, park on hang (until
        :meth:`heal`), raise :class:`InjectedFault` on an injected error."""
        delay, fail, hang = self.decide(op)
        if delay > 0.0:
            self.injected_delays += 1
            await asyncio.sleep(delay)
        if hang:
            self.injected_hangs += 1
            if self._heal_event is None:
                self._heal_event = asyncio.Event()
            await self._heal_event.wait()
            return
        if fail:
            self.injected_errors += 1
            if self.injected_errors == 1:
                self._journal_edge("inject", op)
            raise InjectedFault(op)

    def apply_sync(self, op: str) -> None:
        """Sync gate for DBAPI-level fakes running in executor threads:
        ``time.sleep`` the latency; a hang verdict degrades to an error
        (threads cannot park on the loop's heal event)."""
        delay, fail, hang = self.decide(op)
        if delay > 0.0:
            self.injected_delays += 1
            time.sleep(delay)
        if fail or hang:
            self.injected_errors += 1
            raise InjectedFault(op)

    def gauges(self) -> dict[str, float]:
        return {
            "rio.faults.ops": float(self.ops),
            "rio.faults.errors": float(self.injected_errors),
            "rio.faults.delays": float(self.injected_delays),
            "rio.faults.hangs": float(self.injected_hangs),
            "rio.faults.down_patterns": float(len(self._down)),
        }


@dataclasses.dataclass
class StorageResilienceConfig:
    """Knobs for the storage-outage degraded modes (AppData-resident).

    ``route_timeout`` bounds the request path's directory awaits — with a
    hung (not erroring) rendezvous the routing block times out and sheds
    with the retryable SERVER_BUSY instead of hanging the request.
    ``None`` keeps the pre-fault unbounded behavior (real backends carry
    their own socket timeouts). The backoff pair seeds the gossip/daemon
    :class:`~rio_tpu.utils.backoff.DecorrelatedJitter` retry sleeps.
    """

    route_timeout: float | None = None
    backoff_base: float = 0.05
    backoff_cap: float = 2.0


class StorageHealth:
    """Node-wide storage health: error/latency counters + degraded flags.

    One instance per server (AppData-resident, like the journal): the
    storage wrappers feed op latency and real backend errors; the hardened
    loops (gossip, service routing, daemons) feed per-source degraded
    transitions — ``note_error``/``note_ok`` return ``True`` exactly on
    the edge, so callers journal one STORAGE event per outage, not one
    per failed call.
    """

    def __init__(self) -> None:
        self.ops = 0
        self.errors = 0
        self.injected = 0
        self.degraded_serves = 0  # seated actors served during an outage
        self.sheds = 0  # unseated requests shed retryably during an outage
        self.last_error = ""
        self.last_error_op = ""
        self._down: set[str] = set()  # sources currently degraded
        self._lat_samples = 0
        self._lat_sum_ms = 0.0
        self._lat_max_ms = 0.0

    # -- wrapper feed --------------------------------------------------------

    def note_op(self, seconds: float | None) -> None:
        """Count one successful op; ``seconds`` feeds the latency gauges
        when the caller timed it (the idle-schedule fast path samples
        1-in-N — see ``_FaultyBase._call`` — so request-path delegation
        doesn't pay two clock reads per call)."""
        self.ops += 1
        if seconds is None:
            return
        self._lat_samples += 1
        ms = seconds * 1e3
        self._lat_sum_ms += ms
        if ms > self._lat_max_ms:
            self._lat_max_ms = ms

    # -- degraded-transition tracking ---------------------------------------

    def note_error(
        self, op: str, exc: BaseException, *, source: str = "", injected: bool = False
    ) -> bool:
        """Count one failed storage call; ``True`` when this flips
        ``source`` from healthy to degraded (the journal-once edge)."""
        self.errors += 1
        if injected:
            self.injected += 1
        self.last_error = repr(exc)[:160]
        self.last_error_op = op
        if not source or source in self._down:
            return False
        self._down.add(source)
        return True

    def note_ok(self, source: str) -> bool:
        """``True`` when ``source`` just recovered from degraded."""
        if source in self._down:
            self._down.discard(source)
            return True
        return False

    @property
    def degraded(self) -> bool:
        return bool(self._down)

    def note_degraded_serve(self) -> None:
        self.degraded_serves += 1

    def note_shed(self) -> None:
        self.sheds += 1

    def gauges(self) -> dict[str, float]:
        avg = self._lat_sum_ms / self._lat_samples if self._lat_samples else 0.0
        return {
            "rio.storage.ops": float(self.ops),
            "rio.storage.errors": float(self.errors),
            "rio.storage.injected": float(self.injected),
            "rio.storage.degraded_serves": float(self.degraded_serves),
            "rio.storage.sheds": float(self.sheds),
            "rio.storage.degraded_sources": float(len(self._down)),
            "rio.storage.op_latency_avg_ms": avg,
            "rio.storage.op_latency_max_ms": self._lat_max_ms,
        }


# ---------------------------------------------------------------------------
# Storage trait wrappers
# ---------------------------------------------------------------------------


class _FaultyBase:
    """Delegating wrapper core: gate → time → delegate → count.

    ``__getattr__`` forwards everything not explicitly wrapped (provider
    extensions like ``sync_members``/``rebalance``/``count`` on concrete
    backends), so a wrapped backend keeps its full duck-typed surface —
    ``hasattr`` probes in the service layer see exactly what the inner
    object offers.
    """

    def __init__(self, inner: Any, schedule: FaultSchedule, health: StorageHealth | None = None) -> None:
        self._inner = inner
        self._schedule = schedule
        self._health = health
        schedule._register(self)
        self._rearm()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    @classmethod
    def _gated_methods(cls) -> tuple[str, ...]:
        """The trait coroutines this wrapper gates — every public async def
        declared on the Faulty classes themselves (inherited ABC helpers
        like ``set_active`` route through these, so they stay un-swapped)."""
        cached = cls.__dict__.get("_gated_cache")
        if cached is None:
            names: list[str] = []
            for klass in cls.__mro__:
                if klass is _FaultyBase:
                    break
                for name, fn in vars(klass).items():
                    if not name.startswith("_") and inspect.iscoroutinefunction(fn):
                        names.append(name)
            cached = tuple(dict.fromkeys(names))
            cls._gated_cache = cached
        return cached

    def _rearm(self) -> None:
        """Sync the passthrough swap with ``schedule.enabled``.

        Disabled: the inner backend's bound methods are written straight
        onto the instance, shadowing the gated class methods — a disabled
        wrapper costs literally nothing per call (no extra coroutine, no
        counter), which is the parity contract ``bench.py --faults``
        measures. Enabled: the shadows are removed and every call gates
        through ``_call`` again (idle schedules still count ops/health
        there via the inlined fast path).
        """
        if self._schedule.enabled:
            for name in self._gated_methods():
                self.__dict__.pop(name, None)
        else:
            for name in self._gated_methods():
                inner_fn = getattr(self._inner, name, None)
                if inner_fn is not None:
                    self.__dict__[name] = inner_fn

    async def _call(self, op: str, fn: Callable[..., Any], *args: Any, **kw: Any) -> Any:
        s = self._schedule
        if s.enabled and (s.rules or s.outages or s._down):
            # Gated path: something could fire — full perturb + timing.
            try:
                await s.perturb(op)
            except InjectedFault as e:
                if self._health is not None:
                    self._health.note_error(op, e, injected=True)
                raise
            t0 = time.perf_counter()
            try:
                out = await fn(*args, **kw)
            except asyncio.CancelledError:
                raise
            except NotImplementedError:
                raise  # optional trait surface, not a storage failure
            except Exception as e:
                if self._health is not None:
                    self._health.note_error(op, e)
                raise
            if self._health is not None:
                self._health.note_op(time.perf_counter() - t0)
            return out
        # Idle/disabled fast path (the ``decide`` checks inlined — attribute
        # reads, no property or coroutine): real backend errors still feed
        # health, latency is sampled 1-in-16 so the per-request directory
        # lookup doesn't pay two clock reads (``bench.py --faults`` holds
        # this path to parity with unwrapped backends).
        h = self._health
        if h is None:
            return await fn(*args, **kw)
        t0 = time.perf_counter() if (h.ops & 0xF) == 0 else None
        try:
            out = await fn(*args, **kw)
        except asyncio.CancelledError:
            raise
        except NotImplementedError:
            raise  # optional trait surface, not a storage failure
        except Exception as e:
            h.note_error(op, e)
            raise
        h.note_op(None if t0 is None else time.perf_counter() - t0)
        return out


# The wrappers implement the full abstract surface explicitly (so the ABCs
# instantiate) and inherit each trait's default helpers, which route back
# through the gated methods.

from .cluster.storage import Member, MembershipStorage  # noqa: E402
from .object_placement import ObjectPlacement, ObjectPlacementItem  # noqa: E402
from .registry import ObjectId  # noqa: E402
from .reminders import Lease, Reminder, ReminderStorage  # noqa: E402
from .streams import StreamRecord, StreamStorage, Subscription  # noqa: E402


class FaultyMembershipStorage(_FaultyBase, MembershipStorage):
    """``MembershipStorage`` with a :class:`FaultSchedule` at every call."""

    async def prepare(self) -> None:
        return await self._call("membership.prepare", self._inner.prepare)

    async def push(self, member: Member) -> None:
        return await self._call("membership.push", self._inner.push, member)

    async def remove(self, ip: str, port: int) -> None:
        return await self._call("membership.remove", self._inner.remove, ip, port)

    async def set_is_active(self, ip: str, port: int, active: bool) -> None:
        return await self._call(
            "membership.set_is_active", self._inner.set_is_active, ip, port, active
        )

    async def members(self) -> list[Member]:
        return await self._call("membership.members", self._inner.members)

    async def notify_failure(self, ip: str, port: int) -> None:
        return await self._call(
            "membership.notify_failure", self._inner.notify_failure, ip, port
        )

    async def member_failures(self, ip: str, port: int) -> list[float]:
        return await self._call(
            "membership.member_failures", self._inner.member_failures, ip, port
        )


class FaultyObjectPlacement(_FaultyBase, ObjectPlacement):
    """``ObjectPlacement`` with a :class:`FaultSchedule` at every call."""

    async def prepare(self) -> None:
        return await self._call("placement.prepare", self._inner.prepare)

    async def update(self, item: ObjectPlacementItem) -> None:
        return await self._call("placement.update", self._inner.update, item)

    async def lookup(self, object_id: ObjectId) -> str | None:
        return await self._call("placement.lookup", self._inner.lookup, object_id)

    async def clean_server(self, address: str) -> None:
        return await self._call(
            "placement.clean_server", self._inner.clean_server, address
        )

    async def remove(self, object_id: ObjectId) -> None:
        return await self._call("placement.remove", self._inner.remove, object_id)

    async def lookup_batch(self, object_ids: list[ObjectId]) -> list[str | None]:
        return await self._call(
            "placement.lookup_batch", self._inner.lookup_batch, object_ids
        )

    async def update_batch(self, items: list[ObjectPlacementItem]) -> None:
        return await self._call(
            "placement.update_batch", self._inner.update_batch, items
        )

    async def items(self) -> list[ObjectPlacementItem]:
        return await self._call("placement.items", self._inner.items)

    async def set_standbys(self, object_id: ObjectId, addresses: list[str]) -> int:
        return await self._call(
            "placement.set_standbys", self._inner.set_standbys, object_id, addresses
        )

    async def standbys(self, object_id: ObjectId) -> tuple[list[str], int]:
        return await self._call("placement.standbys", self._inner.standbys, object_id)

    async def promote_standby(
        self, object_id: ObjectId, address: str, expected_epoch: int
    ) -> int | None:
        return await self._call(
            "placement.promote_standby",
            self._inner.promote_standby,
            object_id,
            address,
            expected_epoch,
        )


class FaultyReminderStorage(_FaultyBase, ReminderStorage):
    """``ReminderStorage`` with a :class:`FaultSchedule` at every call."""

    def __init__(self, inner: Any, schedule: FaultSchedule, health: StorageHealth | None = None) -> None:
        super().__init__(inner, schedule, health)
        self.num_shards = inner.num_shards

    async def prepare(self) -> None:
        return await self._call("reminders.prepare", self._inner.prepare)

    async def upsert(self, reminder: Reminder) -> None:
        return await self._call("reminders.upsert", self._inner.upsert, reminder)

    async def remove(self, object_kind: str, object_id: str, reminder_name: str) -> None:
        return await self._call(
            "reminders.remove", self._inner.remove, object_kind, object_id, reminder_name
        )

    async def remove_object(self, object_kind: str, object_id: str) -> None:
        return await self._call(
            "reminders.remove_object", self._inner.remove_object, object_kind, object_id
        )

    async def list_object(self, object_kind: str, object_id: str) -> list[Reminder]:
        return await self._call(
            "reminders.list_object", self._inner.list_object, object_kind, object_id
        )

    async def due(self, shard: int, now: float, limit: int = 256) -> list[Reminder]:
        return await self._call("reminders.due", self._inner.due, shard, now, limit)

    async def reschedule(
        self, object_kind: str, object_id: str, reminder_name: str, next_due: float
    ) -> None:
        return await self._call(
            "reminders.reschedule",
            self._inner.reschedule,
            object_kind,
            object_id,
            reminder_name,
            next_due,
        )

    async def shard_counts(self) -> dict[int, int]:
        return await self._call("reminders.shard_counts", self._inner.shard_counts)

    async def acquire_lease(
        self, shard: int, owner: str, ttl: float, now: float | None = None
    ) -> Lease | None:
        return await self._call(
            "reminders.acquire_lease", self._inner.acquire_lease, shard, owner, ttl, now
        )

    async def release_lease(self, shard: int, owner: str, epoch: int) -> None:
        return await self._call(
            "reminders.release_lease", self._inner.release_lease, shard, owner, epoch
        )

    async def get_lease(self, shard: int) -> Lease | None:
        return await self._call("reminders.get_lease", self._inner.get_lease, shard)


class FaultyStreamStorage(_FaultyBase, StreamStorage):
    """``StreamStorage`` with a :class:`FaultSchedule` at every call.

    The interesting chaos surface for streams is the *durability seam*:
    an ``append`` that fails BEFORE the ack means the publisher retries
    (no loss); a ``commit`` that fails leaves the cursor behind, so the
    redelivery backstop re-reads — at-least-once, never lost-acked.
    """

    def __init__(self, inner: Any, schedule: FaultSchedule, health: StorageHealth | None = None) -> None:
        super().__init__(inner, schedule, health)
        self.num_partitions = inner.num_partitions

    async def prepare(self) -> None:
        return await self._call("streams.prepare", self._inner.prepare)

    async def append(self, record: StreamRecord) -> int:
        return await self._call("streams.append", self._inner.append, record)

    async def read(
        self, stream: str, partition: int, from_offset: int, limit: int = 256
    ) -> list[StreamRecord]:
        return await self._call(
            "streams.read", self._inner.read, stream, partition, from_offset, limit
        )

    async def latest(self, stream: str, partition: int) -> int:
        return await self._call("streams.latest", self._inner.latest, stream, partition)

    async def subscribe(self, sub: Subscription) -> None:
        return await self._call("streams.subscribe", self._inner.subscribe, sub)

    async def unsubscribe(self, stream: str, group: str) -> None:
        return await self._call(
            "streams.unsubscribe", self._inner.unsubscribe, stream, group
        )

    async def subscriptions(self, stream: str) -> list[Subscription]:
        return await self._call(
            "streams.subscriptions", self._inner.subscriptions, stream
        )

    async def commit(
        self, stream: str, group: str, partition: int, offset: int
    ) -> None:
        return await self._call(
            "streams.commit", self._inner.commit, stream, group, partition, offset
        )

    async def committed(self, stream: str, group: str, partition: int) -> int:
        return await self._call(
            "streams.committed", self._inner.committed, stream, group, partition
        )

    async def cursors(self, stream: str, group: str) -> dict[int, int]:
        return await self._call("streams.cursors", self._inner.cursors, stream, group)


# ---------------------------------------------------------------------------
# Transport faults
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LinkRule:
    """Directional perturbation of the ``src -> dst`` link.

    ``src``/``dst`` are ``fnmatch`` patterns over addresses (``"*"`` =
    any). Probabilities are per connect/frame; ``drop=1.0`` is a full
    one-way partition. Rules are directional on purpose — an asymmetric
    partition is two different answers for ``(A, B)`` and ``(B, A)``.
    """

    src: str = "*"
    dst: str = "*"
    drop: float = 0.0
    delay: float = 0.0
    reset: float = 0.0


class TransportFaults:
    """Seeded per-link fault decisions for dials and frames.

    The client (and through it the gossip prober) consults
    :meth:`connect_gate` before dialing and wraps established connections
    via :meth:`wrap_conn`, so both connection-level partitions and
    frame-level drop/delay/reset are injectable without touching the
    transports themselves.
    """

    def __init__(self, seed: int = 0) -> None:
        self._rng = random.Random(seed)
        self.rules: list[LinkRule] = []
        self.connects_blocked = 0
        self.frames_dropped = 0
        self.frames_delayed = 0
        self.resets = 0

    # -- scripting -----------------------------------------------------------

    def add_rule(self, rule: LinkRule) -> None:
        self.rules.append(rule)

    def partition(self, src: str = "*", dst: str = "*", *, symmetric: bool = False) -> None:
        """Full drop of ``src -> dst`` (and the reverse when symmetric)."""
        self.rules.append(LinkRule(src=src, dst=dst, drop=1.0))
        if symmetric:
            self.rules.append(LinkRule(src=dst, dst=src, drop=1.0))

    def heal(self, src: str | None = None, dst: str | None = None) -> None:
        """Remove all rules, or only those matching the given endpoints."""
        if src is None and dst is None:
            self.rules.clear()
            return
        self.rules = [
            r
            for r in self.rules
            if not (
                (src is None or r.src == src) and (dst is None or r.dst == dst)
            )
        ]

    def _verdict(self, src: str, dst: str) -> tuple[bool, float, bool]:
        """``(drop, delay, reset)`` across every matching rule."""
        drop = False
        delay = 0.0
        reset = False
        for r in self.rules:
            if not (fnmatch.fnmatch(src, r.src) and fnmatch.fnmatch(dst, r.dst)):
                continue
            if r.drop and (r.drop >= 1.0 or self._rng.random() < r.drop):
                drop = True
            if r.delay:
                delay += r.delay
            if r.reset and (r.reset >= 1.0 or self._rng.random() < r.reset):
                reset = True
        return drop, delay, reset

    # -- gates ---------------------------------------------------------------

    async def connect_gate(self, src: str, dst: str) -> None:
        """Raise ``ConnectionRefusedError`` (an ``OSError`` — the shape a
        refused dial really has) when the link is down; apply link delay."""
        drop, delay, reset = self._verdict(src, dst)
        if delay > 0.0:
            await asyncio.sleep(delay)
        if drop or reset:
            self.connects_blocked += 1
            raise ConnectionRefusedError(f"injected partition {src or '?'} -> {dst}")

    def wrap_conn(self, conn: Any, src: str, dst: str) -> "FaultyConn":
        return FaultyConn(conn, self, src, dst)

    def gauges(self) -> dict[str, float]:
        return {
            "rio.transport_faults.connects_blocked": float(self.connects_blocked),
            "rio.transport_faults.frames_dropped": float(self.frames_dropped),
            "rio.transport_faults.frames_delayed": float(self.frames_delayed),
            "rio.transport_faults.resets": float(self.resets),
            "rio.transport_faults.rules": float(len(self.rules)),
        }


class FaultyConn:
    """Framed-connection wrapper applying per-frame link verdicts.

    Surface-compatible with both transports' client connections
    (``roundtrip``/``read_frame``/``write``/``close``/``closed``/
    ``pending``/``delivered``) so the pool treats it as any socket. A
    dropped or reset frame closes the underlying connection and raises
    ``Disconnect`` — the client's existing dial-failure retry path takes
    over, exactly as with a real mid-flight cable pull.
    """

    def __init__(self, inner: Any, faults: TransportFaults, src: str, dst: str) -> None:
        self._inner = inner
        self._faults = faults
        self._src = src
        self._dst = dst

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    @property
    def closed(self) -> bool:
        return self._inner.closed

    @property
    def pending(self) -> int:
        return self._inner.pending

    @property
    def delivered(self) -> int:
        return self._inner.delivered

    async def _gate(self) -> None:
        from .errors import Disconnect

        drop, delay, reset = self._faults._verdict(self._src, self._dst)
        if delay > 0.0:
            self._faults.frames_delayed += 1
            await asyncio.sleep(delay)
        if drop:
            self._faults.frames_dropped += 1
            self._inner.close()
            raise Disconnect(f"injected frame drop {self._src or '?'} -> {self._dst}")
        if reset:
            self._faults.resets += 1
            self._inner.close()
            raise Disconnect(f"injected reset {self._src or '?'} -> {self._dst}")

    async def roundtrip(self, frame_bytes: bytes) -> bytes:
        await self._gate()
        return await self._inner.roundtrip(frame_bytes)

    async def read_frame(self) -> bytes | None:
        return await self._inner.read_frame()

    def write(self, frame_bytes: bytes) -> None:
        self._inner.write(frame_bytes)

    def close(self) -> None:
        self._inner.close()


# ---------------------------------------------------------------------------
# Demo / CI smoke
# ---------------------------------------------------------------------------


async def _demo() -> dict[str, float]:
    """Deterministic end-to-end smoke: wrap the in-memory backends, script
    an outage, verify injections and recovery. Returns the gauge snapshot
    (printed by ``--demo``); raises on any contract violation."""
    from .cluster.storage import LocalStorage
    from .object_placement import LocalObjectPlacement

    journal = Journal(capacity=64, node="demo")
    schedule = FaultSchedule(
        seed=7,
        rules=[FaultRule(op="placement.lookup", error_rate=0.5)],
        journal=journal,
    )
    health = StorageHealth()
    members = FaultyMembershipStorage(LocalStorage(), schedule, health)
    placement = FaultyObjectPlacement(LocalObjectPlacement(), schedule, health)

    await members.push(Member.from_address("10.0.0.1:5000", active=True))
    assert [m.address for m in await members.active_members()] == ["10.0.0.1:5000"]

    # Seeded error rate on lookups: some calls fail, some succeed.
    oid = ObjectId("Demo", "x")
    await placement.update(ObjectPlacementItem(object_id=oid, server_address="10.0.0.1:5000"))
    outcomes = []
    for _ in range(16):
        try:
            outcomes.append(await placement.lookup(oid))
        except InjectedFault:
            outcomes.append(None)
    assert any(o is not None for o in outcomes), "every lookup failed at 0.5 rate"
    assert any(o is None for o in outcomes), "no lookup failed at 0.5 rate"

    # Scripted total outage, then recovery.
    schedule.fail_all("membership.*")
    try:
        await members.members()
        raise AssertionError("outage did not fail membership.members")
    except InjectedFault:
        pass
    assert (await placement.lookup(oid) or True), "outage leaked across traits"
    schedule.heal()
    assert [m.address for m in await members.members()] == ["10.0.0.1:5000"]

    # Transport: asymmetric partition blocks A->B only.
    tf = TransportFaults(seed=7)
    tf.partition("10.0.0.1:*", "10.0.0.2:*")
    blocked = False
    try:
        await tf.connect_gate("10.0.0.1:5000", "10.0.0.2:5000")
    except OSError:
        blocked = True
    assert blocked, "partition did not block the forward link"
    await tf.connect_gate("10.0.0.2:5000", "10.0.0.1:5000")  # reverse flows
    tf.heal()
    await tf.connect_gate("10.0.0.1:5000", "10.0.0.2:5000")

    kinds = [ev.kind for ev in journal.events()]
    assert FAULT in kinds, "schedule transitions did not journal FAULT events"
    out = dict(schedule.gauges())
    out.update(health.gauges())
    out.update(tf.gauges())
    return out


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json

    parser = argparse.ArgumentParser(prog="python -m rio_tpu.faults")
    parser.add_argument(
        "--demo",
        action="store_true",
        help="run the deterministic fault-injection smoke and print gauges",
    )
    args = parser.parse_args(argv)
    if not args.demo:
        parser.print_help()
        return 2
    gauges = asyncio.run(_demo())
    print(json.dumps({k: gauges[k] for k in sorted(gauges)}))
    print("faults demo: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
