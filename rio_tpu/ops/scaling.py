"""Scaling-form (Sinkhorn-Knopp) solver: MXU matmuls, no per-iteration exp.

The log-domain solve (:mod:`rio_tpu.ops.sinkhorn`) pays two full
transcendental sweeps (exp) over the (objects x nodes) matrix per
iteration — on TPU that is VPU-bound, not HBM-bound. The classical scaling
form moves every transcendental *out* of the loop:

    K = exp(-C / eps)                  # once
    repeat:  u = a / (K @ v) ;  v = b / (K^T @ u)
    f = eps * log u ;  g = eps * log v

Each iteration is two matrix-vector products — pure MXU work, bandwidth
bound on reading ``K``. ``K`` can be stored bfloat16 (halving the traffic
again); products accumulate in float32.

Two implementations:

* :func:`scaling_sinkhorn` — plain XLA (two reads of K per iteration).
* :func:`pallas_scaling_sinkhorn` — fused Pallas kernel: the grid walks
  row blocks once per iteration, computing ``u_block = a / (K_block @ v)``
  and accumulating ``u_block^T @ K_block`` into the column marginal in VMEM
  scratch — ONE read of K per iteration, the bandwidth floor.

PROMOTED (r5): the fused kernel measured 1.19x the XLA loop by iteration
slope at 262144x1024 on TPU v5e (PALLAS_TPU.json ``pallas_scaling``:
1.297 vs 1.548 ms/iter; the log-domain pallas kernel LOST at 0.72x and
stays quarantined as a parity-tested reference). :func:`scaling_core_auto`
selects it on TPU in the bandwidth-bound regime; the bench solve tier and
any dense flat solve go through that dispatcher.

Numerics: with cost scale O(1) and eps >= ~0.03, exp(-C/eps) stays well
inside float32/bfloat16 range and the scalings stay finite; zero-mass rows
(padding) give u = 0 and dead columns v = 0, reproducing the log-domain
-inf conventions after the final log. Iterations are mathematically
identical to the log-domain updates, so results match within dtype
tolerance (see tests/test_scaling_sinkhorn.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .sinkhorn import (
    SinkhornResult,
    _safe_log,
    marginal_err,
    normalize_marginals,
    pad_axis_to,
)

_NEG_INF = float("-inf")


def _potentials(u, v, eps):
    f = jnp.where(u > 0, eps * _safe_log(u), _NEG_INF)
    g = jnp.where(v > 0, eps * _safe_log(v), _NEG_INF)
    return f, g


def _warm_seed(g_init: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Effective warm seed and the global gauge ``s`` it is lowered by.

    Non-finite entries (dead columns of the previous solve) cold-fill to 0
    — EXACTLY what the log-domain solver does with its warm seed, so the
    two stay comparable. ``v0 = exp(g0 / eps)`` would overflow for large
    potentials, so the seed is gauged down by its max: ``v0 = exp((g0 - s)
    / eps)`` with every exponent <= 0. Unlike the log-domain solver —
    whose g-update recomputes g from scratch each iteration — the scaling
    updates are homogeneous (``u = a/(Kv)``, ``v = b/(K^T u)``), so the
    gauge PERSISTS through every iteration: the converged scalings come
    out as ``(u * e^{s/eps}, v * e^{-s/eps})`` relative to the ungauged
    warm solve, and the caller must correct the final potentials by
    ``f - s`` / ``g + s`` to match the log-domain reference. This is a
    GLOBAL scalar on the warm seed only — fully orthogonal to the
    per-row min-shift on the cost (which must stay per-row, see
    :func:`scaling_core`)."""
    g0 = jnp.where(jnp.isfinite(g_init), g_init.astype(jnp.float32), 0.0)
    return g0, jnp.max(g0)


@functools.partial(jax.jit, static_argnames=("eps", "n_iters", "kernel_dtype"))
def scaling_core(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    kernel_dtype=jnp.bfloat16,
    g_init: jax.Array | None = None,
):
    """The scaling iteration itself; returns ``(u, v, K, row_shift)``.

    ``row_shift`` is the (n,) per-row gauge shift subtracted from the cost
    before exponentiating (add it back to ``eps*log(u)`` to recover ``f``).

    Exposed separately from :func:`scaling_sinkhorn` so capacity-aware
    rounding can reuse the already-materialized kernel ``K`` (see
    :func:`rio_tpu.ops.sinkhorn.plan_rounded_assign_from_scaling`): the
    plan is ``P = diag(u) K diag(v)`` — re-deriving it from the cost
    matrix would re-read the fp32 cost (2x the bytes of a bf16 K) and
    re-do a transcendental sweep.

    ``g_init`` warm-starts ``v0`` from a previous solve's node potentials.
    The seed is gauged by its max entry (:func:`_warm_seed`) so the
    exponential never overflows; that gauge persists through the
    homogeneous iterations, so the returned ``(u, v)`` are the warm solve's
    scalings times ``(e^{s/eps}, e^{-s/eps})`` — callers that need
    log-domain-parity potentials correct by ``s`` (as
    :func:`scaling_sinkhorn` does). Exponents are clipped at -60 (below
    which a live column's seed would denormal to zero and the column would
    restart cold anyway).
    """
    cost = cost.astype(jnp.float32)
    a, b = normalize_marginals(row_mass, col_capacity)
    # PER-ROW min-shift: pure gauge (each row's shift is absorbed into that
    # row's u), keeps every row's best entry at exp(0)=1 — so no row can
    # underflow to all-zeros no matter the global cost range (a global
    # shift breaks down once range/eps >> 88: tail rows lose every entry
    # and their u explodes; observed at the 10M-object hierarchical tier).
    # Individual high-cost pairs may still underflow — acceptable, they are
    # effectively forbidden. The shift is folded back into f by
    # scaling_sinkhorn so the returned potentials match the log-domain
    # solver exactly, not just up to gauge.
    shift = jnp.min(cost, axis=1, keepdims=True)  # (n, 1)
    # Padding rows of +inf cost would make shift inf -> NaN in K; they
    # carry no mass, so pin their shift to 0.
    shift = jnp.where(jnp.isfinite(shift), shift, 0.0)
    cost = cost - shift
    K = jnp.exp(-cost / eps).astype(kernel_dtype)

    def body(carry, _):
        _, v = carry
        Kv = jnp.matmul(K, v.astype(kernel_dtype), preferred_element_type=jnp.float32)
        u = a / jnp.maximum(Kv, 1e-30)
        u = jnp.where(a > 0, u, 0.0)
        KTu = jnp.matmul(u.astype(kernel_dtype), K, preferred_element_type=jnp.float32)
        v = b / jnp.maximum(KTu, 1e-30)
        v = jnp.where(b > 0, v, 0.0)
        return (u, v), None

    u0 = jnp.zeros_like(a)
    if g_init is None:
        v0 = jnp.ones_like(b)
    else:
        g_seed, s = _warm_seed(g_init)
        v0 = jnp.exp(jnp.clip((g_seed - s) / eps, -60.0, 0.0))
    (u, v), _ = lax.scan(body, (u0, v0), None, length=n_iters)
    return u, v, K, shift[:, 0]


@functools.partial(jax.jit, static_argnames=("eps", "n_iters", "kernel_dtype"))
def scaling_sinkhorn(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    kernel_dtype=jnp.bfloat16,
    g_init: jax.Array | None = None,
) -> SinkhornResult:
    """Sinkhorn-Knopp in scaling form; returns log-domain potentials.

    Matches :func:`rio_tpu.ops.sinkhorn.sinkhorn` up to dtype tolerance
    (use ``kernel_dtype=jnp.float32`` for tightest parity) — including
    under ``g_init`` warm start: the warm seed's global gauge (see
    :func:`_warm_seed`) persists through the homogeneous scaling
    iterations and is undone here, so warm potentials agree with the
    warm log-domain reference, not just up to gauge.
    """
    u, v, _, shift = scaling_core(
        cost, row_mass, col_capacity, eps=eps, n_iters=n_iters,
        kernel_dtype=kernel_dtype, g_init=g_init,
    )
    cost = cost.astype(jnp.float32) - shift[:, None]
    _, b = normalize_marginals(row_mass, col_capacity)
    f, g = _potentials(u, v, eps)
    if g_init is not None:
        # Undo the warm gauge (f/g shift by ∓s; f+g is invariant, so the
        # marginal-err diagnostic below is unaffected either way).
        _, s = _warm_seed(g_init)
        f = jnp.where(jnp.isfinite(f), f - s, f)
        g = jnp.where(jnp.isfinite(g), g + s, g)
    err = marginal_err(cost, f, g, b, eps)  # shifted-cost/shifted-f pair
    f = jnp.where(jnp.isfinite(f), f + shift, f)  # undo the gauge shift
    return SinkhornResult(f=f, g=g, err=err)


# ---------------------------------------------------------------------------
# Fused Pallas iteration: one sweep of K per iteration
# ---------------------------------------------------------------------------


def _scaling_kernel(
    a_ref,      # (B, 1) row marginals block
    b_ref,      # (1, M) column marginals
    v_ref,      # (1, M) previous column scaling
    k_ref,      # (B, M) kernel block
    u_out_ref,  # (B, 1) new row scaling for this block
    v_out_ref,  # (1, M) new column scaling (written on last step)
    col_acc,    # (1, M) VMEM scratch: running K^T u partial
):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        col_acc[:] = jnp.zeros_like(col_acc[:])

    # A matvec is bandwidth-bound (one FMA per element), so the VPU with an
    # explicit f32 multiply-reduce hits the same roofline as the MXU would —
    # and Mosaic lowers degenerate (B,M)x(1,M) dots poorly.
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:]  # (1, M)
    a = a_ref[:]  # (B, 1)
    Kv = jnp.sum(k * v, axis=1, keepdims=True)  # (B, 1)
    u = a / jnp.maximum(Kv, 1e-30)
    u = jnp.where(a > 0, u, 0.0)
    u_out_ref[:] = u
    col_acc[:] = col_acc[:] + jnp.sum(k * u, axis=0, keepdims=True)  # (1, M)

    @pl.when(step == pl.num_programs(0) - 1)
    def _finalize():
        b = b_ref[:]
        v_new = b / jnp.maximum(col_acc[:], 1e-30)
        v_out_ref[:] = jnp.where(b > 0, v_new, 0.0)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def fused_scaling_iteration(
    K: jax.Array,
    a: jax.Array,
    b: jax.Array,
    v: jax.Array,
    *,
    block_rows: int = 1024,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One fused scaling iteration: returns (u_new, v_new)."""
    n, m = K.shape
    assert n % block_rows == 0, (n, block_rows)
    grid = (n // block_rows,)
    u, v_new = pl.pallas_call(
        _scaling_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, m), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, m), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, m), jnp.float32)],
        interpret=interpret,
    )(a.reshape(n, 1), b.reshape(1, m), v.reshape(1, m), K)
    return u.reshape(n), v_new.reshape(m)


def pallas_scaling_core(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    kernel_dtype=jnp.bfloat16,
    block_rows: int = 1024,
    interpret: bool | None = None,
):
    """Fused-kernel drop-in for :func:`scaling_core`: ``(u, v, K, shift)``.

    Same contract as :func:`scaling_core` (the returned ``K`` is the
    UNPADDED bf16 kernel, reusable by the rounding pass), but each
    iteration is one HBM sweep of ``K`` instead of two. Promoted after the
    r5 slope head-to-head on TPU v5e measured 1.297 ms/iter fused vs 1.548
    XLA at 262144x1024 (PALLAS_TPU.json, ``pallas_vs_xla: 1.19``).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, m = cost.shape
    cost = cost.astype(jnp.float32)
    a, b = normalize_marginals(row_mass, col_capacity)
    shift = jnp.min(cost, axis=1, keepdims=True)  # per-row gauge, see scaling_core
    shift = jnp.where(jnp.isfinite(shift), shift, 0.0)
    K = jnp.exp(-(cost - shift) / eps).astype(kernel_dtype)

    lane = 128
    n_pad = -(-n // block_rows) * block_rows
    m_pad = -(-m // lane) * lane
    K_p = pad_axis_to(pad_axis_to(K, n_pad, 0, 0.0), m_pad, 1, 0.0)
    a_p = pad_axis_to(a, n_pad, 0, 0.0)
    b_p = pad_axis_to(b, m_pad, 0, 0.0)

    def body(carry, _):
        _, v = carry
        return fused_scaling_iteration(
            K_p, a_p, b_p, v, block_rows=block_rows, interpret=interpret
        ), None

    v0 = pad_axis_to(jnp.ones((m,), jnp.float32), m_pad, 0, 0.0)
    u0 = jnp.zeros((n_pad,), jnp.float32)
    (u, v), _ = lax.scan(body, (u0, v0), None, length=n_iters)
    return u[:n], v[:m], K, shift[:, 0]


# The fused kernel's measured win is HBM-bandwidth reuse, so it only
# applies where K spills far past VMEM; below this element count the XLA
# loop is already cache/VMEM-resident and the pallas grid overhead loses.
_FUSED_MIN_ELEMS = 1 << 24  # 32 MB of bf16 K
# ...and only at WIDE column counts. The r5 TPU A/B: at m=1024 the fused
# kernel wins (1.19x by iteration slope at 262144x1024; 275.7 -> 226.8 ms
# single-call and 212.1 -> 204.6 ms chained at 1Mx1024), but at m=256 it
# LOSES 2.1x (33.3 -> 71.0 ms chained at 1M) — narrow blocks starve the
# sweep: per-grid-step work shrinks with m while the step count and the
# (1, m) accumulator round trips don't. Dispatch only where measured.
_FUSED_MIN_COLS = 1024


def scaling_impl_for(n: int, m: int, *, block_rows: int = 1024) -> str:
    """Which implementation :func:`scaling_core_auto` picks for (n, m)."""
    if (
        jax.default_backend() == "tpu"
        and n * m >= _FUSED_MIN_ELEMS
        and m >= _FUSED_MIN_COLS
        and n % block_rows == 0
    ):
        return "pallas_fused"
    return "xla"


def scaling_core_auto(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    kernel_dtype=jnp.bfloat16,
    block_rows: int = 1024,
):
    """Backend-aware :func:`scaling_core`: fused Pallas on TPU, XLA else.

    Selection is static per (backend, shape): on TPU with
    ``n*m >= 2**24`` (the bandwidth-bound regime the r5 slope measurement
    covers) the fused kernel runs; everywhere else — host CPUs, small
    problems, and any shape the kernel's row-block padding would inflate
    by >12.5% — the plain XLA loop does. Returns ``(u, v, K, shift)``
    either way.
    """
    n, m = cost.shape
    if scaling_impl_for(n, m, block_rows=block_rows) == "pallas_fused":
        return pallas_scaling_core(
            cost, row_mass, col_capacity, eps=eps, n_iters=n_iters,
            kernel_dtype=kernel_dtype, block_rows=block_rows,
        )
    return scaling_core(
        cost, row_mass, col_capacity, eps=eps, n_iters=n_iters,
        kernel_dtype=kernel_dtype,
    )


def pallas_scaling_sinkhorn(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    kernel_dtype=jnp.bfloat16,
    block_rows: int = 1024,
    interpret: bool | None = None,
) -> SinkhornResult:
    """Fused-kernel scaling Sinkhorn: one HBM sweep of K per iteration.

    Pads objects to a ``block_rows`` multiple (zero mass) and nodes to a
    lane multiple (zero capacity + zero kernel column, so padding attracts
    nothing); padding is sliced off the result.
    """
    u, v, _, shift = pallas_scaling_core(
        cost, row_mass, col_capacity, eps=eps, n_iters=n_iters,
        kernel_dtype=kernel_dtype, block_rows=block_rows, interpret=interpret,
    )
    cost = cost.astype(jnp.float32) - shift[:, None]
    _, b = normalize_marginals(row_mass, col_capacity)
    f, g = _potentials(u, v, eps)
    err = marginal_err(cost, f, g, b, eps)  # shifted-cost/shifted-f pair
    f = jnp.where(jnp.isfinite(f), f + shift, f)  # undo the gauge shift
    return SinkhornResult(f=f, g=g, err=err)
