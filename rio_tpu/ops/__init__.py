"""TPU-side numerical ops for rio-tpu.

The reference (rio-rs) resolves object placement row-by-row through SQL
(``rio-rs/src/object_placement/sqlite.rs:68-100``, consulted per request in
``rio-rs/src/service.rs:193-254``) with no load-balancing policy at all
(random client pick + receiving-server self-assign,
``rio-rs/src/client/mod.rs:255-262``, ``service.rs:241-253``).

rio-tpu recasts placement as a **batched assignment problem** solved
on-device: an (objects x nodes) cost matrix built from liveness + load, an
entropic optimal-transport (Sinkhorn) solve or an iterative penalized-argmin
("greedy") solve, and an assignment extraction that is a single fused
argmin. Everything here is jit-friendly: static shapes, ``lax.scan`` control
flow, bfloat16 matmul paths with float32 log-sum-exp accumulation.
"""

from .assignment import (
    assign_from_potentials,
    build_cost_matrix,
    greedy_balanced_assign,
    integer_fair_quotas,
    residual_capacity_assign,
)
from .pallas_sinkhorn import fused_iteration, pallas_sinkhorn
from .scaling import (
    fused_scaling_iteration,
    pallas_scaling_core,
    pallas_scaling_sinkhorn,
    scaling_core,
    scaling_core_auto,
    scaling_impl_for,
    scaling_sinkhorn,
)
from .sinkhorn import (
    SinkhornResult,
    exact_quota_repair,
    plan_rounded_assign,
    plan_rounded_assign_from_scaling,
    sinkhorn,
    sinkhorn_assign,
)

__all__ = [
    "SinkhornResult",
    "fused_iteration",
    "fused_scaling_iteration",
    "pallas_scaling_core",
    "pallas_scaling_sinkhorn",
    "pallas_sinkhorn",
    "scaling_core",
    "scaling_core_auto",
    "scaling_impl_for",
    "scaling_sinkhorn",
    "assign_from_potentials",
    "build_cost_matrix",
    "greedy_balanced_assign",
    "integer_fair_quotas",
    "residual_capacity_assign",
    "exact_quota_repair",
    "plan_rounded_assign",
    "plan_rounded_assign_from_scaling",
    "sinkhorn",
    "sinkhorn_assign",
]
