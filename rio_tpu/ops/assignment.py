"""Cost-matrix construction and greedy balanced assignment.

The cost model replaces the reference's implicit placement policy (random
active server on client cache miss, ``rio-rs/src/client/mod.rs:255-262``;
unconditional self-assign on the receiving server,
``rio-rs/src/service.rs:241-253``) with an explicit objective:

  cost[i, j] = load_penalty * (node_load[j] / capacity[j])
             + affinity_penalty * (1 - affinity[i, j])
             + BIG * (1 - alive[j])

Dead nodes are priced out rather than masked so the matrix keeps a static
shape (cluster size changes do not recompile).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

DEAD_NODE_COST = 1e6


def integer_fair_quotas(cap_alive: np.ndarray, n: int) -> np.ndarray:
    """Largest-remainder integer fair shares of ``n`` seats (host numpy).

    The delta-rebalance counterpart of the device-side quota math inside
    :func:`rio_tpu.ops.sinkhorn.exact_quota_repair`: per-node quotas
    proportional to schedulable capacity, floors plus one bonus unit for
    the ``n - sum(floors)`` largest remainders, summing to ``n`` EXACTLY.
    Same invariant as the device repair: NO global rescale of the raw
    shares (an fp rescale flips floor/remainder units on exact-integer
    columns at large scale; see the r4 note there). Zero-capacity nodes
    get zero share and zero quota.
    """
    cap = np.maximum(np.asarray(cap_alive, np.float64), 0.0)
    total = cap.sum()
    if n <= 0 or total <= 0.0:
        return np.zeros(cap.shape[0], np.int64)
    target = cap / total * n
    quota = np.floor(target).astype(np.int64)
    short = n - int(quota.sum())
    if short > 0:
        # Remainder ties prefer the higher-capacity column (deterministic,
        # and a bonus unit belongs where it displaces least).
        rem_order = np.lexsort((-cap, -(target - quota)))
        quota[rem_order[:short]] += 1
    return quota


def residual_capacity_assign(
    score: np.ndarray, residual: np.ndarray
) -> np.ndarray:
    """Seat D displaced objects into integer residual quotas (host numpy).

    ``residual[j]`` is node j's remaining quota after undisplaced objects
    kept their seats (``sum(residual)`` must equal the displaced count);
    ``score[j]`` orders the fill (typically ``base_cost - g`` with the
    warm-started node potentials, so the cheapest nodes absorb first).
    Objects within the displaced set are interchangeable under the flat
    cost model — every feasible fill has identical transport cost — so
    laying them out as contiguous per-node runs is exact, O(D), and
    deterministic. Returns (D,) int32 node indices.
    """
    residual = np.asarray(residual, np.int64)
    order = np.argsort(np.asarray(score, np.float64), kind="stable")
    return np.repeat(order, residual[order]).astype(np.int32)


def build_cost_matrix(
    node_load: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    affinity: jax.Array | None = None,
    *,
    load_weight: float = 1.0,
    affinity_weight: float = 0.25,
) -> jax.Array:
    """(n_objects x n_nodes) cost from liveness + relative load (+ affinity).

    Args:
      node_load: (n_nodes,) current absorbed load per node.
      node_capacity: (n_nodes,) capacity per node (0 for retired slots).
      alive: (n_nodes,) 1.0 if the member is active (gossip liveness,
        reference ``peer_to_peer.rs:101-112``), else 0.0.
      affinity: optional (n_objects, n_nodes) in [0, 1]; 1 = strongly prefer
        (e.g. state locality / cache warmth). If None, costs are per-node
        only and the result is broadcast to (1, n_nodes).
    """
    cap = jnp.maximum(node_capacity.astype(jnp.float32), 1e-6)
    per_node = load_weight * (node_load.astype(jnp.float32) / cap)
    per_node = per_node + DEAD_NODE_COST * (1.0 - alive.astype(jnp.float32))
    if affinity is None:
        return per_node[None, :]
    aff = affinity_weight * (1.0 - affinity.astype(jnp.float32))
    return per_node[None, :] + aff


def assign_from_potentials(cost_rows: jax.Array, g: jax.Array) -> jax.Array:
    """Incremental placement: argmin_j cost[i,j] - g[j] with cached potentials.

    This is the warm-start fast path — new/churned objects are placed against
    the last solve's node potentials without re-running Sinkhorn.
    """
    g = jnp.where(jnp.isfinite(g), g, -jnp.inf)
    return jnp.argmin(cost_rows.astype(jnp.float32) - g[None, :], axis=1).astype(jnp.int32)


@jax.jit
def rank_within_group(
    keys: jax.Array, group_keys: jax.Array | None = None
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-element rank among equal group keys, via one stable sort.

    Elements are ordered by a stable sort of ``keys``; ranks count within
    runs of equal ``group_keys`` (default: ``keys`` themselves — pass a
    composite sort key plus separate group keys to control ordering WITHIN
    each group, e.g. preferred-first eviction). Returns
    ``(order, sorted_group_keys, rank_sorted)``. Shared by the churn-aware
    greedy rebalance (keep-within-fair-share) and the exact quota repair
    (keep-within-quota) — the scan is subtle enough that one copy is plenty.
    """
    order = jnp.argsort(keys, stable=True)
    sorted_groups = (keys if group_keys is None else group_keys)[order]
    pos = jnp.arange(keys.shape[0])
    is_start = jnp.concatenate(
        [jnp.ones((1,), bool), sorted_groups[1:] != sorted_groups[:-1]]
    )
    group_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, pos, 0)
    )
    return order, sorted_groups, (pos - group_start).astype(jnp.int32)


@jax.jit
def greedy_balanced_assign(
    cost: jax.Array,
    row_mass: jax.Array,
    node_capacity: jax.Array,
    node_load: jax.Array | None = None,
) -> jax.Array:
    """Capacity-proportional waterfilling: the cheap balanced-assignment tier.

    Nodes are sorted by their (column-mean) cost; each node absorbs mass up to
    its *headroom* — the gap between its capacity-fair share of the total
    (existing + incoming) load and its current load. Objects are laid onto
    this sorted partition by cumulative-mass position (``searchsorted``), so
    the result is exactly capacity-balanced, deterministic, and free of the
    oscillation/herding failure modes of simultaneous penalized argmin.
    Zero-capacity (dead) nodes get zero-width intervals and are never chosen.

    Per-object affinity is intentionally ignored here — this tier trades
    placement quality for a single O(N log M) pass; the Sinkhorn tier
    (:func:`rio_tpu.ops.sinkhorn.sinkhorn_assign`) honors full per-object
    costs.
    """
    cost = cost.astype(jnp.float32)
    mass = jnp.maximum(row_mass.astype(jnp.float32), 0.0)
    cap = jnp.maximum(node_capacity.astype(jnp.float32), 0.0)
    n_nodes = cost.shape[1]
    load = (
        jnp.zeros((n_nodes,), jnp.float32)
        if node_load is None
        else node_load.astype(jnp.float32)
    )

    total_mass = jnp.sum(mass)
    cap_share = cap / jnp.maximum(jnp.sum(cap), 1e-30)
    fair = (total_mass + jnp.sum(load)) * cap_share
    headroom = jnp.maximum(fair - load, 0.0)
    # If the cluster is already at/over fair everywhere, fall back to pure
    # capacity shares so the incoming batch still spreads proportionally.
    total_headroom = jnp.sum(headroom)
    width = jnp.where(total_headroom > 1e-30, headroom, cap_share * total_mass)
    # Scale widths to cover exactly the incoming mass (overflow spreads pro rata).
    width = width * (total_mass / jnp.maximum(jnp.sum(width), 1e-30))

    score = jnp.mean(cost, axis=0) + DEAD_NODE_COST * (cap <= 0)
    order = jnp.argsort(score)
    boundaries = jnp.cumsum(width[order])
    # Mid-mass position of each object avoids boundary ties on zero-width bins.
    pos = jnp.cumsum(mass) - 0.5 * mass
    idx = jnp.clip(jnp.searchsorted(boundaries, pos, side="left"), 0, n_nodes - 1)
    return order[idx].astype(jnp.int32)
