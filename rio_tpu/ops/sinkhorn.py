"""Log-domain Sinkhorn (entropic optimal transport) for object placement.

Solves ``min_P <C, P> - eps * H(P)`` subject to ``P @ 1 = a`` (each object
carries its load mass) and ``P.T @ 1 = b`` (each node absorbs mass up to its
capacity share). The optimal plan is ``P = exp((f + g - C) / eps)`` for dual
potentials ``f`` (objects) and ``g`` (nodes); the hard assignment for object
``i`` is ``argmin_j C[i, j] - g[j]`` — it depends on the *node* potentials
only, which is what makes warm-started incremental placement cheap: a new
object needs one cost row and one argmin against the cached ``g``.

TPU notes:
- iterations run under ``lax.scan`` (one traced body, no Python loop);
- all reductions are float32 log-sum-exp (stable in bf16-heavy pipelines);
- shapes are static; callers pad the object axis to a bucket size so XLA
  compiles once per bucket, not once per batch.

This replaces the reference's per-request SQL lookup/self-assign policy
(``rio-rs/src/service.rs:193-254``) with a batched on-device solve; the
``ObjectPlacement`` trait boundary (``rio-rs/src/object_placement/mod.rs:39-56``)
is preserved by :class:`rio_tpu.object_placement.jax_placement.JaxObjectPlacement`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax


class SinkhornResult(NamedTuple):
    """Dual potentials and diagnostics from a Sinkhorn solve."""

    f: jax.Array  # (n_objects,) object potentials, float32
    g: jax.Array  # (n_nodes,) node potentials, float32
    err: jax.Array  # scalar: final L1 column-marginal violation


_NEG_INF = float("-inf")


def _safe_log(x: jax.Array) -> jax.Array:
    return jnp.log(jnp.maximum(x, 1e-30))


def normalize_marginals(row_mass: jax.Array, col_capacity: jax.Array):
    """Scale both marginals to unit total mass (float32)."""
    a = row_mass.astype(jnp.float32)
    b = col_capacity.astype(jnp.float32)
    a = a / jnp.maximum(jnp.sum(a), 1e-30)
    b = b / jnp.maximum(jnp.sum(b), 1e-30)
    return a, b


def marginal_err(cost: jax.Array, f: jax.Array, g: jax.Array, b: jax.Array, eps: float) -> jax.Array:
    """L1 column-marginal violation of the implied plan (diagnostic)."""
    log_p = (f[:, None] + g[None, :] - cost.astype(jnp.float32)) / eps
    col = jnp.sum(jnp.exp(jnp.where(jnp.isfinite(log_p), log_p, -jnp.inf)), axis=0)
    return jnp.sum(jnp.abs(col - b))


def pad_axis_to(x: jax.Array, size: int, axis: int, fill: float) -> jax.Array:
    """Pad ``x`` along ``axis`` up to ``size`` with ``fill`` (no-op if equal)."""
    pad = size - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


def sinkhorn(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    g_init: jax.Array | None = None,
) -> SinkhornResult:
    """Run ``n_iters`` log-domain Sinkhorn iterations.

    Args:
      cost: (n_objects, n_nodes) cost matrix (any float dtype; accumulated f32).
      row_mass: (n_objects,) per-object mass (e.g. normalized load); rows with
        zero mass are padding and are ignored.
      col_capacity: (n_nodes,) per-node capacity share; columns with zero
        capacity (dead nodes) receive -inf potential and attract nothing.
      eps: entropic regularizer. Smaller = sharper assignment, slower
        convergence; 0.02-0.1 of the cost scale works well.
      n_iters: fixed iteration count (static for ``lax.scan``).
      g_init: optional (n_nodes,) warm-start node potentials from a previous
        solve (e.g. the cached ``g`` of an incremental rebalance). Only the
        FIRST f-update consumes it — the g-update recomputes g fully each
        iteration — so a good seed buys convergence in a handful of
        iterations while a stale one costs nothing but those iterations.
        Non-finite entries (dead columns from the previous solve) are
        treated as cold (0); the column marginals of THIS solve decide
        liveness, never the seed.
    """
    cost = cost.astype(jnp.float32)
    a, b = normalize_marginals(row_mass, col_capacity)
    log_a = jnp.where(a > 0, _safe_log(a), -jnp.inf)
    log_b = jnp.where(b > 0, _safe_log(b), -jnp.inf)

    def body(carry, _):
        f, g = carry
        # f-update: f_i = eps*(log a_i - LSE_j((g_j - C_ij)/eps))
        f = eps * (log_a - jax.nn.logsumexp((g[None, :] - cost) / eps, axis=1))
        f = jnp.where(jnp.isfinite(log_a), f, -jnp.inf)
        # g-update: g_j = eps*(log b_j - LSE_i((f_i - C_ij)/eps))
        g = eps * (log_b - jax.nn.logsumexp((f[:, None] - cost) / eps, axis=0))
        g = jnp.where(jnp.isfinite(log_b), g, -jnp.inf)
        return (f, g), None

    f0 = jnp.zeros(cost.shape[0], jnp.float32)
    if g_init is None:
        g0 = jnp.zeros(cost.shape[1], jnp.float32)
    else:
        g0 = jnp.where(
            jnp.isfinite(g_init), g_init.astype(jnp.float32), 0.0
        )
    (f, g), _ = lax.scan(body, (f0, g0), None, length=n_iters)
    return SinkhornResult(f=f, g=g, err=marginal_err(cost, f, g, b, eps))


@jax.jit
def plan_rounded_assign(cost: jax.Array, f: jax.Array, g: jax.Array, eps: float = 0.05) -> jax.Array:
    """Capacity-aware hard rounding of the soft transport plan.

    Row-argmax rounding of ``P = exp((f+g-C)/eps)`` collapses under cost
    ties (every identical row picks the same node, violating capacity).
    Instead, object ``i`` inverts its row's CDF at the deterministic quantile
    ``(i+0.5)/n``: aggregate node loads then match the plan's column
    marginals — i.e. capacities — while identical rows spread contiguously.
    Padding rows (``f = -inf``) fall back to the plan-uniform distribution of
    live columns; callers slice them off. Quantiles are taken over the *real*
    rows only — ranking by position among finite-``f`` rows — so bucket
    padding never skews the spread toward low-cumulative nodes.
    """
    cost = cost.astype(jnp.float32)
    is_real = jnp.isfinite(f)
    logit = (f[:, None] + g[None, :] - cost) / eps
    alive_cols = jnp.isfinite(g)
    logit = jnp.where(
        is_real[:, None],
        logit,
        jnp.where(alive_cols[None, :], 0.0, -jnp.inf),
    )
    p = jax.nn.softmax(logit, axis=1)
    cum = jnp.cumsum(p, axis=1)
    realf = is_real.astype(jnp.float32)
    n_real = jnp.maximum(jnp.sum(realf), 1.0)
    rank = jnp.cumsum(realf) - 1.0  # 0..n_real-1 over real rows
    u = jnp.where(is_real, (rank + 0.5) / n_real, 0.5)
    idx = jnp.sum((cum < u[:, None]).astype(jnp.int32), axis=1)
    return jnp.clip(idx, 0, cost.shape[1] - 1).astype(jnp.int32)


@jax.jit
def plan_rounded_assign_from_scaling(
    K: jax.Array, u: jax.Array, v: jax.Array
) -> jax.Array:
    """:func:`plan_rounded_assign`, but from the scaling-form state.

    The soft plan is ``P = diag(u) K diag(v)`` with ``K = exp(-C'/eps)``
    already materialized by :func:`rio_tpu.ops.scaling.scaling_core` —
    mathematically the same ``exp((f+g-C)/eps)`` the potential form
    exponentiates, so the CDF-inversion rounding below is identical up to
    kernel dtype. Reading the (usually bfloat16) ``K`` instead of the
    float32 cost halves the rounding pass's HBM traffic and removes its
    transcendental sweep — it is the difference between the solve fitting
    the <50 ms class at 1M x 1k or not.

    Padding rows (``u == 0``) spread uniformly over live columns
    (``v > 0``), exactly as the potential-form rounding treats ``f=-inf``.
    """
    u = u.astype(jnp.float32)
    v = v.astype(jnp.float32)
    is_real = u > 0
    alive = (v > 0).astype(jnp.float32)
    p = u[:, None] * K.astype(jnp.float32) * v[None, :]
    p = jnp.where(is_real[:, None], p, alive[None, :])
    # Row-normalize through the cumulative sum: invert each row's CDF at
    # the object's deterministic quantile among REAL rows (plan marginals
    # match capacities, identical rows spread contiguously).
    cum = jnp.cumsum(p, axis=1)
    total = jnp.maximum(cum[:, -1:], 1e-30)
    realf = is_real.astype(jnp.float32)
    n_real = jnp.maximum(jnp.sum(realf), 1.0)
    rank = jnp.cumsum(realf) - 1.0
    q = jnp.where(is_real, (rank + 0.5) / n_real, 0.5)
    idx = jnp.sum((cum < q[:, None] * total).astype(jnp.int32), axis=1)
    return jnp.clip(idx, 0, K.shape[1] - 1).astype(jnp.int32)


@jax.jit
def exact_quota_repair(
    idx: jax.Array,
    expected_counts: jax.Array,
    prefer_keep: jax.Array | None = None,
) -> jax.Array:
    """Make a rounded assignment match integer column quotas EXACTLY.

    CDF-inversion rounding matches the soft plan's column marginals only in
    expectation — per-column counts carry ~sqrt(fair) binomial noise, so the
    max load overshoots fair share by ~3 sigma (measured +33% at fair=128).
    This repair computes integer quotas from the soft marginals (largest-
    remainder method), KEEPS every object whose column is within quota
    (within-column rank < quota), and re-slots only the excess into the
    under-quota columns — the minimal move set (~the total overshoot, a
    few percent), not a global re-slotting. Zero-expected (dead) columns
    get zero quota and end up empty.

    Args:
      idx: (n,) int32 initial assignment (e.g. from plan rounding).
      expected_counts: (m,) float expected objects per column (soft column
        marginals x n); must sum to ~n.
      prefer_keep: optional (n,) bool — objects to evict LAST from an
        over-quota column. A churn re-solve passes "rounded to its current
        seat", so quota eviction lands on objects that were moving anyway
        and the repair adds ~zero extra churn.
    """
    from .assignment import rank_within_group

    n = idx.shape[0]
    m = expected_counts.shape[0]
    counts = jnp.bincount(idx, length=m)
    scaled = jnp.maximum(expected_counts.astype(jnp.float32), 0.0)
    # NO global rescale to sum-n here: multiplying every column by
    # n/sum(scaled) perturbs each by the fp32 summation error, and at
    # 2^24-scale totals that flips floor/remainder units on EXACT-integer
    # columns — observed r4 as a padding-sentinel column whose quota came
    # out one above the padding count, seating a real object on a
    # non-node. Raw marginals keep integer columns' floors exact (their
    # remainder is 0, so they never draw a largest-remainder bonus), and
    # the integer shortfall below absorbs caller drift exactly. The clip
    # guards the documented "sums to ~n" contract: a wildly undershooting
    # caller now underfills (refill clamps) instead of being silently
    # renormalized.
    base = jnp.floor(scaled).astype(jnp.int32)
    rem = scaled - base
    short = jnp.clip(n - jnp.sum(base), 0, m)
    # Largest remainders get the leftover units; remainder ties prefer the
    # MORE-occupied column (awarding a tied bonus to an empty column would
    # displace a seated object for no quota reason — churn, not repair).
    rem_order = jnp.lexsort((-counts, -rem))
    bonus = (
        jnp.zeros((m,), jnp.int32)
        .at[rem_order]
        .set((jnp.arange(m) < short).astype(jnp.int32))
    )
    quota = base + bonus

    # Within-column rank via one stable sort (shared with the greedy
    # churn-aware rebalance): keep iff rank < quota[column]. With a
    # prefer_keep mask, sort by (column, not-preferred) so preferred
    # objects take the low ranks — eviction order is preferred-last.
    if prefer_keep is None:
        order, sorted_idx, rank = rank_within_group(idx)
    else:
        composite = idx.astype(jnp.int32) * 2 + (
            1 - prefer_keep.astype(jnp.int32)
        )
        order, sorted_idx, rank = rank_within_group(composite, idx)
    keep = rank < quota[sorted_idx]

    # Excess objects fill the under-quota columns in cumulative order.
    deficit = jnp.maximum(quota - counts, 0)
    bounds = jnp.cumsum(deficit)
    disp_rank = jnp.cumsum((~keep).astype(jnp.int32)) - 1
    refill = jnp.clip(
        jnp.searchsorted(bounds, disp_rank, side="right"), 0, m - 1
    )
    col_sorted = jnp.where(keep, sorted_idx, refill.astype(idx.dtype))
    return jnp.zeros_like(idx).at[order].set(col_sorted)


def route_sentinel_spill(
    idx: jax.Array, is_real: jax.Array, sentinel: int, capacity: jax.Array
) -> jax.Array:
    """Reseat real rows that quota repair left on the padding sentinel.

    Bucket-shaped solves route padding rows through a sentinel column
    (index ``sentinel``) whose quota is the padding count. Two drifts can
    seat a REAL row there instead: a float32 largest-remainder quota one
    unit above the padding count (observed r4 at the 2^24 bucket boundary
    — the root fix in :func:`exact_quota_repair` keeps integer columns
    exact, so this is belt-and-braces for callers whose expected marginals
    are not exact integers), and the repair refill's clip spilling into
    the last column when caller marginals undershoot. Downstream index
    lookups would otherwise crash (flat path) or silently clamp onto a
    possibly-dead neighbor (``take_along_axis`` in the hierarchical fine
    stage). The drift is at most a unit or two, so reseating spilled rows
    on the highest-capacity live column preserves balance within that
    drift. ONE implementation shared by every bucket-shaped caller
    (``JaxObjectPlacement`` and the hierarchical fine stage) — the guard
    semantics must never diverge between solvers.

    Args:
      idx: (n,) int32 assignment after quota repair.
      is_real: (n,) bool — real rows (padding rows keep the sentinel; they
        are dropped or sliced off by the caller).
      sentinel: first non-column index; anything >= it is a spill.
      capacity: (m,) effective capacity (zero on dead columns) used to
        pick the fallback seat.
    """
    spill = is_real & (idx >= sentinel)
    fallback = jnp.argmax(capacity).astype(idx.dtype)
    return jnp.where(spill, fallback, idx)


def sinkhorn_assign(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
) -> tuple[jax.Array, SinkhornResult]:
    """Solve and extract hard assignments ``argmin_j C[i,j] - g[j]``.

    Returns (assignment (n_objects,) int32, SinkhornResult). Dead nodes
    (zero capacity) are never chosen because their ``g`` is -inf.
    """
    res = sinkhorn(cost, row_mass, col_capacity, eps=eps, n_iters=n_iters)
    g = jnp.where(jnp.isfinite(res.g), res.g, -jnp.inf)
    assignment = jnp.argmin(cost.astype(jnp.float32) - g[None, :], axis=1)
    return assignment.astype(jnp.int32), res
