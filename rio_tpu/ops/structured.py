"""Class-collapsed rebalance solve: exact Sinkhorn at O(M^2), not O(N*M).

The framework's own full-rebalance cost model (``JaxObjectPlacement``) is

    cost[i, j] = base[j] - move_cost * [j == cur_i]

— a per-node vector broadcast plus a stay-put discount on the current
seat. Every object with the same current seat therefore has an IDENTICAL
cost row, and Sinkhorn's row updates depend on rows only through their
values: the (N objects x M nodes) solve collapses *exactly* to an
(M classes x M nodes) solve with row masses equal to the per-seat object
counts. N drops out of the device problem entirely:

* solve: O(M^2) per iteration (1k x 1k is ~1M cells — microseconds on the
  MXU, trivially within BASELINE.md's <50 ms class for ANY N);
* apply: integer per-class quotas (largest-remainder rounding, exact row
  sums) then an O(N) host scatter that keeps ``quota[k, k]`` objects in
  place — objects within a class are interchangeable, so keeping any
  ``quota_kk`` of them is the move-minimal application.

The dense solvers (:mod:`rio_tpu.ops.sinkhorn`, :mod:`rio_tpu.ops.scaling`)
remain the general path for per-object costs (hierarchical affinity
features, external cost matrices); this module is the fast path the
directory uses when no per-object signal exists. The reference has no
counterpart at all — its "rebalance" is never (placement is
write-once-until-death row-by-row SQL, ``object_placement/sqlite.rs``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .sinkhorn import sinkhorn

__all__ = ["class_quotas", "expand_class_quotas"]


@functools.partial(jax.jit, static_argnames=("eps", "n_iters"))
def class_quotas(
    base_cost: jax.Array,
    counts: jax.Array,
    col_capacity: jax.Array,
    *,
    move_cost: float = 0.5,
    eps: float = 0.05,
    n_iters: int = 30,
    g_init: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Integer per-class quotas for the collapsed rebalance problem.

    Args:
      base_cost: (M,) per-node cost (load/liveness pricing; dead nodes at
        ``DEAD_NODE_COST``).
      counts: (M,) objects currently seated on each node class (float32 or
        int; class k = "objects whose current seat is node k").
      col_capacity: (M,) effective capacity (0 for dead nodes).
      move_cost: stay-put discount applied on the diagonal.
      g_init: optional (M,) warm-start node potentials from the previous
        solve (the delta-rebalance path feeds the cached plan potentials
        back in, so a churn re-solve converges in a handful of iterations).

    Returns:
      (quotas, g, err): quotas is (M, M) int32 where ``quotas[k, j]``
      objects of class k should end on node j — every row sums EXACTLY to
      ``counts[k]``; ``g`` is the (M,) node potential from the class solve
      (seed for the incremental warm-start path); ``err`` is the solve's
      scalar final L1 column-marginal violation (the convergence residual
      ``SolveStats`` surfaces).
    """
    m = base_cost.shape[0]
    counts = counts.astype(jnp.float32)
    cost = jnp.broadcast_to(base_cost.astype(jnp.float32)[None, :], (m, m))
    cost = cost - move_cost * jnp.eye(m, dtype=jnp.float32)
    res = sinkhorn(
        cost, counts, col_capacity, eps=eps, n_iters=n_iters, g_init=g_init
    )

    # Soft plan row-conditionals: P[k, :] / a_k (finite rows only).
    logit = (res.f[:, None] + res.g[None, :] - cost) / eps
    live_row = jnp.isfinite(res.f)
    logit = jnp.where(live_row[:, None], logit, -jnp.inf)
    frac = jax.nn.softmax(logit, axis=1)
    frac = jnp.where(live_row[:, None], frac, 0.0)
    # Belt-and-braces: zero out dead columns (their g is already -inf, but
    # largest-remainder must never hand a stray unit to a dead node) and
    # renormalize live rows.
    frac = jnp.where((col_capacity > 0)[None, :], frac, 0.0)
    frac = frac / jnp.maximum(jnp.sum(frac, axis=1, keepdims=True), 1e-30)
    frac = jnp.where(live_row[:, None], frac, 0.0)

    # Largest-remainder rounding to exact integer row sums.
    target = frac * counts[:, None]
    base = jnp.floor(target)
    short = (counts - jnp.sum(base, axis=1)).astype(jnp.int32)  # (M,)
    remainder = target - base
    # Rank remainders descending per row (rank[k, j] = position of column j
    # in row k's descending-remainder order); give one extra unit to the
    # top ``short[k]`` columns of each row.
    order = jnp.argsort(-remainder, axis=1)
    rank = jnp.zeros_like(order).at[
        jnp.arange(m)[:, None], order
    ].set(jnp.broadcast_to(jnp.arange(m)[None, :], (m, m)))
    quotas = (base + (rank < short[:, None])).astype(jnp.int32)
    return quotas, res.g, res.err


@jax.jit
def expand_class_quotas(quotas: jax.Array, cur: jax.Array) -> jax.Array:
    """Expand (M x M) class quotas into a per-object assignment ON DEVICE.

    The device counterpart of the host expansion
    (``jax_placement._apply_class_quotas``) with identical semantics:
    within class k (objects whose current seat is node k, ordered by their
    stable per-class rank) the first ``quotas[k, k]`` objects stay put,
    the rest fill the remaining columns in index order — the move-minimal
    application of :func:`class_quotas`.  Keeping this step on device turns
    the whole collapsed-rebalance decision (counts -> class solve ->
    expansion -> exact repair) into one XLA pipeline with a single 4-byte/row
    host pull at the end: O(N log N) sort + O(N log M) binary search, no
    (N x M) materialization anywhere.

    Args:
      quotas: (M, M) int32, rows summing exactly to per-class counts.
      cur: (B,) int32 current seats, padding rows AFTER the real rows (the
        provider pads with zeros; stable ranking keeps real class-0 ranks
        unaffected).  Padding rows whose rank exceeds their class count get
        a clamped, meaningless target — callers mask them (the provider's
        exact repair overrides padding with a sentinel column).

    Returns:
      (B,) int32 target node per object.
    """
    m = quotas.shape[0]
    cols = jnp.arange(m, dtype=jnp.int32)
    # Diag-first column order per row: [k, 0, 1, ..., k-1, k+1, ..., M-1].
    key = jnp.where(cols[None, :] == cols[:, None], -1, cols[None, :])
    colorder = jnp.argsort(key, axis=1).astype(jnp.int32)
    q_re = jnp.take_along_axis(quotas, colorder, axis=1)
    cum = jnp.cumsum(q_re, axis=1)  # inclusive; cum[k, -1] == counts[k]

    from .assignment import rank_within_group

    order, _, rank_sorted = rank_within_group(cur)
    rank = jnp.zeros_like(cur).at[order].set(rank_sorted)

    # Per-object binary search: smallest j with cum[cur_i, j] > rank_i
    # (searchsorted side='right'), as log2(M) elementwise gathers instead
    # of gathering (B, M) rows (4 GB at 1M x 1k).
    lo = jnp.zeros_like(cur)
    hi = jnp.full_like(cur, m)
    n_steps = max(1, (m + 1).bit_length())

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        go_right = cum[cur, mid] <= rank
        return jnp.where(go_right, mid + 1, lo), jnp.where(go_right, hi, mid)

    lo, hi = jax.lax.fori_loop(0, n_steps, body, (lo, hi))
    jpos = jnp.clip(lo, 0, m - 1)
    return colorder[cur, jpos]
