"""Fused single-pass Sinkhorn iteration as a Pallas TPU kernel.

One entropic-OT iteration needs two reductions over the (objects x nodes)
cost matrix: a row log-sum-exp of ``(g - C)/eps`` (the ``f`` update) and a
column log-sum-exp of ``(f_new - C)/eps`` (the ``g`` update). Expressed in
plain XLA that is two full HBM sweeps of ``C`` per iteration — and at the
BASELINE scale (1M x 1k, 4 GB fp32) the solve is purely HBM-bandwidth
bound.

This kernel fuses both updates into ONE sweep: the grid walks row blocks;
each step (a) computes the block's ``f`` from the previous ``g`` and
(b) immediately folds the block's contribution into an *online* column
log-sum-exp (running max + rebased sum in VMEM scratch, the
flash-attention accumulation pattern). The final grid step materializes the
new ``g``. Net effect: half the HBM traffic of the unfused solve, which is
a ~2x iteration speedup where it matters.

Falls back to interpreter mode off-TPU so the CPU test mesh exercises the
same code path. Semantics match :func:`rio_tpu.ops.sinkhorn.sinkhorn`
(same math, same -inf conventions for padding rows / dead nodes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .sinkhorn import (
    SinkhornResult,
    _safe_log,
    marginal_err,
    normalize_marginals,
    pad_axis_to,
)

_NEG_INF = float("-inf")  # also the kernel-side padding convention


def _iteration_kernel(
    log_a_ref,  # (B, 1) block of row log-marginals
    log_b_ref,  # (1, M) full column log-marginals
    g_ref,      # (1, M) previous node potentials
    cost_ref,   # (B, M) cost block
    eps_ref,    # (1, 1) SMEM scalar
    f_out_ref,  # (B, 1) new row potentials for this block
    g_out_ref,  # (1, M) new node potentials (written on the last step)
    m_acc,      # (1, M) VMEM scratch: running column max
    s_acc,      # (1, M) VMEM scratch: running rebased column sum
):
    step = pl.program_id(0)
    eps = eps_ref[0, 0]
    cost = cost_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)  # (1, M)
    log_a = log_a_ref[:].astype(jnp.float32)  # (B, 1)

    @pl.when(step == 0)
    def _init():
        m_acc[:] = jnp.full_like(m_acc[:], _NEG_INF)
        s_acc[:] = jnp.zeros_like(s_acc[:])

    # ---- f update for this row block: row LSE of (g - C)/eps -------------
    z = (g - cost) / eps  # (B, M), g broadcast over rows
    zmax = jnp.max(z, axis=1, keepdims=True)  # (B, 1)
    zsafe = jnp.where(jnp.isfinite(zmax), zmax, 0.0)
    zsum = jnp.sum(jnp.exp(z - zsafe), axis=1, keepdims=True)
    row_lse = zsafe + jnp.log(jnp.maximum(zsum, 1e-30))
    f = eps * (log_a - row_lse)  # (B, 1)
    f = jnp.where(jnp.isfinite(log_a), f, _NEG_INF)
    f_out_ref[:] = f

    # ---- online column LSE of (f - C)/eps --------------------------------
    w = (f - cost) / eps  # (B, M), f broadcast over columns
    bmax = jnp.max(w, axis=0, keepdims=True)  # (1, M)
    new_m = jnp.maximum(m_acc[:], bmax)
    msafe = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    # Rebase the running sum onto the new max; -inf old max means zero sum.
    rebase = jnp.where(
        jnp.isfinite(m_acc[:]), jnp.exp(m_acc[:] - msafe), 0.0
    )
    block_sum = jnp.sum(jnp.exp(w - msafe), axis=0, keepdims=True)
    s_acc[:] = s_acc[:] * rebase + block_sum
    m_acc[:] = new_m

    # ---- finalize g on the last grid step --------------------------------
    @pl.when(step == pl.num_programs(0) - 1)
    def _finalize():
        log_b = log_b_ref[:].astype(jnp.float32)
        msafe_f = jnp.where(jnp.isfinite(m_acc[:]), m_acc[:], 0.0)
        col_lse = msafe_f + jnp.log(jnp.maximum(s_acc[:], 1e-30))
        g_new = eps * (log_b - col_lse)
        g_out_ref[:] = jnp.where(jnp.isfinite(log_b), g_new, _NEG_INF)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def fused_iteration(
    cost: jax.Array,
    log_a: jax.Array,
    log_b: jax.Array,
    g: jax.Array,
    eps: jax.Array,
    *,
    block_rows: int = 512,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array]:
    """One fused Sinkhorn iteration: returns (f_new, g_new).

    ``cost`` is (N, M) with N divisible by ``block_rows`` and M a multiple
    of 128 (callers pad; see :func:`pallas_sinkhorn`).
    """
    n, m = cost.shape
    assert n % block_rows == 0, (n, block_rows)
    grid = (n // block_rows,)
    eps_arr = jnp.asarray(eps, jnp.float32).reshape(1, 1)
    f, g_new = pl.pallas_call(
        _iteration_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, m), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, m), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, m), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, m), jnp.float32),
            pltpu.VMEM((1, m), jnp.float32),
        ],
        interpret=interpret,
    )(log_a.reshape(n, 1), log_b.reshape(1, m), g.reshape(1, m), cost, eps_arr)
    return f.reshape(n), g_new.reshape(m)


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def pallas_sinkhorn(
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    block_rows: int = 512,
    interpret: bool | None = None,
) -> SinkhornResult:
    """Drop-in for :func:`rio_tpu.ops.sinkhorn.sinkhorn` using the fused
    Pallas kernel (single HBM sweep of the cost matrix per iteration).

    Pads the object axis to a ``block_rows`` multiple with zero-mass rows and
    the node axis to a 128 multiple with zero-capacity columns; padding never
    influences live potentials (-inf marginals contribute nothing to either
    log-sum-exp) and is sliced off the result.
    """
    if interpret is None:
        interpret = not _on_tpu()
    n, m = cost.shape
    cost = cost.astype(jnp.float32)
    a, b = normalize_marginals(row_mass, col_capacity)
    log_a = jnp.where(a > 0, _safe_log(a), -jnp.inf)
    log_b = jnp.where(b > 0, _safe_log(b), -jnp.inf)

    n_pad = -(-n // block_rows) * block_rows
    m_pad = -(-m // 128) * 128
    cost_p = pad_axis_to(pad_axis_to(cost, n_pad, 0, 0.0), m_pad, 1, 0.0)
    log_a_p = pad_axis_to(log_a, n_pad, 0, _NEG_INF)
    log_b_p = pad_axis_to(log_b, m_pad, 0, _NEG_INF)

    eps_arr = jnp.float32(eps)

    def body(carry, _):
        _, g = carry
        f, g_new = fused_iteration(
            cost_p, log_a_p, log_b_p, g, eps_arr,
            block_rows=block_rows, interpret=interpret,
        )
        return (f, g_new), None

    f0 = jnp.zeros((n_pad,), jnp.float32)
    # Padding columns must start at -inf, not 0: the first f-update's row
    # LSE would otherwise see phantom zero-cost nodes. Real columns start at
    # 0 even when dead (matching the unfused solve, whose first iteration
    # includes them before their -inf log_b zeroes them out).
    g0 = pad_axis_to(jnp.zeros((m,), jnp.float32), m_pad, 0, _NEG_INF)
    (f, g), _ = lax.scan(body, (f0, g0), None, length=n_iters)

    f = f[:n]
    g = g[:m]
    return SinkhornResult(f=f, g=g, err=marginal_err(cost, f, g, b, eps))
