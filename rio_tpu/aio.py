"""Protocol-based asyncio transport (the default data plane).

``asyncio.StreamReader``'s ``readexactly`` costs two coroutine round trips
per frame plus wakeup/feed machinery; at rio-tpu's frame sizes that was
~30% of the request path.  These ``asyncio.Protocol`` classes do the
framing inline in ``data_received`` (C-backed buffer handling in
:class:`rio_tpu.codec.FrameReader`) and hand complete frame payloads
straight to the dispatch loop — the same event-driven shape as the C++
epoll engine (``native/rio_native.cc``), so both transports share
semantics: per-connection ordered responses, streaming-mode switch on a
subscription request, finish-in-flight on peer EOF.

Concurrency model: handlers for one connection run **concurrently** (each
actor still serializes its own handlers via its per-object lock), responses
leave in exactly the request order — preserved FIFO by flushing completed
head responses from the handler task's done-callback.  That keeps the
reference's no-correlation-id wire contract (``rio-rs/src/protocol.rs``)
intact under client-side pipelining, without a per-connection writer task.

Reference: the tokio frame loop this replaces is
``rio-rs/src/service.rs:370-459`` (server) and ``client/mod.rs:199-220``
(client framed streams).
"""

from __future__ import annotations

import asyncio
import logging
import os
from collections import deque
from time import perf_counter as _perf
from typing import TYPE_CHECKING, Callable

from .codec import FrameReader
from .errors import Disconnect, SerializationError
from .message_router import MessageRouter
from .spans import Phases, finish_request
from .protocol import (
    CommandEnvelope,
    RequestEnvelope,
    ResponseEnvelope,
    ResponseError,
    SubscriptionRequest,
    SubscriptionResponse,
    UnknownFrameKind,
    decode_inbound,
    encode_response_frame,
    encode_subresponse_frame,
)

if TYPE_CHECKING:
    from .service import Service

log = logging.getLogger("rio_tpu.aio")

# Batch-decode (data-plane ladder rung 1): deserialize every complete frame
# of a data_received burst in one tight pass over the cached codec schemas,
# instead of alternating decode / dispatch-bookkeeping per frame in the
# worker loop. Module global (not per-instance) so the bench can A/B it
# in-session; measured +4-6% under pipelining on the r6 capture.
_BATCH_DECODE = os.environ.get("RIO_TPU_BATCH_DECODE", "1") != "0"

# Egress coalescing (the outbound mirror of batch decode): frames produced
# in one loop tick — e.g. every completed HEAD response of a done-callback
# wave — are corked and written as ONE buffer instead of one syscall per
# frame. Concatenating complete length-prefixed frames is byte-identical on
# the wire, so the FIFO-per-connection contract is untouched. =0 restores
# the per-frame write, which is the baseline leg of `bench.py --egress`.
_EGRESS_COALESCE = os.environ.get("RIO_TPU_EGRESS_COALESCE", "1") != "0"


class _BadFrame:
    """Queued marker for a frame that failed to decode (batch-decode path).

    The error response must leave in arrival order with everything else on
    the connection, so the failure rides the same queue as decoded inbounds.
    ``not_supported`` distinguishes a frame kind this server doesn't speak
    (a newer client's command against an old server — answered
    NOT_SUPPORTED so the peer can downgrade) from a corrupt frame
    (answered UNKNOWN).
    """

    __slots__ = ("detail", "not_supported")

    def __init__(self, detail: str, *, not_supported: bool = False) -> None:
        self.detail = detail
        self.not_supported = not_supported

    def response(self) -> ResponseEnvelope:
        if self.not_supported:
            return ResponseEnvelope.err(ResponseError.not_supported(self.detail))
        return ResponseEnvelope.err(
            ResponseError.unknown(f"bad frame: {self.detail}")
        )


def _stamp_handler_end(task) -> None:
    """Done-callback for pipelined dispatch tasks carrying a phase clock."""
    task._rio_ph[0].handler_end = _perf()


class ServerConnProtocol(asyncio.Protocol):
    """One accepted connection: framing + ordered-concurrent dispatch."""

    MAX_CONCURRENT = 64  # per-connection in-flight handler cap
    MAX_PENDING_FRAMES = 1024  # inbound backpressure threshold (pause reads)

    __slots__ = (
        "_service_factory",
        "_on_task",
        "_service",
        "_frames",
        "_queue",
        "_waiter",
        "_eof",
        "_transport",
        "_worker",
        "_paused",
        "_reading_paused",
        "_drain",
        "_streaming",
        "_resp_q",
        "_room",
        "_broken",
        "_lost",
        "_out",
        "_flush_scheduled",
        "_spans",
        "_affinity",
        "_qos",
        "_ph_tick",
    )

    def __init__(
        self,
        service_factory: Callable[[], "Service"],
        on_task: Callable[[asyncio.Task], None] | None = None,
    ) -> None:
        self._service_factory = service_factory
        self._on_task = on_task
        self._service: Service | None = None
        self._spans = None  # SpanRing (resolved from the service at accept)
        self._affinity = None  # EdgeSampler (TCP byte counters), same resolve
        self._qos = None  # QosScheduler (admission + start grants), same resolve
        self._ph_tick = -1  # 1-in-8 phase-clock stride for untraced traffic
        self._frames = FrameReader()
        # Inbound work: decoded envelopes / _BadFrame markers (batch-decode
        # path) or raw frame payloads (RIO_TPU_BATCH_DECODE=0 fallback).
        self._queue: deque = deque()
        self._waiter: asyncio.Future | None = None  # reader parked on _queue
        self._eof = False
        self._transport: asyncio.Transport | None = None
        self._worker: asyncio.Task | None = None
        self._paused = False
        self._reading_paused = False
        self._drain: asyncio.Future | None = None  # streaming backpressure
        self._streaming = False
        self._resp_q: deque[asyncio.Future] = deque()  # FIFO response slots
        self._room: asyncio.Future | None = None  # reader parked on cap
        self._broken = False  # a response failed; FIFO can't recover
        self._lost = False  # connection_lost fired; writes are pointless
        self._out: list[bytes] = []  # corked response frames (one syscall/tick)
        self._flush_scheduled = False

    # -- transport callbacks -------------------------------------------------

    def connection_made(self, transport: asyncio.BaseTransport) -> None:
        self._transport = transport  # type: ignore[assignment]
        self._service = self._service_factory()
        self._spans = getattr(self._service, "spans", None)
        self._affinity = getattr(self._service, "affinity", None)
        self._qos = getattr(self._service, "qos", None)
        self._worker = asyncio.ensure_future(self._run())
        if self._on_task is not None:
            self._on_task(self._worker)

    def _stamp_inbound(self, env, t_recv: float) -> None:
        """Attach the per-request phase clock (span retention armed only).

        Traced requests always carry one; untraced traffic is sampled on
        the same 1-in-8 stride the RED histograms use, so the ring's
        tail-based capture sees outliers without the hot path paying a
        clock read per request.
        """
        if type(env) is not RequestEnvelope:
            return
        tc = env.trace_ctx
        if tc is None:
            self._ph_tick = tick = (self._ph_tick + 1) & 7
            if tick:
                return
            ph = Phases(t_recv)
        else:
            ph = Phases(t_recv, tc)
        ph.decode = _perf()
        env._phases = ph

    def data_received(self, data: bytes) -> None:
        if self._affinity is not None:
            # Honest bytes-over-TCP ledger (bench --affinity numerator):
            # raw socket reads, before any decode.
            self._affinity.tcp_in_bytes += len(data)
        try:
            payloads = self._frames.feed(data)
        except SerializationError as e:
            # Unframeable stream (oversized header): nothing sane follows.
            log.warning("dropping connection: %s", e)
            assert self._transport is not None
            self._transport.close()
            return
        if payloads:
            if _BATCH_DECODE:
                # One tight decode pass per socket read: the cached dataclass
                # schemas stay hot and the worker loop receives ready
                # envelopes. Decode failures become in-order error markers.
                append = self._queue.append
                if self._spans is None:
                    for p in payloads:
                        try:
                            append(decode_inbound(p))
                        except UnknownFrameKind as e:
                            append(_BadFrame(str(e), not_supported=True))
                        except Exception as e:  # noqa: BLE001 — malformed frame
                            append(_BadFrame(str(e)))
                else:
                    # Span retention armed: one recv stamp per socket read
                    # (shared by the burst), decode stamped per envelope.
                    t_recv = _perf()
                    for p in payloads:
                        try:
                            env = decode_inbound(p)
                        except UnknownFrameKind as e:
                            append(_BadFrame(str(e), not_supported=True))
                            continue
                        except Exception as e:  # noqa: BLE001 — malformed frame
                            append(_BadFrame(str(e)))
                            continue
                        self._stamp_inbound(env, t_recv)
                        append(env)
            else:
                self._queue.extend(payloads)
            self._wake()
            # Inbound backpressure: MAX_CONCURRENT caps in-flight handlers
            # but not buffered frames — a fast pipelining client could grow
            # _queue without bound (the native engine cuts such peers off at
            # _MAX_PENDING_FRAMES).  Pausing the transport propagates real
            # TCP backpressure instead; the dispatch loop resumes reads as
            # it drains.
            if (
                not self._reading_paused
                and len(self._queue) + len(self._resp_q) > self.MAX_PENDING_FRAMES
            ):
                self._reading_paused = True
                assert self._transport is not None
                self._transport.pause_reading()

    def eof_received(self) -> bool | None:
        self._eof = True
        self._wake()
        return True  # keep transport open until responses flush

    def connection_lost(self, exc: Exception | None) -> None:
        self._eof = True
        self._lost = True
        self._wake()
        self._wake_room()
        if self._drain is not None and not self._drain.done():
            self._drain.set_result(None)
        if self._streaming and self._worker is not None:
            # A streaming worker blocks on the router queue, not on inbound
            # frames; cancellation is the only way to stop it (same rule as
            # the native transport).
            self._worker.cancel()

    def pause_writing(self) -> None:
        self._paused = True

    def resume_writing(self) -> None:
        self._paused = False
        if self._drain is not None and not self._drain.done():
            self._drain.set_result(None)

    # -- response FIFO -------------------------------------------------------

    def _push_response(self, fut: asyncio.Future) -> None:
        self._resp_q.append(fut)
        if fut.done():
            self._flush_ready()
        else:
            fut.add_done_callback(self._on_response_ready)

    def _on_response_ready(self, fut: asyncio.Future) -> None:
        self._flush_ready()

    def _flush_ready(self) -> None:
        """Queue every completed head response, preserving request order.

        Runs synchronously from the handler task's done-callback — only the
        FIFO head's completion actually emits (possibly several at once),
        so out-of-order completions cost nothing until their turn.  Frames
        are CORKED: appended to ``_out`` and written as one syscall at the
        end of the loop tick (``_do_flush``) — under pipelining this
        collapses dozens of per-response ``send``s into one.
        """
        q = self._resp_q
        spans = self._spans
        while q and q[0].done() and not self._broken:
            fut = q.popleft()
            if fut.cancelled() or self._lost:
                continue  # shutdown path / dead socket; nothing to write
            try:
                resp = fut.result()
                frame = encode_response_frame(resp)
            except Exception:
                # An unencodable/failed response would desync every later
                # FIFO match on this connection; drop the connection.
                log.exception("response encode error; dropping connection")
                self._break()
                break
            if spans is not None:
                ctx = getattr(fut, "_rio_ph", None)
                if ctx is not None:
                    ph, env = ctx
                    ph.encode = _perf()
                    err = resp.error
                    if err is not None:
                        ph.attrs = {"status": int(err.kind)}
                    self._write_soon(frame)
                    ph.flush = _perf()
                    finish_request(spans, ph, env)
                    continue
            self._write_soon(frame)
        self._wake_room()
        self._maybe_resume_reading()

    def _break(self) -> None:
        self._broken = True
        self._eof = True
        self._out.clear()
        self._wake()
        assert self._transport is not None
        self._transport.close()

    def _write_soon(self, data: bytes) -> None:
        if not _EGRESS_COALESCE:
            # Per-frame baseline (bench A/B): one transport.write per frame.
            if self._lost or self._broken:
                return
            try:
                assert self._transport is not None
                if self._affinity is not None:
                    self._affinity.tcp_out_bytes += len(data)
                self._transport.write(data)
            except Exception:
                log.exception("response write error; dropping connection")
                self._break()
            return
        self._out.append(data)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._do_flush)

    def _do_flush(self) -> None:
        self._flush_scheduled = False
        out = self._out
        if not out:
            return
        data = out[0] if len(out) == 1 else b"".join(out)
        out.clear()
        if self._lost or self._broken:
            return
        try:
            assert self._transport is not None
            if self._affinity is not None:
                self._affinity.tcp_out_bytes += len(data)
            self._transport.write(data)
        except Exception:
            log.exception("response write error; dropping connection")
            self._break()

    def _wake_room(self) -> None:
        r = self._room
        if r is not None and not r.done():
            self._room = None
            r.set_result(None)

    def _maybe_resume_reading(self) -> None:
        if (
            self._reading_paused
            and not self._lost
            and len(self._queue) + len(self._resp_q) <= self.MAX_PENDING_FRAMES // 2
        ):
            self._reading_paused = False
            assert self._transport is not None
            self._transport.resume_reading()

    # -- reader/dispatcher ---------------------------------------------------

    def _wake(self) -> None:
        w = self._waiter
        if w is not None and not w.done():
            self._waiter = None
            w.set_result(None)

    async def _next_inbound(self):
        while not self._queue:
            if self._eof:
                return None
            self._waiter = asyncio.get_running_loop().create_future()
            await self._waiter
        item = self._queue.popleft()
        self._maybe_resume_reading()
        return item

    async def _flushed(self) -> None:
        """Honor write backpressure (the StreamWriter.drain equivalent)."""
        while self._paused and not self._eof:
            self._drain = asyncio.get_running_loop().create_future()
            await self._drain

    async def _run(self) -> None:
        service = self._service
        transport = self._transport
        assert service is not None and transport is not None
        loop = asyncio.get_running_loop()
        cancelled = False
        try:
            while True:
                inbound = await self._next_inbound()
                if inbound is None:
                    # Peer finished sending; keep the socket open until
                    # every in-flight response has been written (the peer
                    # may have half-closed and still be reading).
                    while self._resp_q and not self._lost and not self._broken:
                        self._room = loop.create_future()
                        await self._room
                    return
                if type(inbound) is bytes:
                    # Fallback path (batch decode off): the queue holds raw
                    # frame payloads; decode them here as before. The phase
                    # clock starts at decode (recv_us collapses to ~0 — the
                    # batch path is the measured default).
                    t_recv = _perf() if self._spans is not None else 0.0
                    try:
                        inbound = decode_inbound(inbound)
                    except UnknownFrameKind as e:
                        inbound = _BadFrame(str(e), not_supported=True)
                    except Exception as e:  # malformed frame → error response
                        inbound = _BadFrame(str(e))
                    else:
                        if self._spans is not None:
                            self._stamp_inbound(inbound, t_recv)
                if type(inbound) is _BadFrame:
                    fut: asyncio.Future = loop.create_future()
                    fut.set_result(inbound.response())
                    self._push_response(fut)
                    continue
                if type(inbound) is CommandEnvelope:
                    # Control-plane command: rides the ordinary response
                    # FIFO (commands are infrequent — no inline fast path,
                    # no phase stamping).
                    while len(self._resp_q) >= self.MAX_CONCURRENT and not self._eof:
                        self._room = loop.create_future()
                        await self._room
                    self._push_response(
                        loop.create_task(service.call_command(inbound))
                    )
                    continue
                if type(inbound) is RequestEnvelope:
                    qos = self._qos
                    dispatched = None
                    if qos is not None:
                        # One synchronous admission + grant step between
                        # decode and dispatch: a shed (token bucket / full
                        # class queue) rides the ordinary FIFO response
                        # path as a pre-resolved future — the handler never
                        # starts (_BadFrame pattern, so ordering is
                        # preserved). Otherwise ``dispatched`` is the
                        # awaitable that runs the handler under its grant.
                        dispatched = qos.dispatch(service.call, inbound)
                        if type(dispatched) is ResponseError:
                            fut = loop.create_future()
                            fut.set_result(ResponseEnvelope.err(dispatched))
                            self._push_response(fut)
                            continue
                    ph = (
                        inbound.__dict__.get("_phases")
                        if self._spans is not None
                        else None
                    )
                    if not self._resp_q and not self._queue:
                        # Sole in-flight request on this connection: dispatch
                        # inline (no task) — the common non-pipelined case,
                        # worth ~5-8% (measured). Frames arriving DURING the
                        # inline await just buffer; when it finishes, the
                        # backlog takes the concurrent spawn path below, so
                        # head-of-line serialization is bounded to this one
                        # request (and FIFO response order delays delivery
                        # behind a slow head regardless of execution model).
                        if ph is not None:
                            ph.queue = ph.handler_start = _perf()
                        if dispatched is None:
                            resp = await service.call(inbound)
                        else:
                            # Under contention the grant may park
                            # (weighted-fair / strict tiers) or resolve to
                            # DEADLINE_EXCEEDED without running the handler.
                            resp = await dispatched
                        if ph is not None:
                            ph.handler_end = _perf()
                        if not self._broken:
                            try:
                                frame = encode_response_frame(resp)
                            except Exception:
                                log.exception(
                                    "response encode error; dropping connection"
                                )
                                return
                            if ph is None:
                                self._write_soon(frame)
                            else:
                                ph.encode = _perf()
                                err = resp.error
                                if err is not None:
                                    ph.attrs = {"status": int(err.kind)}
                                self._write_soon(frame)
                                ph.flush = _perf()
                                finish_request(self._spans, ph, inbound)
                        if self._paused:
                            await self._flushed()
                        continue
                    while len(self._resp_q) >= self.MAX_CONCURRENT and not self._eof:
                        self._room = loop.create_future()
                        await self._room
                    task = loop.create_task(
                        service.call(inbound)
                        if dispatched is None
                        else dispatched
                    )
                    if ph is not None:
                        # Pipelined path: handler runs in its own task;
                        # queue-exit/handler-start stamp here, handler-end in
                        # the task's done-callback, encode/flush when the
                        # FIFO head drains it (_flush_ready).
                        ph.queue = ph.handler_start = _perf()
                        task._rio_ph = (ph, inbound)
                        task.add_done_callback(_stamp_handler_end)
                    self._push_response(task)
                else:
                    # Flush every pending response before switching the
                    # connection into subscription streaming mode.
                    while self._resp_q and not self._eof:
                        self._room = loop.create_future()
                        await self._room
                    self._do_flush()  # corked responses precede the stream
                    self._streaming = True
                    await self._stream_subscription(inbound)
                    return
        except asyncio.CancelledError:
            cancelled = True
            raise
        except ConnectionError:
            pass
        except Exception:
            log.exception("connection worker error")
        finally:
            if cancelled:
                # Server shutdown: sever the connection now — cancel every
                # in-flight handler (the pre-pipelining behavior, where the
                # inline-awaited handler died with the worker).
                for fut in self._resp_q:
                    fut.cancel()
                self._resp_q.clear()
                self._out.clear()
            self._do_flush()  # corked frames must beat transport.close()
            transport.close()

    async def _stream_subscription(self, req: SubscriptionRequest) -> None:
        service, transport = self._service, self._transport
        assert service is not None and transport is not None
        result = await service.subscribe(req)
        if isinstance(result, ResponseError):
            transport.write(
                encode_subresponse_frame(SubscriptionResponse(error=result))
            )
            return
        queue = result
        router = service.app_data.get(MessageRouter)
        try:
            while not self._eof:
                item = await queue.get()
                transport.write(encode_subresponse_frame(item))
                if self._paused:
                    await self._flushed()
        finally:
            router.drop_subscription(req.handler_type, req.handler_id, queue)


class ClientConnProtocol(asyncio.Protocol):
    """One outbound connection: framing + FIFO frame delivery.

    Surface-compatible with :class:`rio_tpu.native.transport.NativeClientConn`
    (``roundtrip`` / ``read_frame`` / ``write`` / ``close``), plus
    **pipelining**: multiple requests may be in flight at once.  The wire
    has no correlation ids (the reference's contract), but the server
    answers each connection's requests in order, so inbound frames resolve
    the oldest pending ``roundtrip`` FIFO-style.  ``pending`` exposes the
    in-flight depth for the pool's least-loaded pick.
    """

    __slots__ = (
        "_frames",
        "_waiters",
        "_queue",
        "_transport",
        "closed",
        "delivered",
        "_out",
        "_flush_scheduled",
    )

    def __init__(self) -> None:
        self._frames = FrameReader()
        self._waiters: deque[asyncio.Future] = deque()  # FIFO roundtrips
        self._queue: deque[bytes] = deque()  # frames beyond waiters (subscribe)
        self._transport: asyncio.Transport | None = None
        self.closed = False
        self.delivered = 0  # inbound frames seen (client's progress signal)
        self._out: list[bytes] = []  # corked request frames (one syscall/tick)
        self._flush_scheduled = False

    @property
    def pending(self) -> int:
        return len(self._waiters)

    def connection_made(self, transport: asyncio.BaseTransport) -> None:
        self._transport = transport  # type: ignore[assignment]

    def data_received(self, data: bytes) -> None:
        try:
            payloads = self._frames.feed(data)
        except SerializationError:
            self.closed = True
            assert self._transport is not None
            self._transport.close()
            return
        for payload in payloads:
            self.delivered += 1
            if self._waiters:
                w = self._waiters.popleft()
                if not w.done():
                    w.set_result(payload)
                # else: the matching roundtrip was cancelled mid-flight —
                # this payload is its orphaned response; drop it (handing
                # it to the next waiter would shift every later match).
            else:
                self._queue.append(payload)

    def connection_lost(self, exc: Exception | None) -> None:
        self.closed = True
        for w in self._waiters:
            if not w.done():
                w.set_result(None)
        self._waiters.clear()

    # -- conn surface ---------------------------------------------------------

    def _write_soon(self, frame_bytes: bytes) -> None:
        """Cork writes: one syscall per loop tick instead of per request.

        Order safety: waiter registration order == append order == flush
        order, and the server cannot answer a frame before it is written,
        so FIFO matching is unaffected.
        """
        if not _EGRESS_COALESCE:
            # Per-frame baseline (bench A/B), mirroring the server side.
            if self.closed or self._transport is None:
                return
            try:
                self._transport.write(frame_bytes)
            except Exception:
                log.exception("request write error; dropping connection")
                self.close()
            return
        self._out.append(frame_bytes)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            asyncio.get_running_loop().call_soon(self._do_flush)

    def _do_flush(self) -> None:
        self._flush_scheduled = False
        out = self._out
        if not out or self.closed or self._transport is None:
            out.clear()
            return
        data = out[0] if len(out) == 1 else b"".join(out)
        out.clear()
        try:
            self._transport.write(data)
        except Exception:
            log.exception("request write error; dropping connection")
            self.close()

    async def roundtrip(self, frame_bytes: bytes) -> bytes:
        if self.closed:
            raise Disconnect("connection closed")
        assert self._transport is not None
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        self._write_soon(frame_bytes)
        payload = await fut
        if payload is None:
            raise Disconnect("connection closed mid-request")
        return payload

    async def read_frame(self) -> bytes | None:
        """Next inbound frame; None at EOF (subscription streaming)."""
        while not self._queue:
            if self.closed:
                return None
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            return await fut
        return self._queue.popleft()

    def write(self, frame_bytes: bytes) -> None:
        assert self._transport is not None
        self._write_soon(frame_bytes)

    def close(self) -> None:
        self._do_flush()  # corked frames must beat transport.close()
        self.closed = True
        if self._transport is not None:
            self._transport.close()


async def connect(host: str, port: int, timeout: float) -> ClientConnProtocol:
    """Dial ``host:port`` and return the framed connection."""
    loop = asyncio.get_running_loop()
    _, proto = await asyncio.wait_for(
        loop.create_connection(ClientConnProtocol, host, port), timeout
    )
    return proto
