"""Two-level (hierarchical) optimal-transport placement.

The 1k-node x 10M-object tier (``BASELINE.md`` row 5) cannot materialize a
flat cost matrix: 10M x 1k fp32 is 40 GB, over a single chip's HBM. The
hierarchical solve replaces it with two bounded stages over a *factorized*
affinity (object features x node features, the MXU-friendly form):

1. **Coarse**: nodes are partitioned into ``G`` groups (racks/hosts or
   contiguous slices); each group gets capacity-weighted mean features and
   the summed capacity of its live members. One (N x G) Sinkhorn solve +
   capacity-aware rounding assigns every object a group, with per-group
   quotas following group capacity.
2. **Fine**: objects are bucketed by group (static bucket size with slack,
   scatter by rank-in-group), and ``G`` independent (B x S) solves run
   batched under ``vmap`` — batched matmuls and batched Sinkhorn, ideal
   XLA shapes. Results map back through the group member table.

Peak memory is O(N*G + N*S + N*d) instead of O(N*M) — for 10M x 1024
with G = S = 32 that is ~2.6 GB instead of 40 GB.

Scaling out: the object axis is embarrassingly parallel — shard objects
across the mesh and give every shard ``1/n_shards`` of each node's
capacity (:func:`sharded_hierarchical_assign`); no cross-shard collective
is needed beyond the initial capacity split, so the solve rides data
parallelism to any mesh size. Past the per-shard compile wall, the same
independence composes with temporal chunking
(:func:`mesh_chunked_hierarchical_assign`): each (device, chunk) cell
solves its slice against ``1/(n_shards*n_chunks)`` capacity, so ONE
compiled body at the cell shape covers 10M-100M rows.

The reference has no counterpart — its placement directory is row-by-row
SQL (``rio-rs/src/object_placement/sqlite.rs:68-100``) with a random-pick
policy (``client/mod.rs:255-262``); this module is the scale ceiling of
the TPU-native redesign.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.scaling import scaling_sinkhorn
from ..ops.sinkhorn import (
    exact_quota_repair,
    plan_rounded_assign,
    route_sentinel_spill,
)

__all__ = [
    "HierarchicalResult",
    "chunked_hierarchical_assign",
    "chunked_hierarchical_assign_timed",
    "hierarchical_assign",
    "mesh_chunked_hierarchical_assign",
    "mesh_chunked_hierarchical_assign_timed",
    "sharded_hierarchical_assign",
]


class HierarchicalResult(NamedTuple):
    assignment: jax.Array  # (N,) int32 global node index
    group: jax.Array       # (N,) int32 coarse group index
    overflow: jax.Array    # scalar int32: objects that missed their bucket
    # (G,) coarse-stage group potentials — the warm seed for the NEXT
    # (delta) solve's coarse stage. None on the sharded path (each shard
    # solves its own coarse problem; no single seed to return).
    coarse_g: jax.Array | None = None
    # Scalar final L1 column-marginal violation of the coarse solve —
    # the convergence residual SolveStats surfaces. None on the sharded
    # path (per-shard residuals have no single summary without a
    # collective this solve otherwise never needs).
    coarse_err: jax.Array | None = None


_HIER_STATIC = ("n_groups", "bucket", "eps", "coarse_iters", "fine_iters")


def _hierarchical_assign_impl(
    obj_feat: jax.Array,
    node_feat: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    *,
    n_groups: int,
    bucket: int | None = None,
    eps: float = 0.05,
    coarse_iters: int = 30,
    fine_iters: int = 30,
    coarse_g_init: jax.Array | None = None,
) -> HierarchicalResult:
    """Two-level OT assignment over factorized affinity.

    Args:
      obj_feat: (N, d) object features (e.g. hashed identity embeddings).
      node_feat: (d, M) node features; affinity[i, j] = obj_feat[i] @ node_feat[:, j].
      node_capacity: (M,) capacity per node (0 = retired slot).
      alive: (M,) liveness in {0.0, 1.0}; dead nodes attract nothing.
      n_groups: number of node groups; M must be divisible by it.
      bucket: per-group object bucket size (static). Defaults to
        ``ceil(1.25 * N / G)`` rounded up to a multiple of 8 — sized for
        roughly uniform group capacity. With skewed capacity (or mostly-dead
        groups) pass an explicit bucket ~ ``1.3 * N * max_group_cap_share``
        or quotas overflow into the fallback path.
      coarse_g_init: optional (G,) warm-start potentials for the coarse
        solve — the previous solve's ``coarse_g``, fed back by the delta
        rebalance path so a churn re-solve's coarse stage converges in a
        handful of iterations. The fine stages always start cold (their
        populations change with the coarse outcome).
    """
    n, d = obj_feat.shape
    d2, m = node_feat.shape
    assert d == d2 and m % n_groups == 0, (obj_feat.shape, node_feat.shape, n_groups)
    s = m // n_groups
    if bucket is None:
        bucket = -(-int(1.25 * n) // n_groups)
        bucket = -(-bucket // 8) * 8
    obj_feat = obj_feat.astype(jnp.float32)
    node_feat = node_feat.astype(jnp.float32)
    cap = node_capacity.astype(jnp.float32) * alive.astype(jnp.float32)

    # ---- stage 1: coarse obj -> group ------------------------------------
    # Coarse affinity = the object's BEST live member in each group, not
    # the group's mean embedding: with near-orthogonal node embeddings a
    # mean dilutes a single warm node by 1/S (measured: it dropped the
    # churn-failover locality hit rate to chance in
    # tests/test_affinity_payoff.py), while the max routes the object to
    # whichever group holds its warm state.  Computed blockwise over
    # groups — an (N, S) temp per step, the same working-set scale as the
    # fine stage; the (N, M) product is never materialized.
    node_feat_grouped = node_feat.reshape(d, n_groups, s).transpose(1, 0, 2)
    alive_grouped = (cap > 0).reshape(n_groups, s)
    group_cap = cap.reshape(n_groups, s).sum(axis=1)  # (G,)

    def _group_best(args):
        nf_g, alive_g = args  # (d, S), (S,)
        scores = obj_feat @ nf_g  # (N, S)
        scores = jnp.where(alive_g[None, :], scores, -jnp.inf)
        return jnp.max(scores, axis=1)  # (N,)

    coarse_aff = jax.lax.map(_group_best, (node_feat_grouped, alive_grouped))
    live_group = group_cap > 0  # (G,)
    raw_cost = -coarse_aff.T  # (N, G); +inf on all-dead groups
    # Normalize the cost scale so eps is a relative knob (and the scaling
    # solver's exp(-C/eps) stays in float range for any feature magnitude)
    # — statistics over LIVE groups only, then a finite terrible cost on
    # dead groups (their zero group_cap already excludes them from the OT
    # marginals).
    std = jnp.std(raw_cost, where=live_group[None, :])
    coarse_cost = jnp.where(
        live_group[None, :], raw_cost / jnp.maximum(std, 1e-6), 1e6
    )
    mass = jnp.ones((n,), jnp.float32)
    res_c = scaling_sinkhorn(
        coarse_cost, mass, group_cap, eps=eps, n_iters=coarse_iters,
        g_init=coarse_g_init,
    )
    group = plan_rounded_assign(coarse_cost, res_c.f, res_c.g, eps)  # (N,)
    # Exact group quotas: CDF rounding matches group capacities only in
    # expectation; the repair pins every group to its largest-remainder
    # quota, so a bucket sized >= max quota makes overflow structurally
    # impossible (instead of merely improbable).
    group = exact_quota_repair(
        group, group_cap / jnp.maximum(jnp.sum(group_cap), 1e-30) * n
    )

    # ---- bucket objects by group (static shapes) -------------------------
    # rank-in-group via a stable sort by group id; each group's objects are
    # a contiguous run of the sorted order.
    order = jnp.argsort(group, stable=True)  # (N,)
    sorted_group = group[order]
    counts = jnp.bincount(group, length=n_groups)  # (G,)
    starts = jnp.cumsum(counts) - counts  # (G,)
    rank = jnp.arange(n) - starts[sorted_group]  # rank within group
    in_bucket = rank < bucket
    overflow = jnp.sum(~in_bucket).astype(jnp.int32)
    # Scatter sorted object indices into the (G, bucket) table; sentinel N
    # marks padding (reads a zero feature row). Overflow writes are routed
    # to an out-of-bounds slot and dropped.
    flat = jnp.full((n_groups * bucket,), n, jnp.int32)
    slot = jnp.where(in_bucket, sorted_group * bucket + rank, n_groups * bucket)
    flat = flat.at[slot].set(order.astype(jnp.int32), mode="drop")
    idx = flat.reshape(n_groups, bucket)  # (G, B) object ids or N

    # ---- stage 2: fine per-group solves, batched -------------------------
    obj_feat_pad = jnp.concatenate([obj_feat, jnp.zeros((1, d), jnp.float32)], 0)
    feat_b = obj_feat_pad[idx]  # (G, B, d)
    node_feat_g = node_feat.reshape(d, n_groups, s).transpose(1, 0, 2)  # (G, d, S)
    fine_cost = -jnp.einsum("gbd,gds->gbs", feat_b, node_feat_g)  # (G, B, S)
    fine_cost = fine_cost / jnp.maximum(jnp.std(fine_cost), 1e-6)
    fine_mass = (idx < n).astype(jnp.float32)  # (G, B)
    cap_g = cap.reshape(n_groups, s)  # (G, S)

    def solve_one(c, a, b):
        r = scaling_sinkhorn(c, a, b, eps=eps, n_iters=fine_iters)
        local = plan_rounded_assign(c, r.f, r.g, eps)
        # Exact per-node quotas within the group (same largest-remainder
        # repair as the coarse stage): padding rows go to a sentinel slot
        # sized to their count, so real rows land exactly on capacity
        # shares of the group's real population.
        n_real = jnp.sum(a)
        local = jnp.where(a > 0, local, s)
        pad_count = (jnp.float32(a.shape[0]) - n_real)[None]
        expected = jnp.concatenate(
            [b / jnp.maximum(jnp.sum(b), 1e-30) * n_real, pad_count]
        )
        repaired = exact_quota_repair(local, expected)
        # Real rows spilled onto the sentinel column (quota drift / refill
        # clip) would be take_along_axis-clamped onto member s-1, which may
        # be dead — route them to the group's best live member instead.
        return route_sentinel_spill(repaired, a > 0, s, b)

    fine_local = jax.vmap(solve_one)(fine_cost, fine_mass, cap_g)  # (G, B) in [0,S]
    members = jnp.arange(m, dtype=jnp.int32).reshape(n_groups, s)
    fine_global = jnp.take_along_axis(members, fine_local, axis=1)  # (G, B)

    # ---- map back to object order ----------------------------------------
    assignment = jnp.zeros((n,), jnp.int32)
    assignment = assignment.at[idx.reshape(-1)].set(
        fine_global.reshape(-1), mode="drop"
    )
    # Overflow objects (rank >= bucket) fall back to their group's highest-
    # capacity live member (rare: bucket has 25% slack over a capacity-
    # balanced coarse quota; never materializes an (N x M) matrix).
    fallback = jnp.take_along_axis(
        members, jnp.argmax(cap_g, axis=1, keepdims=True), axis=1
    )[:, 0]  # (G,)
    missed = jnp.zeros((n,), bool).at[order].set(~in_bucket)
    assignment = jnp.where(missed, fallback[group], assignment)
    return HierarchicalResult(
        assignment=assignment, group=group, overflow=overflow,
        coarse_g=res_c.g, coarse_err=res_c.err,
    )


hierarchical_assign = jax.jit(_hierarchical_assign_impl, static_argnames=_HIER_STATIC)

# Donation twin for the host-looped timed paths: each chunk's feature slab
# is freshly sliced/built there, so its device buffer can back the result
# instead of doubling residency for the dispatch. Only engaged off-CPU —
# the CPU runtime ignores donation with a per-call warning, and the timed
# twins' bit-parity pins run on CPU against the non-donated executable.
_hierarchical_assign_donated = jax.jit(
    _hierarchical_assign_impl, static_argnames=_HIER_STATIC, donate_argnums=(0,)
)


def _donation_profitable(donate: bool) -> bool:
    return donate and jax.default_backend() != "cpu"


@functools.partial(jax.jit, static_argnames=("n_groups", "n_chunks", "bucket", "eps", "coarse_iters", "fine_iters"))
def chunked_hierarchical_assign(
    obj_feat: jax.Array,
    node_feat: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    *,
    n_groups: int,
    n_chunks: int,
    coarse_g_init: jax.Array | None = None,
    **kw,
) -> HierarchicalResult:
    """Single-chip scale-out: the sharded solve's design, run temporally.

    The TPU backend's compile time for :func:`hierarchical_assign` is
    superlinear in the object count (measured on v5e: 50 s at 655k,
    599 s at 2.6M — while CPU XLA stays flat at ~7 s), so giant flat
    shapes price a full re-solve out of any watchdog budget. This wrapper
    reuses the exact per-shard independence `sharded_hierarchical_assign`
    rides (each shard solves its slice against ``1/n_shards`` of every
    node's capacity; marginal normalization spreads each slice across the
    same capacity proportions): chunks run *sequentially* under
    ``lax.map``, so XLA traces and compiles ONE body at the chunk shape —
    compile cost is pinned to the chunk size while execution scales
    linearly with N. Per-chunk exact quota repair makes total node loads
    exact to chunk granularity, same as the mesh version.
    """
    n = obj_feat.shape[0]
    assert n % n_chunks == 0, (n, n_chunks)
    of = obj_feat.reshape(n_chunks, n // n_chunks, obj_feat.shape[1])

    def one(of_c):
        return hierarchical_assign(
            of_c, node_feat, node_capacity / n_chunks, alive,
            n_groups=n_groups, coarse_g_init=coarse_g_init, **kw,
        )

    res = jax.lax.map(one, of)
    return HierarchicalResult(
        assignment=res.assignment.reshape(-1),
        group=res.group.reshape(-1),
        overflow=jnp.sum(res.overflow),
        # Every chunk solves the same capacity proportions (its slice vs
        # 1/n_chunks of each node), so any chunk's coarse potentials are a
        # valid warm seed for the next solve; keep the last.
        coarse_g=res.coarse_g[-1],
        coarse_err=res.coarse_err[-1],
    )


def chunked_hierarchical_assign_timed(
    obj_feat: jax.Array,
    node_feat: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    *,
    n_groups: int,
    n_chunks: int,
    coarse_g_init: jax.Array | None = None,
    donate: bool = True,
    **kw,
) -> tuple[HierarchicalResult, list[float]]:
    """:func:`chunked_hierarchical_assign` with per-chunk host timings.

    The ``lax.map`` form runs every chunk inside ONE executable, so chunk
    boundaries are invisible to the host; this twin loops the chunks on
    the host instead, calling the SAME jitted :func:`hierarchical_assign`
    per chunk (compile stays pinned to the chunk shape — the whole point
    of chunking) and timing each dispatch+``block_until_ready`` cycle.
    Identical inputs per chunk, so outputs match the ``lax.map`` form
    exactly (``tests/test_hierarchical.py`` pins the parity); the first
    chunk's timing includes the one-time compile, which is exactly the
    compile-vs-execute signal SolveStats wants. The sync per chunk is a
    single ``block_until_ready`` on a chained jit result — the pattern
    CLAUDE.md's r4 wedge notes mark safe (sub-ms, unlike eager pulls).

    ``donate`` releases each chunk's feature slab into its own solve
    (``donate_argnums`` on the chunk body) — the slab is a fresh slice per
    iteration, so off-CPU this halves the chunk's device residency; on CPU
    it is a no-op (see ``_hierarchical_assign_donated``).

    Returns ``(result, chunk_ms)`` with one wall-ms entry per chunk.
    """
    import time as _time

    n = obj_feat.shape[0]
    assert n % n_chunks == 0, (n, n_chunks)
    solve = (
        _hierarchical_assign_donated
        if _donation_profitable(donate)
        else hierarchical_assign
    )
    of = jnp.asarray(obj_feat).reshape(n_chunks, n // n_chunks, obj_feat.shape[1])
    # Sync staged inputs BEFORE the timed loop: dispatch is async, so a
    # still-pending producer chain (e.g. feature generation, O(N) in total
    # rows) would otherwise drain inside chunk 0's timer and masquerade as
    # compile time — chunk_ms must measure the solve, pinned to cell shape.
    jax.block_until_ready((of, node_feat, node_capacity, alive))
    assignments: list[jax.Array] = []
    groups: list[jax.Array] = []
    overflow = jnp.zeros((), jnp.int32)
    chunk_ms: list[float] = []
    res = None
    for c in range(n_chunks):
        t0 = _time.perf_counter()
        res = solve(
            of[c], node_feat, node_capacity / n_chunks, alive,
            n_groups=n_groups, coarse_g_init=coarse_g_init, **kw,
        )
        jax.block_until_ready(res.assignment)
        chunk_ms.append(round((_time.perf_counter() - t0) * 1e3, 3))
        assignments.append(res.assignment)
        groups.append(res.group)
        overflow = overflow + res.overflow
    return (
        HierarchicalResult(
            assignment=jnp.concatenate(assignments),
            group=jnp.concatenate(groups),
            overflow=overflow,
            coarse_g=res.coarse_g,
            coarse_err=res.coarse_err,
        ),
        chunk_ms,
    )


def _shard_map_check_kw():
    """Resolve shard_map plus its replication-check kwarg, disabled.

    The kwarg was renamed across jax versions (check_rep -> check_vma);
    return ``(shard_map, {that_kwarg: False})`` for whichever this install
    understands.
    """
    import inspect

    from . import shard_map  # version-gated import (top-level vs experimental)

    params = inspect.signature(shard_map).parameters
    check_kw = next((k for k in ("check_vma", "check_rep") if k in params), None)
    return shard_map, ({check_kw: False} if check_kw else {})


def _mesh_inputs(
    mesh, obj_feat, node_feat, node_capacity, alive, coarse_g_init, n_groups
):
    """Place the solve inputs: object rows sharded, everything else replicated.

    A missing warm seed becomes the zero seed — bitwise the same solve
    (``v0 = exp(0) = 1`` either way, see ``ops.scaling.scaling_core``) —
    and an always-an-array seed keeps the traced signature stable instead
    of minting a second executable on the cold/warm flip.
    """
    axes = mesh.axis_names
    obj_feat = jax.device_put(obj_feat, NamedSharding(mesh, P(axes, None)))
    rep = NamedSharding(mesh, P())
    node_feat = jax.device_put(jnp.asarray(node_feat), rep)
    node_capacity = jax.device_put(jnp.asarray(node_capacity), rep)
    alive = jax.device_put(jnp.asarray(alive), rep)
    if coarse_g_init is None:
        coarse_g_init = jnp.zeros((n_groups,), jnp.float32)
    coarse_g_init = jax.device_put(jnp.asarray(coarse_g_init, jnp.float32), rep)
    return obj_feat, node_feat, node_capacity, alive, coarse_g_init


def _hier_out_specs(axes):
    return HierarchicalResult(
        assignment=P(axes), group=P(axes), overflow=P(),
        # Coarse potentials/residual come back REPLICATED: every shard
        # solves the same capacity proportions (its slice vs 1/n_shards of
        # each node), so the pmean of the per-shard potentials is a valid
        # warm seed for the next solve — this is what persists into
        # PlanState on the mesh path (it used to be dropped entirely).
        coarse_g=P(), coarse_err=P(),
    )


def sharded_hierarchical_assign(
    mesh: Mesh,
    obj_feat: jax.Array,
    node_feat: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    *,
    n_groups: int,
    coarse_g_init: jax.Array | None = None,
    **kw,
) -> HierarchicalResult:
    """Data-parallel hierarchical solve: objects sharded over the mesh.

    ``shard_map`` runs an *independent* two-level solve per object shard
    (marginal normalization makes each shard spread its slice across the
    same capacity proportions), so no cross-shard collective is needed at
    all — the sort/bucket/scatter machinery stays shard-local instead of
    turning into a global all-to-all. Node-side inputs are replicated
    (O(M), tiny next to the object axis); the overflow counter is psum'd
    and the coarse potentials/residual are pmean'd to a replicated warm
    seed (``coarse_g_init`` threads the previous one back in).
    """
    shard_map, check = _shard_map_check_kw()
    axes = mesh.axis_names
    obj_feat, node_feat, node_capacity, alive, coarse_g_init = _mesh_inputs(
        mesh, obj_feat, node_feat, node_capacity, alive, coarse_g_init, n_groups
    )

    def local_solve(of, nf, cap, al, g0):
        res = hierarchical_assign(
            of, nf, cap, al, n_groups=n_groups, coarse_g_init=g0, **kw
        )
        return HierarchicalResult(
            assignment=res.assignment,
            group=res.group,
            overflow=jax.lax.psum(res.overflow, axes),
            coarse_g=jax.lax.pmean(res.coarse_g, axes),
            coarse_err=jax.lax.pmean(res.coarse_err, axes),
        )

    fn = shard_map(
        local_solve,
        mesh=mesh,
        in_specs=(P(axes, None), P(), P(), P(), P()),
        out_specs=_hier_out_specs(axes),
        **check,
    )
    return fn(obj_feat, node_feat, node_capacity, alive, coarse_g_init)


def mesh_chunked_hierarchical_assign(
    mesh: Mesh,
    obj_feat: jax.Array,
    node_feat: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    *,
    n_groups: int,
    n_chunks: int,
    coarse_g_init: jax.Array | None = None,
    **kw,
) -> HierarchicalResult:
    """Mesh x chunk composed solve: devices AND chunks scale the row count.

    :func:`sharded_hierarchical_assign` divides N by the device count but
    still compiles one flat body per shard — at TPU-backend compile costs
    superlinear in the row count (CLAUDE.md r5) that hits the same wall
    one octave later. This composition runs the ``lax.map``-chunked body
    *inside* each shard: every (device, chunk) cell solves
    ``N / (n_shards * n_chunks)`` rows against ``1 / (n_shards *
    n_chunks)`` of each node's capacity (the same per-slice independence
    both parents ride), so the ONE compiled body is pinned to the cell
    shape while rows scale with devices times chunks. Overflow is psum'd;
    coarse potentials are pmean'd across shards (last chunk per shard,
    matching :func:`chunked_hierarchical_assign`) into a replicated warm
    seed.
    """
    shard_map, check = _shard_map_check_kw()
    axes = mesh.axis_names
    n_shards = int(mesh.devices.size)
    n = obj_feat.shape[0]
    assert n % (n_shards * n_chunks) == 0, (n, n_shards, n_chunks)
    scale = n_shards * n_chunks
    obj_feat, node_feat, node_capacity, alive, coarse_g_init = _mesh_inputs(
        mesh, obj_feat, node_feat, node_capacity, alive, coarse_g_init, n_groups
    )

    def local_solve(of, nf, cap, al, g0):
        ofc = of.reshape(n_chunks, of.shape[0] // n_chunks, of.shape[1])

        def one(of_c):
            # Divide by the FULL scale in one step — the timed twin does
            # the identical division, so the two forms stay comparable to
            # the last ulp.
            return hierarchical_assign(
                of_c, nf, cap / scale, al,
                n_groups=n_groups, coarse_g_init=g0, **kw,
            )

        res = jax.lax.map(one, ofc)
        return HierarchicalResult(
            assignment=res.assignment.reshape(-1),
            group=res.group.reshape(-1),
            overflow=jax.lax.psum(jnp.sum(res.overflow), axes),
            coarse_g=jax.lax.pmean(res.coarse_g[-1], axes),
            coarse_err=jax.lax.pmean(res.coarse_err[-1], axes),
        )

    fn = shard_map(
        local_solve,
        mesh=mesh,
        in_specs=(P(axes, None), P(), P(), P(), P()),
        out_specs=_hier_out_specs(axes),
        **check,
    )
    return fn(obj_feat, node_feat, node_capacity, alive, coarse_g_init)


@functools.lru_cache(maxsize=8)
def _mesh_cell_solver(mesh: Mesh, scale: int, n_groups: int, kw_key: tuple):
    """One jitted shard_map cell solver per (mesh, scale, solve config).

    The timed twin dispatches every chunk through this SAME executable —
    the cache (keyed on hashables only; ``Mesh`` hashes by device/axis
    layout) is what pins compile cost to the first chunk of the first
    solve at a given cell shape, across chunks AND across rebalances.
    """
    shard_map, check = _shard_map_check_kw()
    axes = mesh.axis_names
    kw = dict(kw_key)

    def local_solve(of, nf, cap, al, g0):
        res = hierarchical_assign(
            of, nf, cap / scale, al,
            n_groups=n_groups, coarse_g_init=g0, **kw,
        )
        return HierarchicalResult(
            assignment=res.assignment,
            group=res.group,
            overflow=jax.lax.psum(res.overflow, axes),
            coarse_g=jax.lax.pmean(res.coarse_g, axes),
            coarse_err=jax.lax.pmean(res.coarse_err, axes),
        )

    fn = shard_map(
        local_solve,
        mesh=mesh,
        in_specs=(P(axes, None), P(), P(), P(), P()),
        out_specs=_hier_out_specs(axes),
        **check,
    )
    return jax.jit(fn)


def mesh_chunked_hierarchical_assign_timed(
    mesh: Mesh,
    obj_feat: jax.Array,
    node_feat: jax.Array,
    node_capacity: jax.Array,
    alive: jax.Array,
    *,
    n_groups: int,
    n_chunks: int,
    coarse_g_init: jax.Array | None = None,
    **kw,
) -> tuple[HierarchicalResult, list[float]]:
    """:func:`mesh_chunked_hierarchical_assign` with per-chunk host timings.

    Same split as :func:`chunked_hierarchical_assign_timed`: the
    ``lax.map`` form hides chunk boundaries inside one executable, so this
    twin loops the chunks on the host — each iteration dispatches one
    mesh-wide slab (every device solves its own cell of that chunk)
    through the cached jitted cell solver (:func:`_mesh_cell_solver`) and
    times dispatch+``block_until_ready``. The slab for chunk ``c`` is
    exactly the ``lax.map`` form's set of (device, chunk ``c``) cells —
    same rows per cell, same ``cap / (n_shards * n_chunks)`` division —
    so the composed result matches the single-executable form. The first
    chunk's timing carries the one-time compile: the compile-vs-exec
    signal SolveStats wants, now at mesh scale.
    """
    import time as _time

    n_shards = int(mesh.devices.size)
    n, d = obj_feat.shape
    assert n % (n_shards * n_chunks) == 0, (n, n_shards, n_chunks)
    cell = n // (n_shards * n_chunks)
    solve = _mesh_cell_solver(
        mesh, n_shards * n_chunks, n_groups, tuple(sorted(kw.items()))
    )
    # (shard, chunk, cell, d) view: slab c = every shard's chunk-c cell,
    # laid out shard-major so P(axes) sharding hands each device its own
    # cell — the exact row->cell mapping of the lax.map form.
    of = jnp.asarray(obj_feat).reshape(n_shards, n_chunks, cell, d)
    # Sync staged inputs BEFORE the timed loop (same reason as the chunked
    # twin): an async pending producer chain behind obj_feat is O(N) in
    # TOTAL rows and would drain inside chunk 0's timer, inflating the
    # "compile" number superlinearly with N — the exact signal the
    # composed solve exists to keep flat.
    jax.block_until_ready((of, node_feat, node_capacity, alive))
    shard_spec = NamedSharding(mesh, P(mesh.axis_names, None))
    rep_inputs = None
    assignments: list[jax.Array] = []
    groups: list[jax.Array] = []
    overflow = jnp.zeros((), jnp.int32)
    chunk_ms: list[float] = []
    res = None
    for c in range(n_chunks):
        t0 = _time.perf_counter()
        slab = of[:, c].reshape(n_shards * cell, d)
        if rep_inputs is None:
            slab, nf, cap, al, g0 = _mesh_inputs(
                mesh, slab, node_feat, node_capacity, alive,
                coarse_g_init, n_groups,
            )
            rep_inputs = (nf, cap, al, g0)
        else:
            slab = jax.device_put(slab, shard_spec)
            nf, cap, al, g0 = rep_inputs
        res = solve(slab, nf, cap, al, g0)
        jax.block_until_ready(res.assignment)
        chunk_ms.append(round((_time.perf_counter() - t0) * 1e3, 3))
        assignments.append(res.assignment.reshape(n_shards, cell))
        groups.append(res.group.reshape(n_shards, cell))
        overflow = overflow + res.overflow
    # Chunk results stack to (shard, chunk, cell) when interleaved back on
    # axis 1 — the shard-major global row order the input was reshaped from.
    asn = jnp.stack(assignments, axis=1).reshape(-1)
    grp = jnp.stack(groups, axis=1).reshape(-1)
    return (
        HierarchicalResult(
            assignment=asn,
            group=grp,
            overflow=overflow,
            coarse_g=res.coarse_g,
            coarse_err=res.coarse_err,
        ),
        chunk_ms,
    )
