"""Multi-chip sharded placement solve: mesh + shard_map + XLA collectives.

The scale target (``BASELINE.md`` row 5) is 10M objects x 1k nodes — a cost
matrix that must be sharded across chips. The design follows the standard
TPU recipe: pick a 2-D ``jax.sharding.Mesh`` with axes ``("obj", "node")``,
shard the cost matrix on both axes, express the Sinkhorn row/column
normalizations with explicit ``psum``/``pmax`` collectives inside
``shard_map`` (they ride ICI within a slice), and let XLA lay out everything
else. The reference has no device story at all — its cross-node transport is
tokio TCP + SQL rendezvous (``rio-rs/src/service.rs:370-378``); here the
control plane stays on host TCP while the solver plane lives on the mesh.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 promotes shard_map to the top-level namespace
    from jax import shard_map
except ImportError:  # pragma: no cover - exercised on jax 0.4.x installs
    from jax.experimental.shard_map import shard_map

if hasattr(lax, "pcast"):
    _pcast = lax.pcast
else:  # pragma: no cover - jax < 0.9: shard_map does not track manual-axis
    # variance through scan, so the explicit marking is simply unnecessary.
    def _pcast(x, axes, to="varying"):  # noqa: ARG001 - match lax.pcast
        return x

__all__ = [
    "HierarchicalResult",
    "hierarchical_assign",
    "make_mesh",
    "shard_cost",
    "sharded_hierarchical_assign",
    "sharded_scaling_sinkhorn",
    "sharded_sinkhorn",
    "sharded_sinkhorn_assign",
]


def __getattr__(name):
    # Lazy: hierarchical pulls in the ops stack; keep `import rio_tpu.parallel`
    # light for users who only need the mesh helpers.
    if name in ("HierarchicalResult", "hierarchical_assign", "sharded_hierarchical_assign"):
        from . import hierarchical

        return getattr(hierarchical, name)
    if name == "multihost":
        # importlib, not `from . import`: the from-import re-enters this
        # __getattr__ while the attribute is still unset (RecursionError).
        import importlib

        return importlib.import_module(".multihost", __name__)
    raise AttributeError(name)


def make_mesh(devices=None, *, obj_axis: int | None = None) -> Mesh:
    """Build a 2-D ``("obj", "node")`` mesh over the given (or all) devices.

    The object axis gets the larger factor — the object count dominates the
    node count by ~4 orders of magnitude (10M x 1k), so row sharding carries
    almost all the memory.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if obj_axis is None:
        obj_axis = n
        node_axis = 1
        # Prefer a 2-D factorization when n is not prime, e.g. 8 -> (4, 2).
        for cand in range(int(math.isqrt(n)), 0, -1):
            if n % cand == 0:
                obj_axis, node_axis = n // cand, cand
                break
    else:
        node_axis = n // obj_axis
    import numpy as np

    return Mesh(np.asarray(devices).reshape(obj_axis, node_axis), ("obj", "node"))


def shard_cost(mesh: Mesh, cost: jax.Array) -> jax.Array:
    """Place a cost matrix on the mesh, rows over "obj", cols over "node"."""
    return jax.device_put(cost, NamedSharding(mesh, P("obj", "node")))


def _dist_lse(z_local: jax.Array, axis: int, mesh_axis: str) -> jax.Array:
    """Numerically stable log-sum-exp over a sharded axis.

    Local LSE along ``axis``, then the standard two-collective combine:
    global max via ``pmax`` and a ``psum`` of re-based exponentials over the
    mesh axis. Both collectives are single-hop ICI reductions.
    """
    local_max = jnp.max(z_local, axis=axis)
    gmax = lax.pmax(local_max, mesh_axis)
    safe = jnp.where(jnp.isfinite(gmax), gmax, 0.0)
    local_sum = jnp.sum(jnp.exp(z_local - jnp.expand_dims(safe, axis)), axis=axis)
    gsum = lax.psum(local_sum, mesh_axis)
    return safe + jnp.log(jnp.maximum(gsum, 1e-30))


def sharded_sinkhorn(
    mesh: Mesh,
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
) -> tuple[jax.Array, jax.Array]:
    """Log-domain Sinkhorn with the cost matrix sharded on both mesh axes.

    Returns (f, g) potentials, sharded P("obj") / P("node") respectively.
    Semantics match :func:`rio_tpu.ops.sinkhorn.sinkhorn`; see there for the
    math. Row updates reduce over the "node" axis, column updates over the
    "obj" axis — each iteration is two ICI reductions per direction.
    """

    def solve(c, a, b):
        c = c.astype(jnp.float32)
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        total_a = jnp.maximum(lax.psum(jnp.sum(a), "obj"), 1e-30)
        total_b = jnp.maximum(lax.psum(jnp.sum(b), "node"), 1e-30)
        a = a / total_a
        b = b / total_b
        log_a = jnp.where(a > 0, jnp.log(jnp.maximum(a, 1e-30)), -jnp.inf)
        log_b = jnp.where(b > 0, jnp.log(jnp.maximum(b, 1e-30)), -jnp.inf)

        def body(carry, _):
            f, g = carry
            f = eps * (log_a - _dist_lse((g[None, :] - c) / eps, 1, "node"))
            f = jnp.where(jnp.isfinite(log_a), f, -jnp.inf)
            g = eps * (log_b - _dist_lse((f[:, None] - c) / eps, 0, "obj"))
            g = jnp.where(jnp.isfinite(log_b), g, -jnp.inf)
            return (f, g), None

        # Mark the carry as varying over its mesh axis up front (JAX >= 0.9
        # shard_map tracks manual-axis variance through scan).
        f0 = _pcast(jnp.zeros(c.shape[0], jnp.float32), ("obj",), to="varying")
        g0 = _pcast(jnp.zeros(c.shape[1], jnp.float32), ("node",), to="varying")
        (f, g), _ = lax.scan(body, (f0, g0), None, length=n_iters)
        return f, g

    fn = shard_map(
        solve,
        mesh=mesh,
        in_specs=(P("obj", "node"), P("obj"), P("node")),
        out_specs=(P("obj"), P("node")),
    )
    return fn(cost, row_mass, col_capacity)


def sharded_scaling_sinkhorn(
    mesh: Mesh,
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
    kernel_dtype=jnp.bfloat16,
) -> tuple[jax.Array, jax.Array]:
    """Scaling-form Sinkhorn-Knopp sharded over the 2-D mesh.

    The kernel ``K = exp(-C/eps)`` is built shard-local from the sharded
    cost (one transcendental sweep total); each iteration is two local
    matvec partials + one ``psum`` per direction — no per-iteration
    transcendentals, matching :func:`rio_tpu.ops.scaling.scaling_sinkhorn`
    semantics (returns log-domain potentials (f, g)).
    """

    def solve(c, a, b):
        c = c.astype(jnp.float32)
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
        a = a / jnp.maximum(lax.psum(jnp.sum(a), "obj"), 1e-30)
        b = b / jnp.maximum(lax.psum(jnp.sum(b), "node"), 1e-30)
        # PER-ROW gauge shift (pmin across node shards): every row keeps its
        # best entry at exp(0)=1, so no row underflows to all-zeros however
        # wide the cost range — same stabilization as scaling_core (a global
        # shift breaks tail rows once range/eps >> 88); see ops/scaling.py.
        shift = lax.pmin(jnp.min(c, axis=1, keepdims=True), "node")
        shift = jnp.where(jnp.isfinite(shift), shift, 0.0)
        K = jnp.exp(-(c - shift) / eps).astype(kernel_dtype)

        def body(carry, _):
            u, v = carry
            Kv = lax.psum(
                jnp.matmul(K, v.astype(kernel_dtype), preferred_element_type=jnp.float32),
                "node",
            )
            u = jnp.where(a > 0, a / jnp.maximum(Kv, 1e-30), 0.0)
            KTu = lax.psum(
                jnp.matmul(u.astype(kernel_dtype), K, preferred_element_type=jnp.float32),
                "obj",
            )
            v = jnp.where(b > 0, b / jnp.maximum(KTu, 1e-30), 0.0)
            return (u, v), None

        u0 = _pcast(jnp.zeros(c.shape[0], jnp.float32), ("obj",), to="varying")
        v0 = _pcast(jnp.ones(c.shape[1], jnp.float32), ("node",), to="varying")
        (u, v), _ = lax.scan(body, (u0, v0), None, length=n_iters)
        f = jnp.where(
            u > 0, eps * jnp.log(jnp.maximum(u, 1e-30)) + shift[:, 0], -jnp.inf
        )
        g = jnp.where(v > 0, eps * jnp.log(jnp.maximum(v, 1e-30)), -jnp.inf)
        return f, g

    fn = shard_map(
        solve,
        mesh=mesh,
        in_specs=(P("obj", "node"), P("obj"), P("node")),
        out_specs=(P("obj"), P("node")),
    )
    return fn(cost, row_mass, col_capacity)


@jax.jit
def _assign_with_g(cost, g):
    g = jnp.where(jnp.isfinite(g), g, -jnp.inf)
    return jnp.argmin(cost.astype(jnp.float32) - g[None, :], axis=1).astype(jnp.int32)


def sharded_sinkhorn_assign(
    mesh: Mesh,
    cost: jax.Array,
    row_mass: jax.Array,
    col_capacity: jax.Array,
    *,
    eps: float = 0.05,
    n_iters: int = 50,
) -> jax.Array:
    """Sharded solve + assignment extraction.

    The extraction (``argmin_j cost - g``) runs under plain jit with the cost
    still sharded P("obj", "node"): XLA all-gathers the small ``g`` vector
    along "node" and reduces — no hand-written collective needed.
    """
    f, g = sharded_sinkhorn(
        mesh, cost, row_mass, col_capacity, eps=eps, n_iters=n_iters
    )
    return _assign_with_g(cost, g)
