"""Multi-host bring-up for the solver plane (SPMD over ICI + DCN).

The reference's cross-host scaling story is tokio TCP + SQL rendezvous for
the control plane and nothing for compute (``rio-rs/src/service.rs:370-378``
is its transport ceiling). rio-tpu's compute plane scales the TPU way
instead: every host runs the SAME program, :func:`initialize` wires the
hosts into one multi-controller jax runtime, and the mesh/shard_map code in
:mod:`rio_tpu.parallel` then spans all hosts unchanged — ``jax.devices()``
becomes the global device set, XLA routes the Sinkhorn ``psum``/``pmax``
collectives over ICI within a slice and DCN across slices, and no solver
code differs between 1 and N hosts. (This replaces what NCCL/MPI init +
communicator plumbing does for the reference stack's GPU cousins.)

Per-host data feeding: each host holds only its own objects (its servers'
directory shard). :func:`distributed_array` assembles the global sharded
array from per-host shards without ever materializing the global array on
any one host — the multi-host analog of ``jax.device_put``.

Bring-up recipe (one process per host, e.g. under a process manager or the
TPU pod runtime):

    from rio_tpu.parallel import make_mesh, multihost

    multihost.initialize()          # env-driven on TPU pods; explicit
                                    # coordinator args elsewhere
    mesh = make_mesh()              # spans ALL hosts' devices
    obj_feat = multihost.distributed_array(
        mesh, P("obj", None), local_obj_feat)   # this host's rows only
    res = sharded_hierarchical_assign(mesh, obj_feat, ...)

Single-process (tests, one chip, CPU mesh) every function degrades to the
local equivalent, so the same program text runs everywhere.
"""

from __future__ import annotations

import logging
import os

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("rio_tpu.parallel.multihost")

__all__ = ["initialize", "is_multihost", "distributed_array", "process_rows"]


def _already_initialized() -> bool:
    """Whether jax.distributed.initialize has run, WITHOUT initializing
    the backend (the public probes all do)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # internal layout changed; assume not initialized
        return False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> bool:
    """Idempotent :func:`jax.distributed.initialize` wrapper.

    With no arguments, jax reads the cluster environment (TPU pod runtime,
    SLURM, etc.); pass explicit coordinator args everywhere else. Safe to
    call unconditionally at server startup:

    * already initialized -> no-op;
    * single-process with no cluster env and no args -> no-op (jax would
      otherwise raise on the missing coordinator);
    * returns True iff the runtime is multi-process afterwards.

    NOTE this function must not touch the jax backend before calling
    ``jax.distributed.initialize`` — even ``jax.process_count()``
    initializes the single-process backend and silently breaks the
    multi-controller bring-up — hence the internal-state probe.
    """
    if _already_initialized():
        return jax.process_count() > 1
    explicit = coordinator_address is not None
    cluster_env = any(
        os.environ.get(k)
        for k in (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "TPU_WORKER_HOSTNAMES",
            "SLURM_JOB_ID",
        )
    )
    if not explicit and not cluster_env:
        log.debug("no coordinator configured; staying single-process")
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except RuntimeError as e:
        # Double-initialize (e.g. two Servers in one process) is benign.
        if "already" not in str(e).lower():
            raise
    return jax.process_count() > 1


def is_multihost() -> bool:
    return jax.process_count() > 1


def process_rows(n_global: int, mesh: Mesh, axis: str = "obj") -> slice:
    """The global row range this PROCESS must supply for an ``axis``-sharded
    array of ``n_global`` rows (rows are laid out in mesh-axis order, the
    same order :func:`distributed_array` assembles them).
    """
    axis_size = mesh.shape[axis]
    per_shard, rem = divmod(n_global, axis_size)
    assert rem == 0, (n_global, axis_size)
    # Which shard indices along `axis` live on this process's devices?
    axis_pos = list(mesh.axis_names).index(axis)
    local = set()
    import numpy as np

    dev_grid = np.asarray(mesh.devices)
    for idx in np.ndindex(dev_grid.shape):
        if dev_grid[idx].process_index == jax.process_index():
            local.add(idx[axis_pos])
    lo, hi = min(local), max(local)
    assert local == set(range(lo, hi + 1)), "non-contiguous process shards"
    return slice(lo * per_shard, (hi + 1) * per_shard)


def distributed_array(mesh: Mesh, spec: P, local_data) -> jax.Array:
    """Assemble a globally-sharded array from per-process local shards.

    ``local_data`` is this process's slice (see :func:`process_rows`);
    no host ever materializes the global array. Single-process this is
    exactly ``jax.device_put(local_data, NamedSharding(mesh, spec))``.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local_data, sharding)
    return jax.make_array_from_process_local_data(sharding, local_data)
