"""Multi-host bring-up for the solver plane (SPMD over ICI + DCN).

The reference's cross-host scaling story is tokio TCP + SQL rendezvous for
the control plane and nothing for compute (``rio-rs/src/service.rs:370-378``
is its transport ceiling). rio-tpu's compute plane scales the TPU way
instead: every host runs the SAME program, :func:`initialize` wires the
hosts into one multi-controller jax runtime, and the mesh/shard_map code in
:mod:`rio_tpu.parallel` then spans all hosts unchanged — ``jax.devices()``
becomes the global device set, XLA routes the Sinkhorn ``psum``/``pmax``
collectives over ICI within a slice and DCN across slices, and no solver
code differs between 1 and N hosts. (This replaces what NCCL/MPI init +
communicator plumbing does for the reference stack's GPU cousins.)

Per-host data feeding: each host holds only its own objects (its servers'
directory shard). :func:`distributed_array` assembles the global sharded
array from per-host shards without ever materializing the global array on
any one host — the multi-host analog of ``jax.device_put``.

Bring-up recipe (one process per host, e.g. under a process manager or the
TPU pod runtime):

    from rio_tpu.parallel import make_mesh, multihost

    multihost.initialize()          # env-driven on TPU pods; explicit
                                    # coordinator args elsewhere
    mesh = make_mesh()              # spans ALL hosts' devices
    obj_feat = multihost.distributed_array(
        mesh, P("obj", None), local_obj_feat)   # this host's rows only
    res = sharded_hierarchical_assign(mesh, obj_feat, ...)

Single-process (tests, one chip, CPU mesh) every function degrades to the
local equivalent, so the same program text runs everywhere.
"""

from __future__ import annotations

import logging

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("rio_tpu.parallel.multihost")

__all__ = ["initialize", "is_multihost", "distributed_array", "process_rows"]


def _already_initialized() -> bool:
    """Whether jax.distributed.initialize has run, WITHOUT initializing
    the backend (the public probes all do)."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:  # internal layout changed; assume not initialized
        return False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> bool:
    """Idempotent :func:`jax.distributed.initialize` wrapper.

    With no arguments, jax reads the cluster environment (TPU pod runtime,
    SLURM, etc.); pass explicit coordinator args everywhere else. Safe to
    call unconditionally at server startup:

    * already initialized -> no-op;
    * single-process with no cluster env and no args -> no-op (jax would
      otherwise raise on the missing coordinator);
    * returns True iff the runtime is multi-process afterwards.

    NOTE this function must not touch the jax backend before calling
    ``jax.distributed.initialize`` — even ``jax.process_count()``
    initializes the single-process backend and silently breaks the
    multi-controller bring-up — hence the internal-state probe.
    """
    if _already_initialized():
        return jax.process_count() > 1
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except ValueError as e:
        # jax runs its cluster auto-detection inside initialize(); with no
        # explicit coordinator and no recognizable cluster it raises this
        # — which IS the single-process answer, not an error. (Env-var
        # sniffing is not a substitute: e.g. this image's sitecustomize
        # exports TPU_WORKER_HOSTNAMES=localhost without any cluster.)
        if (
            coordinator_address is None
            and num_processes is None
            and process_id is None
            and "coordinator_address" in str(e)
        ):
            log.debug("no cluster detected; staying single-process")
            return False
        # Any explicit multi-process intent (world size / rank given but
        # the coordinator missing) must fail loudly, not downgrade.
        raise
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg:
            pass  # double-initialize (e.g. two Servers in one process)
        elif "before" in msg and coordinator_address is None:
            # The backend is already up (long-lived process, test runner):
            # opportunistic env-driven bring-up is no longer possible —
            # stay in whatever mode the process is in. With an EXPLICIT
            # coordinator this is a real ordering bug and still raises.
            # On what LOOKS like a cluster, a silent downgrade to N
            # independent single-host programs would be invisible in
            # production — warn loudly there. (Env sniffing is fine for
            # log-level selection; a false negative only softens the log.)
            import os

            clusterish = any(
                os.environ.get(k)
                for k in (
                    "JAX_COORDINATOR_ADDRESS",
                    "COORDINATOR_ADDRESS",
                    "MEGASCALE_COORDINATOR_ADDRESS",
                    "SLURM_JOB_ID",
                )
            )
            (log.warning if clusterish else log.debug)(
                "jax backend was initialized before multihost.initialize();"
                " staying single-process. For multi-host, call initialize()"
                " before ANY jax backend use (jax.devices, computations)."
            )
            return jax.process_count() > 1
        else:
            raise
    return jax.process_count() > 1


def is_multihost() -> bool:
    """True iff this process is part of a multi-controller runtime.

    Safe to call before :func:`initialize`: probes the distributed state
    WITHOUT touching the jax backend (``jax.process_count()`` would boot
    the single-process backend and break a later bring-up).
    """
    if not _already_initialized():
        return False
    return jax.process_count() > 1


def process_rows(
    n_global: int, mesh: Mesh, axis: str | tuple[str, ...] | None = None
) -> slice:
    """The global row range this PROCESS must supply for a row-sharded
    array of ``n_global`` rows.

    ``axis`` must name the mesh axes the ROW dimension is sharded over,
    exactly as in the ``PartitionSpec`` fed to :func:`distributed_array` —
    the default (``None``) means ALL mesh axes in order, matching the
    ``P(mesh.axis_names, None)`` layout the sharded solvers use. Rows are
    laid out in mesh-axis order, the same order
    :func:`distributed_array` assembles them.
    """
    import numpy as np

    if axis is None:
        axes = tuple(mesh.axis_names)
    elif isinstance(axis, str):
        axes = (axis,)
    else:
        axes = tuple(axis)
    sizes = [mesh.shape[a] for a in axes]
    n_shards = int(np.prod(sizes))
    per_shard, rem = divmod(n_global, n_shards)
    assert rem == 0, (n_global, n_shards)
    # Which row-shard indices live on this process's devices? A device at
    # grid position idx owns row shard ravel(idx restricted to `axes`).
    names = list(mesh.axis_names)
    axis_pos = [names.index(a) for a in axes]
    local = set()
    dev_grid = np.asarray(mesh.devices)
    for idx in np.ndindex(dev_grid.shape):
        if dev_grid[idx].process_index == jax.process_index():
            coords = tuple(idx[p] for p in axis_pos)
            local.add(int(np.ravel_multi_index(coords, sizes)))
    if not local:
        raise ValueError(
            f"process {jax.process_index()} owns no devices in this mesh "
            f"({dict(mesh.shape)}); build the mesh over devices from every "
            f"participating process"
        )
    lo, hi = min(local), max(local)
    assert local == set(range(lo, hi + 1)), "non-contiguous process shards"
    return slice(lo * per_shard, (hi + 1) * per_shard)


def distributed_array(mesh: Mesh, spec: P, local_data) -> jax.Array:
    """Assemble a globally-sharded array from per-process local shards.

    ``local_data`` is this process's slice (see :func:`process_rows`);
    no host ever materializes the global array. Single-process this is
    exactly ``jax.device_put(local_data, NamedSharding(mesh, spec))``.
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local_data, sharding)
    return jax.make_array_from_process_local_data(sharding, local_data)
