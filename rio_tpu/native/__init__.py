"""ctypes binding for the C++ data plane (``native/rio_native.cc``).

The native library provides:

* a wire codec for the framework envelopes (exactly the byte layout of
  :mod:`rio_tpu.protocol`) plus an incremental frame reader, and
* an epoll connection engine that owns sockets + framing on a native
  thread (see :mod:`rio_tpu.native.transport`).

Everything degrades gracefully: :func:`get` returns ``None`` when the
library can't be built/loaded (or ``RIO_TPU_NATIVE=0``), and callers fall
back to the pure-Python paths, which are wire-compatible.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

from ..errors import SerializationError

log = logging.getLogger("rio_tpu.native")

_SRC_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_SRC = _SRC_DIR / "rio_native.cc"
_SO = _SRC_DIR / "librio_native.so"

_lock = threading.Lock()
_lib: "NativeLib | None | bool" = False  # False = not attempted yet


class RnEvent(ctypes.Structure):
    _fields_ = [
        ("type", ctypes.c_uint32),
        ("pad", ctypes.c_uint32),
        ("conn", ctypes.c_uint64),
        ("data", ctypes.POINTER(ctypes.c_uint8)),
        ("len", ctypes.c_uint64),
    ]


EV_FRAME = 1
EV_CLOSED = 2
EV_OPENED = 3

_U8P = ctypes.POINTER(ctypes.c_uint8)
_U32 = ctypes.c_uint32
_U32P = ctypes.POINTER(ctypes.c_uint32)


def _ensure_built() -> Path | None:
    """Compile the shared library if missing or stale; None on failure."""
    env_lib = os.environ.get("RIO_TPU_NATIVE_LIB")
    if env_lib:
        return Path(env_lib) if Path(env_lib).exists() else None
    if not _SRC.exists():
        return _SO if _SO.exists() else None
    if _SO.exists() and _SO.stat().st_mtime >= _SRC.stat().st_mtime:
        return _SO
    try:
        subprocess.run(
            [
                os.environ.get("CXX", "g++"),
                "-O2", "-std=c++17", "-fPIC", "-Wall", "-pthread",
                "-shared", "-o", str(_SO), str(_SRC),
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"")
        log.warning("native build failed: %s %s", e, detail)
        return None
    return _SO


class NativeLib:
    """Typed wrapper over the loaded shared library."""

    def __init__(self, dll: ctypes.CDLL) -> None:
        self._dll = dll
        dll.rn_free.argtypes = [_U8P]
        dll.rn_free.restype = None

        enc_sig = {
            "rn_encode_request_frame": 4,
            "rn_encode_subscribe_frame": 2,
            "rn_encode_subresponse_ok_frame": 2,
        }
        for name, n_bufs in enc_sig.items():
            fn = getattr(dll, name)
            fn.argtypes = [ctypes.c_char_p, _U32] * n_bufs + [_U32P]
            fn.restype = _U8P
        dll.rn_encode_response_ok_frame.argtypes = [ctypes.c_char_p, _U32, _U32P]
        dll.rn_encode_response_ok_frame.restype = _U8P
        for name in ("rn_encode_response_err_frame", "rn_encode_subresponse_err_frame"):
            fn = getattr(dll, name)
            fn.argtypes = [_U32, ctypes.c_char_p, _U32, ctypes.c_char_p, _U32, _U32P]
            fn.restype = _U8P

        dll.rn_encode_request_frame_traced.argtypes = (
            [ctypes.c_char_p, _U32] * 6 + [ctypes.c_int32, _U32P]
        )
        dll.rn_encode_request_frame_traced.restype = _U8P

        try:
            # Command frames (KIND_COMMAND, streams/sagas PR): absent from
            # env-pinned prebuilt libraries, which then report
            # has_command=False and callers stay on the Python codec.
            dll.rn_encode_command_frame.argtypes = (
                [ctypes.c_char_p, _U32] * 3 + [_U32P]
            )
            dll.rn_encode_command_frame.restype = _U8P
            dll.rn_encode_command_frame_traced.argtypes = (
                [ctypes.c_char_p, _U32] * 5 + [ctypes.c_int32, _U32P]
            )
            dll.rn_encode_command_frame_traced.restype = _U8P
            self.has_command = True
        except AttributeError:
            self.has_command = False

        dll.rn_decode_inbound.argtypes = [
            ctypes.c_char_p, _U32, _U32P, _U32P, ctypes.POINTER(ctypes.c_int32),
        ]
        dll.rn_decode_inbound.restype = ctypes.c_int

        try:
            # QoS request frames (tenant/priority/deadline_ms, ISSUE 20):
            # absent from env-pinned prebuilt libraries, which then report
            # has_qos=False — callers stay on the Python codec and the
            # parity tests skip.
            dll.rn_encode_request_frame_qos.argtypes = (
                [ctypes.c_char_p, _U32] * 6
                + [ctypes.c_int32, ctypes.c_char_p, _U32,
                   ctypes.c_uint64, ctypes.c_uint64, _U32P]
            )
            dll.rn_encode_request_frame_qos.restype = _U8P
            dll.rn_decode_inbound_qos.argtypes = [
                ctypes.c_char_p, _U32, _U32P, _U32P,
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
            ]
            dll.rn_decode_inbound_qos.restype = ctypes.c_int
            self.has_qos = True
        except AttributeError:
            self.has_qos = False
        for name in ("rn_decode_response", "rn_decode_subresponse"):
            fn = getattr(dll, name)
            fn.argtypes = [ctypes.c_char_p, _U32, _U32P, _U32P, _U32P]
            fn.restype = ctypes.c_int

        dll.rn_reader_new.argtypes = []
        dll.rn_reader_new.restype = ctypes.c_void_p
        dll.rn_reader_free.argtypes = [ctypes.c_void_p]
        dll.rn_reader_free.restype = None
        dll.rn_reader_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p, _U32]
        dll.rn_reader_feed.restype = ctypes.c_int
        dll.rn_reader_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p),
            _U32P,
        ]
        dll.rn_reader_next.restype = ctypes.c_int

        dll.rn_engine_create.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint16)]
        dll.rn_engine_create.restype = ctypes.c_void_p
        try:
            # Newer ABI with the SO_REUSEPORT flag; absent from env-pinned
            # prebuilt libraries (RIO_TPU_NATIVE_LIB), which then refuse
            # reuse_port loudly in the transport instead of ignoring it.
            dll.rn_engine_create_opt.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint16), ctypes.c_int32,
            ]
            dll.rn_engine_create_opt.restype = ctypes.c_void_p
            self.has_engine_opt = True
        except AttributeError:
            self.has_engine_opt = False
        dll.rn_engine_notify_fd.argtypes = [ctypes.c_void_p]
        dll.rn_engine_notify_fd.restype = ctypes.c_int
        dll.rn_engine_port.argtypes = [ctypes.c_void_p]
        dll.rn_engine_port.restype = ctypes.c_uint16
        dll.rn_engine_start.argtypes = [ctypes.c_void_p]
        dll.rn_engine_start.restype = None
        dll.rn_engine_drain.argtypes = [ctypes.c_void_p, ctypes.POINTER(RnEvent), ctypes.c_int]
        dll.rn_engine_drain.restype = ctypes.c_int
        dll.rn_engine_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, _U32]
        dll.rn_engine_send.restype = None
        dll.rn_engine_backlog.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        dll.rn_engine_backlog.restype = ctypes.c_longlong
        dll.rn_engine_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint16]
        dll.rn_engine_connect.restype = ctypes.c_uint64
        dll.rn_engine_close_conn.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        dll.rn_engine_close_conn.restype = None
        dll.rn_engine_stop.argtypes = [ctypes.c_void_p]
        dll.rn_engine_stop.restype = None
        dll.rn_engine_free.argtypes = [ctypes.c_void_p]
        dll.rn_engine_free.restype = None

    # -- codec ---------------------------------------------------------

    def _take(self, ptr, n: int) -> bytes:
        out = ctypes.string_at(ptr, n)
        self._dll.rn_free(ptr)
        return out

    def encode_request_frame(self, ht: bytes, hid: bytes, mt: bytes, payload: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_request_frame(
            ht, len(ht), hid, len(hid), mt, len(mt), payload, len(payload), ctypes.byref(n)
        )
        if not ptr:
            raise SerializationError("rn_encode_request_frame: frame too large")
        return self._take(ptr, n.value)

    def encode_request_frame_traced(
        self, ht: bytes, hid: bytes, mt: bytes, payload: bytes,
        trace_id: bytes, span_id: bytes, sampled: bool,
    ) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_request_frame_traced(
            ht, len(ht), hid, len(hid), mt, len(mt), payload, len(payload),
            trace_id, len(trace_id), span_id, len(span_id),
            1 if sampled else 0, ctypes.byref(n),
        )
        if not ptr:
            raise SerializationError("rn_encode_request_frame_traced: frame too large")
        return self._take(ptr, n.value)

    def encode_command_frame(self, cmd: bytes, subject: bytes, payload: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_command_frame(
            cmd, len(cmd), subject, len(subject), payload, len(payload), ctypes.byref(n)
        )
        if not ptr:
            raise SerializationError("rn_encode_command_frame: frame too large")
        return self._take(ptr, n.value)

    def encode_command_frame_traced(
        self, cmd: bytes, subject: bytes, payload: bytes,
        trace_id: bytes, span_id: bytes, sampled: bool,
    ) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_command_frame_traced(
            cmd, len(cmd), subject, len(subject), payload, len(payload),
            trace_id, len(trace_id), span_id, len(span_id),
            1 if sampled else 0, ctypes.byref(n),
        )
        if not ptr:
            raise SerializationError("rn_encode_command_frame_traced: frame too large")
        return self._take(ptr, n.value)

    def encode_request_frame_qos(
        self, ht: bytes, hid: bytes, mt: bytes, payload: bytes,
        trace_id: bytes, span_id: bytes, sampled: int,
        tenant: bytes, priority: int, deadline_ms: int,
    ) -> bytes:
        """QoS-classified request frame; ``sampled`` < 0 means untraced
        (the wire carries a nil trace slot to hold position)."""
        n = _U32(0)
        ptr = self._dll.rn_encode_request_frame_qos(
            ht, len(ht), hid, len(hid), mt, len(mt), payload, len(payload),
            trace_id, len(trace_id), span_id, len(span_id), sampled,
            tenant, len(tenant), priority, deadline_ms, ctypes.byref(n),
        )
        if not ptr:
            raise SerializationError("rn_encode_request_frame_qos: frame too large")
        return self._take(ptr, n.value)

    def encode_subscribe_frame(self, ht: bytes, hid: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_subscribe_frame(ht, len(ht), hid, len(hid), ctypes.byref(n))
        if not ptr:
            raise SerializationError("rn_encode_subscribe_frame: frame too large")
        return self._take(ptr, n.value)

    def encode_response_ok_frame(self, body: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_response_ok_frame(body, len(body), ctypes.byref(n))
        if not ptr:
            raise SerializationError("rn_encode_response_ok_frame: frame too large")
        return self._take(ptr, n.value)

    def encode_response_err_frame(self, kind: int, detail: bytes, payload: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_response_err_frame(
            kind, detail, len(detail), payload, len(payload), ctypes.byref(n)
        )
        if not ptr:
            raise SerializationError("rn_encode_response_err_frame: frame too large")
        return self._take(ptr, n.value)

    def encode_subresponse_ok_frame(self, message_type: bytes, body: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_subresponse_ok_frame(
            message_type, len(message_type), body, len(body), ctypes.byref(n)
        )
        if not ptr:
            raise SerializationError("rn_encode_subresponse_ok_frame: frame too large")
        return self._take(ptr, n.value)

    def encode_subresponse_err_frame(self, kind: int, detail: bytes, payload: bytes) -> bytes:
        n = _U32(0)
        ptr = self._dll.rn_encode_subresponse_err_frame(
            kind, detail, len(detail), payload, len(payload), ctypes.byref(n)
        )
        if not ptr:
            raise SerializationError("rn_encode_subresponse_err_frame: frame too large")
        return self._take(ptr, n.value)

    def decode_inbound(self, payload: bytes):
        """Returns ``(0, ht, hid, mt, body)`` (traced frames append
        ``tid, sid, sampled``) | ``(1, ht, hid)`` |
        ``(2, cmd, subject, body[, tid, sid, sampled])`` | None."""
        offs = (_U32 * 6)()
        lens = (_U32 * 6)()
        sampled = ctypes.c_int32(-1)
        rc = self._dll.rn_decode_inbound(
            payload, len(payload), offs, lens, ctypes.byref(sampled)
        )
        if rc < 0:
            return None
        n_fields = 4 if rc == 0 else 3 if rc == 2 else 2
        spans = [payload[offs[i] : offs[i] + lens[i]] for i in range(n_fields)]
        if rc in (0, 2) and sampled.value >= 0:
            spans.extend(
                (
                    payload[offs[4] : offs[4] + lens[4]],
                    payload[offs[5] : offs[5] + lens[5]],
                    bool(sampled.value),
                )
            )
        return (rc, *spans)

    def decode_inbound_qos(self, payload: bytes):
        """QoS-aware inbound decode. For requests, always returns the full
        11-tuple ``(0, ht, hid, mt, body, tid, sid, sampled, tenant,
        priority, deadline_ms)`` where ``sampled`` is None on untraced
        frames; other kinds match :meth:`decode_inbound`. None on error."""
        offs = (_U32 * 7)()
        lens = (_U32 * 7)()
        sampled = ctypes.c_int32(-1)
        qos = (ctypes.c_uint64 * 2)()
        rc = self._dll.rn_decode_inbound_qos(
            payload, len(payload), offs, lens, ctypes.byref(sampled), qos
        )
        if rc < 0:
            return None
        if rc == 0:
            spans = [payload[offs[i] : offs[i] + lens[i]] for i in range(4)]
            traced = sampled.value >= 0
            return (
                0,
                *spans,
                payload[offs[4] : offs[4] + lens[4]] if traced else b"",
                payload[offs[5] : offs[5] + lens[5]] if traced else b"",
                bool(sampled.value) if traced else None,
                payload[offs[6] : offs[6] + lens[6]],
                int(qos[0]),
                int(qos[1]),
            )
        n_fields = 3 if rc == 2 else 2
        spans = [payload[offs[i] : offs[i] + lens[i]] for i in range(n_fields)]
        if rc == 2 and sampled.value >= 0:
            spans.extend(
                (
                    payload[offs[4] : offs[4] + lens[4]],
                    payload[offs[5] : offs[5] + lens[5]],
                    bool(sampled.value),
                )
            )
        return (rc, *spans)

    def decode_response(self, payload: bytes):
        """Returns ``(True, body)`` | ``(False, kind, detail, err_payload)`` | None."""
        kind = _U32(0)
        offs = (_U32 * 2)()
        lens = (_U32 * 2)()
        rc = self._dll.rn_decode_response(payload, len(payload), ctypes.byref(kind), offs, lens)
        if rc < 0:
            return None
        if rc == 1:
            return (True, payload[offs[0] : offs[0] + lens[0]])
        return (
            False,
            kind.value,
            payload[offs[0] : offs[0] + lens[0]],
            payload[offs[1] : offs[1] + lens[1]],
        )

    def decode_subresponse(self, payload: bytes):
        """Returns ``(True, mt, body)`` | ``(False, kind, detail, err_payload)`` | None."""
        kind = _U32(0)
        offs = (_U32 * 2)()
        lens = (_U32 * 2)()
        rc = self._dll.rn_decode_subresponse(payload, len(payload), ctypes.byref(kind), offs, lens)
        if rc < 0:
            return None
        if rc == 1:
            return (
                True,
                payload[offs[0] : offs[0] + lens[0]],
                payload[offs[1] : offs[1] + lens[1]],
            )
        return (
            False,
            kind.value,
            payload[offs[0] : offs[0] + lens[0]],
            payload[offs[1] : offs[1] + lens[1]],
        )


class NativeFrameReader:
    """Incremental frame decoder backed by the C++ reader.

    Drop-in for :class:`rio_tpu.codec.FrameReader`.
    """

    def __init__(self, lib: NativeLib | None = None) -> None:
        self._lib = lib or get()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._handle = self._lib._dll.rn_reader_new()

    def feed(self, data: bytes) -> list[bytes]:
        dll = self._lib._dll
        n = dll.rn_reader_feed(self._handle, data, len(data))
        if n < 0:
            raise SerializationError("incoming frame too large")
        out: list[bytes] = []
        ptr = ctypes.c_void_p()
        ln = _U32(0)
        for _ in range(n):
            if not dll.rn_reader_next(self._handle, ctypes.byref(ptr), ctypes.byref(ln)):
                break
            out.append(ctypes.string_at(ptr, ln.value))
        return out

    def __del__(self) -> None:
        handle, self._handle = getattr(self, "_handle", None), None
        if handle and getattr(self, "_lib", None) is not None:
            self._lib._dll.rn_reader_free(handle)


def engine_profitable() -> bool:
    """Whether the ``auto`` transport should pick the C++ epoll engine.

    The engine's win is running sockets + framing on a separate OS thread,
    overlapping with the interpreter.  MEASURED on a single-core host that
    becomes a pure loss: every message pays ~4 eventfd wakeups / context
    switches of thread ping-pong with nothing to overlap (9.0k msgs/s
    native vs 25k asyncio on the bench box).  So ``auto`` only picks the
    engine when there is real parallelism to exploit; explicit
    ``transport="native"`` always honors the caller.  Override with
    ``RIO_TPU_FORCE_NATIVE=1`` for A/B measurements.
    """
    if os.environ.get("RIO_TPU_FORCE_NATIVE") == "1":
        return get() is not None
    if (os.cpu_count() or 1) < 2:
        return False
    return get() is not None


def get() -> NativeLib | None:
    """Load (building on demand) the native library; None when unavailable."""
    global _lib
    if _lib is not False:
        return _lib  # type: ignore[return-value]
    with _lock:
        if _lib is not False:
            return _lib  # type: ignore[return-value]
        if os.environ.get("RIO_TPU_NATIVE", "1") == "0":
            _lib = None
            return None
        path = _ensure_built()
        if path is None:
            _lib = None
            return None
        try:
            _lib = NativeLib(ctypes.CDLL(str(path)))
        except OSError as e:
            log.warning("failed to load %s: %s", path, e)
            _lib = None
    return _lib
