"""Server transport backed by the C++ epoll engine.

The engine (``native/rio_native.cc``) owns the listening socket, the
accepted connections, framing, and write backpressure on a dedicated
native thread — the counterpart of the reference's accept + per-connection
frame loops (``rio-rs/src/server.rs:285-305``, ``service.rs:370-459``).
Python only sees complete frame payloads (via an eventfd the asyncio loop
watches) and hands back complete response frames, so the per-byte work
never touches the interpreter.

Dispatch semantics match :meth:`rio_tpu.service.Service.run` exactly:
requests on one connection are answered in order, and a subscription
request switches the connection into streaming mode.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
from collections import deque
from time import perf_counter as _perf
from typing import TYPE_CHECKING, Callable

from ..message_router import MessageRouter
from ..spans import Phases, finish_request
from ..protocol import (
    CommandEnvelope,
    RequestEnvelope,
    ResponseEnvelope,
    ResponseError,
    SubscriptionRequest,
    UnknownFrameKind,
    decode_inbound,
    encode_response_frame,
    encode_subresponse_frame,
)
from . import EV_CLOSED, EV_FRAME, EV_OPENED, NativeLib, RnEvent, get

if TYPE_CHECKING:
    from ..service import Service

log = logging.getLogger("rio_tpu.native.transport")

_DRAIN_BATCH = 256
_MAX_PENDING_FRAMES = 1024  # per-conn cap (reference relies on TCP backpressure)
_MAX_CONCURRENT = 64  # per-conn in-flight handler cap (matches aio transport)
_MAX_WRITE_BACKLOG = 1 << 20  # pause subscription pumps past 1 MiB unsent

# Same knob as rio_tpu.aio: join a done-callback wave of completed HEAD
# responses into one engine.send (one mutex grab + eventfd kick, one write
# syscall) instead of one per frame. Concatenated length-prefixed frames
# are byte-identical on the wire.
_EGRESS_COALESCE = os.environ.get("RIO_TPU_EGRESS_COALESCE", "1") != "0"


class Engine:
    """Thin pythonic wrapper over the rn_engine_* C ABI."""

    def __init__(
        self, lib: NativeLib, host: str, port: int, reuse_port: bool = False
    ) -> None:
        self._lib = lib
        self._dll = lib._dll
        port_inout = ctypes.c_uint16(port)
        if reuse_port:
            if not getattr(lib, "has_engine_opt", False):
                raise OSError(
                    "reuse_port needs rn_engine_create_opt — rebuild native/ "
                    "(the env-pinned library predates it)"
                )
            self._handle = self._dll.rn_engine_create_opt(
                host.encode(), ctypes.byref(port_inout), 1
            )
        else:
            self._handle = self._dll.rn_engine_create(
                host.encode(), ctypes.byref(port_inout)
            )
        if not self._handle:
            raise OSError(f"rn_engine_create failed for {host}:{port}")
        self.port = port_inout.value
        self.notify_fd: int = self._dll.rn_engine_notify_fd(self._handle)
        self._events = (RnEvent * _DRAIN_BATCH)()

    def start(self) -> None:
        self._dll.rn_engine_start(self._handle)

    def drain(self) -> list[tuple[int, int, bytes]]:
        if self._handle is None:
            return []
        n = self._dll.rn_engine_drain(self._handle, self._events, _DRAIN_BATCH)
        out = []
        for i in range(n):
            ev = self._events[i]
            data = ctypes.string_at(ev.data, ev.len) if ev.len else b""
            out.append((ev.type, ev.conn, data))
        return out

    def send(self, conn: int, data: bytes) -> None:
        # Stragglers (e.g. a subscription pump racing shutdown) must not
        # pass NULL into the C ABI.
        if self._handle is not None:
            self._dll.rn_engine_send(self._handle, conn, data, len(data))

    def backlog(self, conn: int) -> int:
        if self._handle is None:
            return 0
        return int(self._dll.rn_engine_backlog(self._handle, conn))

    def close_conn(self, conn: int) -> None:
        if self._handle is not None:
            self._dll.rn_engine_close_conn(self._handle, conn)

    def shutdown(self) -> None:
        handle, self._handle = self._handle, None
        if handle:
            self._dll.rn_engine_free(handle)


class NativeClientConn:
    """One outbound connection managed by a :class:`ClientEngine`.

    Exposes the same surface the asyncio client connection offers
    (``roundtrip``/``read_frame``/``write``/``close``) including
    **pipelining**: concurrent roundtrips register futures in a FIFO deque
    and inbound frames resolve the oldest one inside the engine's event
    drain — the same design as :class:`rio_tpu.aio.ClientConnProtocol`.
    (A shared Queue was racy here: a parked getter woken by a response
    could be beaten to ``get_nowait`` by a roundtrip issued later,
    silently delivering the response to the wrong caller.)  A roundtrip
    cancelled mid-flight leaves its cancelled future in the deque; its
    response, when it arrives, is discarded rather than shifting every
    later match.
    """

    def __init__(self, engine: "ClientEngine", conn_id: int) -> None:
        self._engine = engine
        self._id = conn_id
        self._waiters: deque[asyncio.Future] = deque()  # FIFO roundtrips
        self._queue: deque[bytes] = deque()  # frames beyond waiters (subscribe)
        self.opened: asyncio.Future[bool] = asyncio.get_running_loop().create_future()
        self.closed = False
        self.delivered = 0  # inbound frames seen (client's progress signal)

    @property
    def pending(self) -> int:
        return len(self._waiters)

    def _deliver(self, payload: bytes) -> None:
        """Resolve the oldest pending roundtrip (engine drain context)."""
        self.delivered += 1
        if self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(payload)
            # else: that roundtrip was cancelled — this payload is its
            # orphaned response; drop it (each cancelled roundtrip is owed
            # exactly ONE orphan frame; never skip several slots per frame).
            return
        self._queue.append(payload)

    def _close_pending(self) -> None:
        for w in self._waiters:
            if not w.done():
                w.set_result(None)
        self._waiters.clear()

    async def roundtrip(self, frame_bytes: bytes) -> bytes:
        """Send one request; await its response (FIFO-matched)."""
        from ..errors import Disconnect

        if self.closed:
            raise Disconnect("native connection closed")
        fut = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        self._engine._engine.send(self._id, frame_bytes)
        payload = await fut
        if payload is None:
            raise Disconnect("connection closed mid-request")
        return payload

    async def read_frame(self) -> bytes | None:
        """Next inbound frame; None at EOF (subscription streaming)."""
        while not self._queue:
            if self.closed:
                return None
            fut = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            return await fut
        return self._queue.popleft()

    def write(self, frame_bytes: bytes) -> None:
        self._engine._engine.send(self._id, frame_bytes)

    def close(self) -> None:
        # Always drop: the C++ Conn/fd must be released even when the close
        # was peer-initiated (closed=True set by EV_CLOSED).  Locally
        # initiated closes emit no EV_CLOSED, so park-ed waiters must be
        # resolved here or they hang forever.
        self.closed = True
        self._close_pending()
        self._engine._drop(self._id)


class ClientEngine:
    """Client-side connection manager over a listener-less engine.

    One engine (one native IO thread) serves every outbound connection of
    a :class:`rio_tpu.Client`; frames and connect results come back
    through the same eventfd/drain bridge the server transport uses.
    """

    def __init__(self) -> None:
        lib = get()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._engine = Engine(lib, "", 0)
        self._conns: dict[int, NativeClientConn] = {}
        self._loop: asyncio.AbstractEventLoop | None = None
        self._started = False

    def _ensure_started(self) -> None:
        if self._started:
            return
        self._loop = asyncio.get_running_loop()
        self._loop.add_reader(self._engine.notify_fd, self._on_ready)
        self._engine.start()
        self._started = True

    def _on_ready(self) -> None:
        for _ in range(8):
            events = self._engine.drain()
            if not events:
                return
            self._dispatch_events(events)

    def _dispatch_events(self, events) -> None:
        for ev_type, conn, data in events:
            c = self._conns.get(conn)
            if c is None:
                continue
            if ev_type == EV_OPENED:
                if not c.opened.done():
                    c.opened.set_result(True)
            elif ev_type == EV_FRAME:
                c._deliver(data)
            elif ev_type == EV_CLOSED:
                c.closed = True
                if not c.opened.done():
                    c.opened.set_result(False)
                c._close_pending()
                self._conns.pop(conn, None)
                # Free the C++ side: a peer FIN takes the engine's soft-EOF
                # path, which keeps the fd open for writes until told
                # otherwise (server semantics); clients have no reply to
                # flush, so release it now.
                self._engine.close_conn(conn)

    async def connect(self, host: str, port: int, timeout: float) -> NativeClientConn:
        import socket as _socket

        from ..errors import ServerNotAvailable

        self._ensure_started()
        try:
            # Async resolution inside the timeout — a stuck resolver must
            # not stall the event loop (the asyncio path gets this from
            # open_connection).
            infos = await asyncio.wait_for(
                asyncio.get_running_loop().getaddrinfo(
                    host, port, family=_socket.AF_INET, type=_socket.SOCK_STREAM
                ),
                timeout,
            )
            quad = infos[0][4][0]
        except (OSError, asyncio.TimeoutError) as e:
            raise ServerNotAvailable(f"{host}:{port}: resolve failed: {e}") from e
        conn_id = self._lib._dll.rn_engine_connect(
            self._engine._handle, quad.encode(), port
        )
        if conn_id == 0:
            raise ServerNotAvailable(f"{host}:{port}: bad address")
        conn = NativeClientConn(self, conn_id)
        self._conns[conn_id] = conn
        try:
            ok = await asyncio.wait_for(conn.opened, timeout)
        except asyncio.TimeoutError:
            conn.close()
            raise ServerNotAvailable(f"{host}:{port}: connect timeout") from None
        if not ok:
            raise ServerNotAvailable(f"{host}:{port}: connection refused")
        return conn

    def _drop(self, conn_id: int) -> None:
        self._conns.pop(conn_id, None)
        self._engine.close_conn(conn_id)

    def close(self) -> None:
        if self._loop is not None and self._started:
            self._loop.remove_reader(self._engine.notify_fd)
        for c in list(self._conns.values()):
            c.closed = True
            c._close_pending()
        self._conns.clear()
        self._engine.shutdown()


class _ConnState:
    __slots__ = (
        "queue",
        "waiter",
        "eof",
        "worker",
        "streaming",
        "resp_q",
        "room",
        "broken",
        "ph_tick",
    )

    def __init__(self) -> None:
        # The worker drains ``queue`` and, at EOF, finishes in-flight
        # requests (FIFO) before exiting — matching the asyncio path where
        # a peer disconnect never cancels a running handler mid-mutation.
        # When span retention is armed the queue holds (payload, recv_ts)
        # tuples instead of raw payloads — the engine decodes frames later
        # in the worker, so receive time must ride along.
        self.queue: deque = deque()
        self.waiter: asyncio.Future | None = None
        self.eof = False
        self.worker: asyncio.Task | None = None
        self.streaming = False
        self.resp_q: deque[asyncio.Future] = deque()  # FIFO response slots
        self.room: asyncio.Future | None = None
        self.broken = False
        self.ph_tick = -1  # 1-in-8 phase-clock stride for untraced traffic

    def wake(self) -> None:
        w = self.waiter
        if w is not None and not w.done():
            self.waiter = None
            w.set_result(None)

    def wake_room(self) -> None:
        r = self.room
        if r is not None and not r.done():
            self.room = None
            r.set_result(None)


def _stamp_handler_end(task) -> None:
    """Done-callback for pipelined dispatch tasks carrying a phase clock."""
    task._rio_ph[0].handler_end = _perf()


class NativeServerTransport:
    """Accept/dispatch loop over the native engine.

    Mirrors the shape of ``asyncio.Server`` enough for
    :class:`rio_tpu.server.Server` (``close()`` + ``wait_closed()``).
    """

    def __init__(
        self,
        service_factory: Callable[[], "Service"],
        host: str,
        port: int,
        reuse_port: bool = False,
    ) -> None:
        lib = get()
        if lib is None:
            raise RuntimeError("native library unavailable (build native/ first)")
        self._lib = lib
        self._service_factory = service_factory
        if host in ("", "::"):
            host = "0.0.0.0"
        else:
            # The engine only takes dotted quads. ``Server.bind()`` resolves
            # names asynchronously before constructing us; this fallback only
            # runs for direct construction off the event loop.
            import socket

            try:
                socket.inet_aton(host)
            except OSError:
                host = socket.gethostbyname(host)
        self._engine = Engine(lib, host, port, reuse_port=reuse_port)
        self.port = self._engine.port
        # SpanRing (node-wide; resolved from the first connection's service
        # — the factory builds services lazily, and the event dispatcher
        # needs the handle before any worker has run).
        self._spans = None
        self._spans_resolved = False
        # EdgeSampler (node-wide TCP byte counters), same lazy resolve.
        self._affinity = None
        # QosScheduler (admission + handler-start grants), same lazy resolve.
        self._qos = None
        self._conns: dict[int, _ConnState] = {}
        self._workers: set[asyncio.Task] = set()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._closed = asyncio.Event()
        self._started = False

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._loop.add_reader(self._engine.notify_fd, self._on_ready)
        self._engine.start()
        self._started = True

    def _on_ready(self) -> None:
        # Bounded batches per callback: enough to amortize the eventfd
        # round trip, small enough that conn workers still run between
        # wakeups (the engine re-arms the eventfd when more is pending).
        for _ in range(8):
            events = self._engine.drain()
            if not events:
                return
            self._dispatch_events(events)

    def _dispatch_events(self, events) -> None:
        for ev_type, conn, data in events:
            if ev_type == EV_OPENED:
                state = _ConnState()
                state.worker = asyncio.ensure_future(self._conn_worker(conn, state))
                self._workers.add(state.worker)
                state.worker.add_done_callback(self._workers.discard)
                self._conns[conn] = state
            elif ev_type == EV_FRAME:
                if self._affinity is not None:
                    # Frame payload + the 4-byte length prefix the engine
                    # already consumed — matches what crossed TCP.
                    self._affinity.tcp_in_bytes += len(data) + 4
                state = self._conns.get(conn)
                if state is not None:
                    if len(state.queue) >= _MAX_PENDING_FRAMES:
                        # The asyncio path gets TCP backpressure for free
                        # (reads pause past the handler cap); the engine
                        # reads greedily, so an unbounded pipeliner must be
                        # cut off rather than allowed to grow server memory.
                        # Dropping the state + EOF here (Python-initiated
                        # closes emit no EV_CLOSED) lets the worker finish
                        # in-flight frames and exit instead of leaking.
                        log.warning("conn %d exceeded pending-frame cap", conn)
                        self._conns.pop(conn, None)
                        if state.streaming:
                            # A streaming worker never reads state.queue
                            # again; EOF alone would orphan it.
                            if state.worker is not None:
                                state.worker.cancel()
                        else:
                            # Drop the unserved backlog: the engine closes as
                            # soon as its write queue drains, so responses for
                            # these frames would be thrown away — don't burn
                            # the worker executing them into a dead socket.
                            state.queue.clear()
                            state.eof = True
                            state.wake()
                        self._engine.close_conn(conn)
                    else:
                        if self._spans is not None:
                            # Frame-receive stamp; decode happens in the
                            # worker (unlike the asyncio transport).
                            state.queue.append((data, _perf()))
                        else:
                            state.queue.append(data)
                        state.wake()
            elif ev_type == EV_CLOSED:
                state = self._conns.pop(conn, None)
                if state is not None and state.worker is not None:
                    if state.streaming:
                        # Subscription pumps block on the router queue, not
                        # on inbound frames; cancellation is the only (and
                        # safe — no actor state) way to stop them.
                        state.worker.cancel()
                    else:
                        state.eof = True
                        state.wake()
                        state.wake_room()

    # ------------------------------------------------------------------

    def _push_response(self, conn: int, state: _ConnState, fut: asyncio.Future) -> None:
        state.resp_q.append(fut)
        if fut.done():
            self._flush_ready(conn, state)
        else:
            fut.add_done_callback(lambda _f: self._flush_ready(conn, state))

    def _flush_ready(self, conn: int, state: _ConnState) -> None:
        """Write every completed head response, preserving request order.

        Runs synchronously from the handler task's done-callback (the same
        FIFO-flush design as :class:`rio_tpu.aio.ServerConnProtocol`), so
        out-of-order completions cost nothing until their turn. With egress
        coalescing on (default) the whole wave leaves as ONE joined
        ``engine.send`` — one mutex grab + eventfd kick + write syscall
        instead of one per frame; wire bytes are identical.
        """
        q = state.resp_q
        spans = self._spans
        affinity = self._affinity
        wave: list[bytes] = []  # coalesced frames awaiting one engine.send
        stamped: list = []  # (ph, env) pairs whose flush stamp awaits that send
        try:
            while q and q[0].done() and not state.broken:
                fut = q.popleft()
                if fut.cancelled():
                    continue  # shutdown path; nothing to write
                resp = fut.result()
                frame = encode_response_frame(resp)
                if spans is not None:
                    ctx = getattr(fut, "_rio_ph", None)
                    if ctx is not None:
                        ph, env = ctx
                        ph.encode = _perf()
                        err = resp.error
                        if err is not None:
                            ph.attrs = {"status": int(err.kind)}
                        if _EGRESS_COALESCE:
                            wave.append(frame)
                            stamped.append((ph, env))
                            continue
                        if affinity is not None:
                            affinity.tcp_out_bytes += len(frame)
                        self._engine.send(conn, frame)
                        ph.flush = _perf()
                        finish_request(spans, ph, env)
                        continue
                if _EGRESS_COALESCE:
                    wave.append(frame)
                else:
                    if affinity is not None:
                        affinity.tcp_out_bytes += len(frame)
                    self._engine.send(conn, frame)
            if wave:
                data = wave[0] if len(wave) == 1 else b"".join(wave)
                if affinity is not None:
                    affinity.tcp_out_bytes += len(data)
                self._engine.send(conn, data)
                if stamped:
                    t = _perf()
                    for ph, env in stamped:
                        ph.flush = t
                        finish_request(spans, ph, env)
        except Exception:
            log.exception("response write error; dropping conn %d", conn)
            # Best-effort: frames collected before the failure are complete
            # responses in FIFO order — hand them to the engine (which
            # flushes its queue before close) like the per-frame path did.
            if wave:
                try:
                    self._engine.send(conn, b"".join(wave))
                except Exception:  # noqa: BLE001 — conn is done either way
                    pass
            state.broken = True
            state.eof = True
            state.wake()
            self._conns.pop(conn, None)
            self._engine.close_conn(conn)
        state.wake_room()

    def _stamp_inbound(
        self, state: _ConnState, env: RequestEnvelope, t_recv: float
    ) -> "Phases | None":
        """Attach the per-request phase clock (span retention armed only).

        Traced requests always carry one; untraced traffic samples on the
        same 1-in-8 stride the RED histograms use (per connection), so the
        ring's tail capture sees outliers without a per-request clock read.
        """
        tc = env.trace_ctx
        if tc is None:
            state.ph_tick = tick = (state.ph_tick + 1) & 7
            if tick:
                return None
            ph = Phases(t_recv)
        else:
            ph = Phases(t_recv, tc)
        ph.decode = _perf()
        env._phases = ph
        return ph

    async def _next_payload(self, state: _ConnState) -> bytes | None:
        while not state.queue:
            if state.eof:
                return None
            state.waiter = asyncio.get_running_loop().create_future()
            await state.waiter
        return state.queue.popleft()

    async def _conn_worker(self, conn: int, state: _ConnState) -> None:
        """Ordered-concurrent dispatch for one connection.

        Same semantics as the asyncio transport: handlers run concurrently
        per connection, responses leave strictly in request order
        (service.rs:370-459 wire shape under pipelining).
        """
        service = self._service_factory()
        if not self._spans_resolved:
            self._spans_resolved = True
            self._spans = getattr(service, "spans", None)
            self._affinity = getattr(service, "affinity", None)
            self._qos = getattr(service, "qos", None)
        loop = asyncio.get_running_loop()
        cancelled = False
        try:
            while True:
                payload = await self._next_payload(state)
                if payload is None:
                    # Peer finished sending; flush every in-flight response
                    # before handing the fd back (the engine then closes
                    # once its write queue drains).
                    while state.resp_q and not state.broken:
                        state.room = loop.create_future()
                        await state.room
                    return
                if type(payload) is tuple:
                    payload, t_recv = payload
                else:
                    t_recv = 0.0
                try:
                    inbound = decode_inbound(payload)
                except UnknownFrameKind as e:
                    # A frame kind this server doesn't speak (newer client):
                    # clean NOT_SUPPORTED, connection survives.
                    fut: asyncio.Future = loop.create_future()
                    fut.set_result(
                        ResponseEnvelope.err(ResponseError.not_supported(str(e)))
                    )
                    self._push_response(conn, state, fut)
                    continue
                except Exception as e:  # malformed frame → error response
                    fut = loop.create_future()
                    fut.set_result(
                        ResponseEnvelope.err(ResponseError.unknown(f"bad frame: {e}"))
                    )
                    self._push_response(conn, state, fut)
                    continue
                if type(inbound) is CommandEnvelope:
                    # Control-plane command: ordinary response FIFO, no
                    # inline fast path or phase stamping (commands are
                    # infrequent) — mirrors rio_tpu.aio.
                    while len(state.resp_q) >= _MAX_CONCURRENT and not state.eof:
                        state.room = loop.create_future()
                        await state.room
                    self._push_response(
                        conn, state, loop.create_task(service.call_command(inbound))
                    )
                    continue
                ph = None
                if t_recv and type(inbound) is RequestEnvelope:
                    ph = self._stamp_inbound(state, inbound, t_recv)
                if type(inbound) is RequestEnvelope:
                    qos = self._qos
                    dispatched = None
                    if qos is not None:
                        # One synchronous admission + grant step between
                        # decode and dispatch: sheds ride the FIFO response
                        # path as pre-resolved futures — the handler never
                        # starts (same design as the asyncio transport).
                        dispatched = qos.dispatch(service.call, inbound)
                        if type(dispatched) is ResponseError:
                            fut = loop.create_future()
                            fut.set_result(ResponseEnvelope.err(dispatched))
                            self._push_response(conn, state, fut)
                            continue
                    if not state.resp_q and not state.queue:
                        # Sole in-flight request on this connection:
                        # dispatch inline (no task), the common case.
                        if ph is not None:
                            ph.queue = ph.handler_start = _perf()
                        if dispatched is None:
                            resp = await service.call(inbound)
                        else:
                            resp = await dispatched
                        if ph is not None:
                            ph.handler_end = _perf()
                        if not state.broken:
                            frame = encode_response_frame(resp)
                            if self._affinity is not None:
                                self._affinity.tcp_out_bytes += len(frame)
                            if ph is None:
                                self._engine.send(conn, frame)
                            else:
                                ph.encode = _perf()
                                err = resp.error
                                if err is not None:
                                    ph.attrs = {"status": int(err.kind)}
                                self._engine.send(conn, frame)
                                ph.flush = _perf()
                                finish_request(self._spans, ph, inbound)
                        continue
                    while len(state.resp_q) >= _MAX_CONCURRENT and not state.eof:
                        state.room = loop.create_future()
                        await state.room
                    task = loop.create_task(
                        service.call(inbound)
                        if dispatched is None
                        else dispatched
                    )
                    if ph is not None:
                        # Pipelined path: handler-end stamps in the task's
                        # done-callback; encode/flush when the FIFO head
                        # drains it (_flush_ready).
                        ph.queue = ph.handler_start = _perf()
                        task._rio_ph = (ph, inbound)
                        task.add_done_callback(_stamp_handler_end)
                    self._push_response(conn, state, task)
                else:
                    if conn not in self._conns:
                        # Peer already disconnected (CLOSED was drained while
                        # this frame sat in the queue): entering streaming
                        # mode now would leak the router subscription — no
                        # EV_CLOSED will ever cancel us again.
                        return
                    # Flush pending responses before streaming mode.
                    while state.resp_q and not state.eof:
                        state.room = loop.create_future()
                        await state.room
                    state.streaming = True
                    await self._stream_subscription(conn, service, inbound)
                    return
        except asyncio.CancelledError:
            cancelled = True
            raise
        except Exception:
            log.exception("native conn worker error (conn=%d)", conn)
        finally:
            if cancelled:
                for fut in state.resp_q:
                    fut.cancel()
                state.resp_q.clear()
            # Mirror the asyncio transport's close: whatever ends the
            # worker, the engine should close the socket — after pending
            # responses flush (close_pending semantics in the engine).
            self._conns.pop(conn, None)
            self._engine.close_conn(conn)

    async def _stream_subscription(
        self, conn: int, service: "Service", req: SubscriptionRequest
    ) -> None:
        from ..protocol import SubscriptionResponse

        result = await service.subscribe(req)
        if isinstance(result, ResponseError):
            self._engine.send(
                conn, encode_subresponse_frame(SubscriptionResponse(error=result))
            )
            self._engine.close_conn(conn)
            return
        queue = result
        router = service.app_data.get(MessageRouter)
        try:
            while True:
                item = await queue.get()
                # Write backpressure: the asyncio path blocks in
                # writer.drain(); here we poll the engine's per-conn unsent
                # byte count so a stalled subscriber can't grow the write
                # queue without bound.
                while self._engine.backlog(conn) > _MAX_WRITE_BACKLOG:
                    await asyncio.sleep(0.005)
                self._engine.send(conn, encode_subresponse_frame(item))
        finally:
            router.drop_subscription(req.handler_type, req.handler_id, queue)

    # ------------------------------------------------------------------

    def close(self) -> None:
        if self._loop is not None and self._started:
            self._loop.remove_reader(self._engine.notify_fd)
        # Cancel every worker ever started (not just those still in _conns:
        # a worker whose conn closed mid-dispatch may still be draining).
        for worker in list(self._workers):
            worker.cancel()
        self._workers.clear()
        self._conns.clear()
        self._engine.shutdown()
        self._closed.set()

    async def wait_closed(self) -> None:
        await self._closed.wait()
