"""Shared async SQLite helper for the sql-backed providers.

The reference uses sqlx pools (``rio-rs/src/cluster/storage/sqlite.rs``,
``object_placement/sqlite.rs``, ``state/sqlite.rs``); Python's stdlib
``sqlite3`` is synchronous, so every call runs in the default thread pool
behind one connection + lock (plenty for the control plane, which is exactly
the role these backends play — the hot placement path lives on TPU).
"""

from __future__ import annotations

import asyncio
import sqlite3
import threading
from typing import Any, Iterable


class SqliteDb:
    def __init__(self, path: str) -> None:
        self.path = path
        self._conn: sqlite3.Connection | None = None
        self._lock = threading.Lock()

    def _connect(self) -> sqlite3.Connection:
        if self._conn is None:
            self._conn = sqlite3.connect(self.path, check_same_thread=False)
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA busy_timeout=5000")
        return self._conn

    def _execute(self, sql: str, params: Iterable[Any]) -> list[tuple]:
        with self._lock:
            conn = self._connect()
            cur = conn.execute(sql, tuple(params))
            rows = cur.fetchall()
            conn.commit()
            return rows

    def _executescript(self, sql: str) -> None:
        with self._lock:
            conn = self._connect()
            conn.executescript(sql)
            conn.commit()

    async def execute(self, sql: str, *params: Any) -> list[tuple]:
        return await asyncio.to_thread(self._execute, sql, params)

    async def migrate(self, queries: list[str]) -> None:
        """Run migration statements (reference ``sql_migration.rs``)."""
        for q in queries:
            await asyncio.to_thread(self._executescript, q)

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
