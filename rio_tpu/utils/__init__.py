"""Small shared utilities (LRU cache, backoff policy, async helpers)."""

from .lru import LruCache
from .backoff import DecorrelatedJitter, ExponentialBackoff

__all__ = ["LruCache", "ExponentialBackoff", "DecorrelatedJitter"]
