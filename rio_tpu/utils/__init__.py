"""Small shared utilities (LRU cache, backoff policy, async helpers)."""

from .lru import LruCache
from .backoff import ExponentialBackoff

__all__ = ["LruCache", "ExponentialBackoff"]
