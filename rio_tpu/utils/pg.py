"""Shared async PostgreSQL helper for the sql-backed providers.

Mirrors :class:`rio_tpu.utils.sqlite.SqliteDb` so the Postgres backends can
reuse the SQLite backends' query logic (the reference keeps the same shape
between its sqlx SQLite and Postgres impls, e.g.
``rio-rs/src/cluster/storage/postgres.rs:28-56`` vs ``sqlite.rs:74-92``).

The driver is discovered at runtime — ``psycopg`` (v3) or ``psycopg2`` —
and queries written with ``?`` placeholders are translated to
the DBAPI ``%s`` paramstyle. If no driver is installed, constructing a
:class:`PgDb` raises a clear error; the rest of the framework never imports
this module unless a Postgres backend is requested (the reference gates the
same way with the ``postgres`` cargo feature).
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Iterable

# pg8000 is excluded: its connect() takes (user, host, ...) kwargs, not a
# DSN string, so it cannot sit behind this DSN-based interface unmodified.
_DRIVERS = ("psycopg", "psycopg2")


def _find_driver():
    for name in _DRIVERS:
        try:
            module = __import__(name)
        except ImportError:
            continue
        for part in name.split(".")[1:]:
            module = getattr(module, part)
        return module
    return None


def driver_available() -> bool:
    return _find_driver() is not None


def _translate(sql: str) -> str:
    """``?`` placeholders → ``%s`` (outside of string literals)."""
    out: list[str] = []
    in_str = False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
        if ch == "?" and not in_str:
            out.append("%s")
        else:
            out.append(ch)
    return "".join(out)


class PgDb:
    def __init__(self, dsn: str) -> None:
        self._driver = _find_driver()
        if self._driver is None:
            raise RuntimeError(
                "no PostgreSQL driver installed (tried psycopg, psycopg2); "
                "install one to use the Postgres backends"
            )
        self.dsn = dsn
        self._conn: Any = None
        self._lock = threading.Lock()

    def _connect(self) -> Any:
        if self._conn is None:
            self._conn = self._driver.connect(self.dsn)
        return self._conn

    def _recover(self, conn: Any) -> None:
        """A failed statement leaves the transaction aborted (psycopg raises
        InFailedSqlTransaction on every later query); roll it back, and if
        even that fails the socket is gone — drop the connection so the next
        call redials."""
        try:
            conn.rollback()
        except Exception:
            try:
                conn.close()
            except Exception:
                pass
            self._conn = None

    def _execute(self, sql: str, params: Iterable[Any]) -> list[tuple]:
        with self._lock:
            conn = self._connect()
            try:
                with conn.cursor() as cur:
                    cur.execute(_translate(sql), tuple(params))
                    rows = cur.fetchall() if cur.description is not None else []
                conn.commit()
            except Exception:
                self._recover(conn)
                raise
            return [tuple(r) for r in rows]

    def _executescript(self, sql: str) -> None:
        with self._lock:
            conn = self._connect()
            try:
                with conn.cursor() as cur:
                    for stmt in (s.strip() for s in sql.split(";")):
                        if stmt:
                            cur.execute(stmt)
                conn.commit()
            except Exception:
                self._recover(conn)
                raise

    async def execute(self, sql: str, *params: Any) -> list[tuple]:
        return await asyncio.to_thread(self._execute, sql, params)

    async def migrate(self, queries: list[str]) -> None:
        for q in queries:
            await asyncio.to_thread(self._executescript, q)

    def close(self) -> None:
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
