"""Measured elastic autoscaling on live clusters: idle cost + the ramp soak.

Two artifacts back the autoscale subsystem (`bench.py --autoscale`):

* :func:`measure_autoscale_idle_overhead` — the faults_live pricing
  discipline applied to the controller: two in-process clusters serving
  identical echo traffic, one with autoscaling absent (``autoscale_config
  =None`` — the server holds literally no controller object) and one with
  the controller armed but pinned (``min_nodes == max_nodes``: it ticks,
  samples gauges, evaluates trend rules, and can never act). The headline
  is the MEDIAN of per-batch paired ratios where batch k's off/on share
  the same seconds of box weather; the disabled side is additionally
  asserted to be structurally free (``server.autoscale is None``).

* :func:`measure_autoscale_ramp` — the deliverable soak: a supervisor
  with a :class:`~rio_tpu.autoscale.provision.SubprocessProvisioner`
  ramps offered load ~10x up and back down while a ``faults.py`` schedule
  blips the supervisor's membership+placement view and one managed node
  takes a real SIGKILL mid-scale-in drain. Writes go through a durable
  shared-sqlite state provider and are counted ONLY when acked, so the
  zero-lost bar is exact: every acked increment must be in the final
  counter values (duplicates — an applied write whose ack died with the
  node — are tolerated and reported, lost ones fail the soak). The
  supervisor's journal must show the full causal chain for every
  decision: a HEALTH alarm for the trigger rule strictly before the SCALE
  decision, and scale-ins completing through drain-request → retire.
"""

from __future__ import annotations

import asyncio
import contextlib
import gc
import os
import shutil
import statistics
import tempfile
import time

from .. import AppData, Client, Registry, ServiceObject, handler, message
from ..commands import ServerInfo
from ..errors import (
    Disconnect,
    RetryExhausted,
    ServerBusy,
    ServerNotAvailable,
)
from ..state import StateProvider, managed_state
from ..state.sqlite import SqliteState
from .backoff import ExponentialBackoff

RETRYABLE = (RetryExhausted, ServerBusy, ServerNotAvailable, Disconnect, OSError)


# -- the soak actor -----------------------------------------------------------
# Module-level on purpose: SubprocessProvisioner workers import it through
# the "rio_tpu.utils.autoscale_live:build_soak_registry" factory spec.


@message(name="autoscale_live.Add")
class Add:
    n: int = 1


@message(name="autoscale_live.Get")
class Get:
    pass


@message(name="autoscale_live.Total")
class Total:
    value: int = 0
    address: str = ""


@message(name="autoscale_live.CounterState")
class CounterState:
    value: int = 0


class SoakCounter(ServiceObject):
    """Durable counter: the ack is sent only after the state saved, so a
    node death at ANY point loses nothing the client counted."""

    state = managed_state(CounterState)

    @handler
    async def add(self, msg: Add, ctx: AppData) -> Total:
        self.state.value += msg.n
        await self.save_state(ctx)
        info = ctx.try_get(ServerInfo)
        return Total(value=self.state.value, address=info.address if info else "")

    @handler
    async def get(self, msg: Get, ctx: AppData) -> Total:
        info = ctx.try_get(ServerInfo)
        return Total(value=self.state.value, address=info.address if info else "")


def build_soak_registry() -> Registry:
    return Registry().add_type(SoakCounter)


def sqlite_state(data_dir: str) -> SqliteState:
    """Shared durable state factory (``--node`` spec: "state" key)."""
    return SqliteState(os.path.join(data_dir, "autoscale-state.db"))


# -- idle controller overhead (the disabled-must-stay-free A/B) ---------------


async def measure_autoscale_idle_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 64,
    n_objects: int = 256,
    batches: int = 24,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with autoscaling absent vs armed-but-pinned.

    Returns best-of msgs/sec per mode plus ``autoscale_overhead_pct``
    (median per-batch paired ratio of off/on, positive = slower). The
    "on" controller genuinely runs — its tick count is asserted > 0 —
    but ``min_nodes == max_nodes`` pins it so no decision can fire.
    """
    from ..autoscale import AutoscaleConfig, ScalePolicy
    from ..autoscale.provision import InProcessProvisioner
    from ..cluster.storage import LocalStorage
    from ..object_placement import LocalObjectPlacement
    from .routing_live import Echo, EchoActor, boot_echo_cluster

    on_members = LocalStorage()
    on_placement = LocalObjectPlacement()
    provisioner = InProcessProvisioner(
        on_members,
        on_placement,
        registry_builder=build_soak_registry,
    )
    modes: dict[str, dict] = {
        "off": dict(members=LocalStorage(), placement=LocalObjectPlacement()),
        "on": dict(
            members=on_members,
            placement=on_placement,
            server_kwargs=dict(
                load_interval=0.1,
                autoscale_config=AutoscaleConfig(
                    provisioner=provisioner,
                    # Pinned: nodes can neither grow nor shrink, so the
                    # controller pays its full observation cost (gauge
                    # aggregation, EMA, trend rules) and never acts.
                    policy=ScalePolicy(
                        min_nodes=n_servers, max_nodes=n_servers
                    ),
                    interval=0.25,
                ),
            ),
        ),
    }
    clusters: dict[str, tuple] = {}  # name -> (client, tasks, servers)
    rates: dict[str, list[float]] = {name: [] for name in modes}
    try:
        for name, cfg in modes.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                members=cfg["members"],
                placement=cfg["placement"],
                server_kwargs=cfg.get("server_kwargs"),
            )
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks, servers)
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        # Disabled is structurally free: no controller object exists.
        assert all(s.autoscale is None for s in clusters["off"][2])
        assert any(s.autoscale is not None for s in clusters["on"][2])

        async def batch(name: str) -> float:
            client = clusters[name][0]
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
            return total / elapsed

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                o = await batch("off")
                r = await batch("on")
            else:
                r = await batch("on")
                o = await batch("off")
            rates["off"].append(o)
            rates["on"].append(r)
            ratios.append(o / r - 1.0)

        ticks = sum(
            s.autoscale.ticks for s in clusters["on"][2] if s.autoscale
        )
        if ticks <= 0:
            raise RuntimeError("pinned controller never ticked during the A/B")
        decisions = sum(
            s.autoscale.scale_outs + s.autoscale.scale_ins
            for s in clusters["on"][2]
            if s.autoscale
        )
        if decisions:
            raise RuntimeError("pinned controller acted during the idle A/B")
    finally:
        for client, tasks, _ in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks, _ in clusters.values() for t in tasks],
            return_exceptions=True,
        )
        await provisioner.close()

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "autoscale_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "controller_ticks_on": ticks,
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }


# -- the ramp soak ------------------------------------------------------------


async def measure_autoscale_ramp(
    *,
    data_dir: str | None = None,
    n_keys: int = 16,
    writers_low: int = 2,
    writers_high: int = 20,
    low_sleep_s: float = 0.02,
    high_sleep_s: float = 0.002,
    warm_secs: float = 3.0,
    high_timeout: float = 90.0,
    settle_timeout: float = 150.0,
    p99_bound_s: float = 5.0,
    blip_period_s: float = 2.0,
    blip_secs: float = 0.3,
    max_nodes: int = 3,
) -> dict:
    """Ramp offered load ~10x up and back down against a self-sizing
    cluster under fault weather; return the full evidence bundle.

    Asserted inline (a failure raises): scale-out AND scale-in each fire,
    a managed node takes a SIGKILL mid-scale-in, zero acked writes are
    lost, request p99 stays under ``p99_bound_s`` through every resize,
    the final node count returns to the floor, and every SCALE decision
    in the journal is preceded by a HEALTH alarm for its trigger rule.
    """
    from ..autoscale import AutoscaleConfig, ScalePolicy
    from ..autoscale.provision import SubprocessProvisioner
    from ..cluster.membership_protocol import LocalClusterProvider
    from ..commands import AdminCommand
    from ..faults import (
        FaultSchedule,
        FaultyMembershipStorage,
        FaultyObjectPlacement,
        StorageHealth,
    )
    from ..journal import HEALTH, SCALE
    from ..server import Server
    from ..sharded import sqlite_members, sqlite_placement

    own_dir = data_dir is None
    if own_dir:
        data_dir = tempfile.mkdtemp(prefix="rio-autoscale-soak-")

    schedule = FaultSchedule(seed=2024)
    storage_health = StorageHealth()
    members = FaultyMembershipStorage(
        sqlite_members(data_dir), schedule, storage_health
    )
    placement = FaultyObjectPlacement(
        sqlite_placement(data_dir), schedule, storage_health
    )
    state = sqlite_state(data_dir)
    await state.prepare()
    app_data = AppData()
    app_data.set(state, as_type=StateProvider)

    provisioner = SubprocessProvisioner(
        data_dir,
        registry="rio_tpu.utils.autoscale_live:build_soak_registry",
        state="rio_tpu.utils.autoscale_live:sqlite_state",
        server_kwargs={"load_interval": 0.1},
    )
    # Rate-band policy: the writer phases differ ~10x in offered req/s,
    # and per-node rate is what the bands cut. The low band sits far above
    # the controller's own poke/heartbeat floor (~3 req/s).
    policy = ScalePolicy(
        min_nodes=1,
        max_nodes=max_nodes,
        high_pressure=600.0,
        low_pressure=150.0,
        sustain=2,
        ema_alpha=0.6,
        inflight_weight=0.0,
        lag_weight=0.0,
        rate_weight=1.0,
        shed_weight=0.0,
        out_cooldown_s=1.0,
        in_cooldown_s=1.0,
        cooldown_max_s=4.0,
        drain_timeout_s=15.0,
    )
    supervisor = Server(
        address="127.0.0.1:0",
        registry=build_soak_registry(),
        cluster_provider=LocalClusterProvider(members),
        object_placement_provider=placement,
        app_data=app_data,
        load_interval=0.1,
        placement_daemon=True,  # churn-kicked rebalance spreads the keys
        autoscale_config=AutoscaleConfig(
            provisioner=provisioner, policy=policy, interval=0.25
        ),
    )
    await supervisor.prepare()
    await supervisor.bind()
    serve = asyncio.ensure_future(supervisor.run())
    runtime = supervisor.autoscale
    assert runtime is not None
    client = Client(
        members, backoff=ExponentialBackoff(initial=0.01, cap=0.1, max_retries=6)
    )

    acked: dict[str, int] = {f"soak-{i}": 0 for i in range(n_keys)}
    latencies: list[float] = []
    failures = 0
    writer_sleep = low_sleep_s
    stop_load = asyncio.Event()
    stop_blips = asyncio.Event()
    blips = 0
    killed = ""
    t_start = time.monotonic()

    async def writer(w: int) -> None:
        nonlocal failures
        i = 0
        while not stop_load.is_set():
            # Round-robin over the key space so every counter sees traffic
            # in every phase regardless of how many writers are live.
            key = f"soak-{(w + i) % n_keys}"
            i += 1
            t0 = time.perf_counter()
            try:
                await client.send(SoakCounter, key, Add(n=1), returns=Total)
            except RETRYABLE:
                failures += 1
            else:
                acked[key] += 1
                latencies.append(time.perf_counter() - t0)
            await asyncio.sleep(writer_sleep)

    async def blipper() -> None:
        # Storage weather: short scripted full outages of the
        # supervisor's membership+placement view, healed each time.
        nonlocal blips
        while not stop_blips.is_set():
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(stop_blips.wait(), blip_period_s)
                return
            schedule.fail_all("membership.*")
            schedule.fail_all("placement.*")
            blips += 1
            await asyncio.sleep(blip_secs)
            schedule.heal()

    async def killer() -> None:
        # The chaos centerpiece: the moment a scale-in drain is in
        # flight, SIGKILL the victim process mid-drain.
        nonlocal killed
        while not killed and not stop_load.is_set():
            victim = runtime.pending
            if victim and victim in provisioner.managed():
                provisioner.terminate(victim)
                killed = victim
                return
            await asyncio.sleep(0.01)

    async def wait_for(pred, timeout: float, what: str) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return
            await asyncio.sleep(0.1)
        raise TimeoutError(f"soak: no {what} within {timeout:.0f}s")

    writers: list[asyncio.Task] = []
    chaos: list[asyncio.Task] = []
    try:
        # Phase 1 — low offered load: seat every key, bank clean writes.
        writers = [asyncio.ensure_future(writer(w)) for w in range(writers_low)]
        await asyncio.sleep(warm_secs)
        if not all(acked.values()):
            raise RuntimeError("soak: not every key served during warm-up")

        # Phase 2 — ~10x offered load under storage blips: the sustained
        # overload trend must grow the cluster.
        writer_sleep = high_sleep_s
        writers += [
            asyncio.ensure_future(writer(w))
            for w in range(writers_low, writers_high)
        ]
        chaos.append(asyncio.ensure_future(blipper()))
        await wait_for(
            lambda: runtime.scale_outs >= 1 and runtime.last_nodes >= 2,
            high_timeout,
            "scale-out under load",
        )

        # Phase 3 — back to 1x: the falling trend must shrink it; the
        # killer SIGKILLs the first drain victim mid-scale-in.
        chaos.append(asyncio.ensure_future(killer()))
        writer_sleep = low_sleep_s
        for w in writers[writers_low:]:
            w.cancel()
        await asyncio.gather(*writers[writers_low:], return_exceptions=True)
        writers = writers[:writers_low]
        await wait_for(
            lambda: runtime.scale_ins >= 1,
            settle_timeout,
            "completed scale-in",
        )
        await wait_for(
            lambda: runtime.last_nodes <= policy.min_nodes
            and not provisioner.managed(),
            settle_timeout,
            "node count back at the floor",
        )
        if not killed:
            raise RuntimeError("soak: no victim was SIGKILLed mid-scale-in")
    finally:
        stop_load.set()
        stop_blips.set()
        for t in writers + chaos:
            t.cancel()
        await asyncio.gather(*writers, *chaos, return_exceptions=True)
        schedule.heal()

    soak_secs = time.monotonic() - t_start

    # Zero lost acked writes: every increment the client saw acked is in
    # the durable counter. An applied-but-unacked write (its ack died with
    # the killed node) may legitimately over-count; it is reported, never
    # silently absorbed into the loss check.
    lost_keys: list[str] = []
    final_total = 0
    for key, want in acked.items():
        got = await client.send(SoakCounter, key, Get(), returns=Total)
        final_total += got.value
        if got.value < want:
            lost_keys.append(f"{key}: acked {want}, found {got.value}")
    if lost_keys:
        raise AssertionError(f"soak: LOST acked writes: {lost_keys}")
    acked_total = sum(acked.values())

    # Bounded p99 through every resize.
    lat = sorted(latencies)
    p50 = lat[len(lat) // 2] if lat else 0.0
    p99 = lat[int(len(lat) * 0.99)] if lat else 0.0
    if p99 > p99_bound_s:
        raise AssertionError(f"soak: p99 {p99:.2f}s exceeds {p99_bound_s}s")

    # Causality: every SCALE decision has a journaled trigger alarm of its
    # rule strictly before it, and every scale-in completes through
    # drain-request → retired.
    assert supervisor.journal is not None
    events = supervisor.journal.events(kinds=[HEALTH, SCALE])
    chain: list[str] = []
    alarm_rules_seen: set[str] = set()
    in_flight: dict[str, int] = {}
    retired: set[str] = set()
    drain_requested: set[str] = set()
    for ev in events:
        if ev.kind == HEALTH:
            alarm_rules_seen.add(ev.attrs.get("rule", "") or ev.key)
            continue
        action = ev.attrs.get("action", "")
        chain.append(f"{action}:{ev.key}")
        if action in ("scale_out", "scale_in"):
            rule = ev.attrs.get("rule", "")
            if rule not in alarm_rules_seen:
                raise AssertionError(
                    f"soak: SCALE {action} fired without a prior HEALTH "
                    f"alarm for rule {rule!r}: {chain}"
                )
        if action == "scale_in":
            in_flight[ev.key] = 1
        elif action in ("drain_requested", "drain_request_failed"):
            # A failed request is still the drain EDGE of the causal chain:
            # under storage/victim chaos the wire request can exhaust its
            # retries (the victim may already be SIGKILLed), and the
            # deadline branch is the designed path to the retire.
            drain_requested.add(ev.key)
        elif action == "retired":
            retired.add(ev.key)
    for victim in in_flight:
        if victim not in retired:
            raise AssertionError(f"soak: scale-in of {victim} never retired")
        if victim not in drain_requested:
            raise AssertionError(f"soak: {victim} retired without a drain attempt")

    result = {
        "scale_outs": runtime.scale_outs,
        "scale_ins": runtime.scale_ins,
        "final_nodes": runtime.last_nodes,
        "killed_mid_drain": killed,
        "acked_writes": acked_total,
        "final_counter_total": final_total,
        "duplicates": final_total - acked_total,
        "lost": 0,
        "retryable_failures": failures,
        "p50_ms": round(p50 * 1000.0, 2),
        "p99_ms": round(p99 * 1000.0, 2),
        "offered_ratio": round(
            (writers_high / max(1, writers_low)) * (low_sleep_s / high_sleep_s), 1
        ),
        "storage_blips": blips,
        "seconds": round(soak_secs, 1),
        "chain": chain,
    }

    client.close()
    supervisor.admin_sender().send(AdminCommand.server_exit())
    with contextlib.suppress(Exception):
        await asyncio.wait_for(serve, timeout=15.0)
    serve.cancel()
    await asyncio.gather(serve, return_exceptions=True)
    await provisioner.close()
    await runtime.close()
    with contextlib.suppress(Exception):
        members.close()
    with contextlib.suppress(Exception):
        placement.close()
    if own_dir:
        shutil.rmtree(data_dir, ignore_errors=True)
    return result
