"""Measured control-plane journal overhead on the live RPC loop.

The flight recorder (``rio_tpu/journal.py``) promises the data path pays
nothing for it: events are recorded on control-plane TRANSITIONS only
(assign, shed, migrate phases, solve, ...) — never per request — and the
request path's only journal touch is the ``app_data.try_get`` each manager
does once at construction. This module *measures* that promise the same
way ``tracing_live`` prices the metrics layer: two cluster configurations,
identical traffic, one process —

* **off** — servers booted with ``journal=False``: no Journal in AppData,
  every subsystem's journal reference is ``None``.
* **on** — the shipping default (``journal=True``, capacity 4096): the
  acceptance bar (ISSUE 9: ≤ ~2%) is ``on`` vs ``off`` on the echo loop.

The measurement discipline is inherited wholesale from ``tracing_live``
(it exists because the naive one-cluster-per-mode cut read -1%..+8% under
box drift): both clusters boot once and coexist, placement is pre-seated
identically, GC is collected before and disabled during each timed batch,
and the artifact is the MEDIAN of per-batch paired ratios where batch k's
off/on share the same seconds of box weather.
"""

from __future__ import annotations

import asyncio
import gc
import time

from .. import Client
from .routing_live import Echo, EchoActor, boot_echo_cluster


async def measure_journal_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 64,
    n_objects: int = 256,
    batches: int = 24,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with the control-plane journal off vs on.

    Returns best-of msgs/sec per mode plus ``journal_overhead_pct`` (the
    median per-batch paired ratio of off/on, positive = slower), and the
    on-cluster's recorded-event count. With pre-seated placement and no
    daemons the echo loop makes NO control transitions, so that count is
    typically 0 — the whole point: journal on, data path untouched. The
    off-cluster is asserted journal-free so the A/B is real.
    """
    import statistics

    modes = {"off": False, "on": True}
    clusters: dict[str, tuple] = {}  # name -> (client, tasks, servers)
    rates: dict[str, list[float]] = {name: [] for name in modes}
    try:
        for name, journal_on in modes.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                server_kwargs={"journal": journal_on},
            )
            # Identical pre-seating in both clusters (see tracing_live: a
            # skewed provider split reads as a durable throughput delta).
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks, servers)
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        async def batch(name: str) -> float:
            client = clusters[name][0]
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
            return total / elapsed

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                o = await batch("off")
                r = await batch("on")
            else:
                r = await batch("on")
                o = await batch("off")
            rates["off"].append(o)
            rates["on"].append(r)
            ratios.append(o / r - 1.0)
        on_servers = clusters["on"][2]
        recorded = sum(s.journal.recorded for s in on_servers)
        off_servers = clusters["off"][2]
        if any(s.journal is not None for s in off_servers):
            raise RuntimeError("journal=False cluster still built a Journal")
    finally:
        for client, tasks, _ in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks, _ in clusters.values() for t in tasks],
            return_exceptions=True,
        )

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "journal_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "events_recorded_on": int(recorded),
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }
