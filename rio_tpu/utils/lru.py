"""Bounded LRU map (the client's placement cache, reference
``client/mod.rs:137-147`` — 1,000 entries by default)."""

from __future__ import annotations

from collections import OrderedDict
from typing import Generic, Hashable, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


class LruCache(Generic[K, V]):
    def __init__(self, capacity: int = 1000) -> None:
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._map: OrderedDict[K, V] = OrderedDict()

    def get(self, key: K) -> V | None:
        try:
            self._map.move_to_end(key)
            return self._map[key]
        except KeyError:
            return None

    def put(self, key: K, value: V) -> None:
        self._map[key] = value
        self._map.move_to_end(key)
        while len(self._map) > self.capacity:
            self._map.popitem(last=False)

    def pop(self, key: K) -> V | None:
        return self._map.pop(key, None)

    def clear(self) -> None:
        self._map.clear()

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: K) -> bool:
        return key in self._map
