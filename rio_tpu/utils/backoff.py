"""Exponential backoff policy for the client retry middleware.

Reference: ``rio-rs/src/client/tower_services.rs:142-146`` — 1 µs doubling
to a 2 s cap, at most 20 retries.
"""

from __future__ import annotations

import asyncio
import dataclasses


@dataclasses.dataclass
class ExponentialBackoff:
    initial: float = 1e-6
    cap: float = 2.0
    factor: float = 2.0
    max_retries: int = 20

    def delays(self):
        """Yield ``max_retries`` sleep durations."""
        d = self.initial
        for _ in range(self.max_retries):
            yield min(d, self.cap)
            d *= self.factor

    async def sleep(self, attempt: int) -> None:
        await asyncio.sleep(min(self.initial * (self.factor**attempt), self.cap))
