"""Exponential backoff policy for the client retry middleware.

Reference: ``rio-rs/src/client/tower_services.rs:142-146`` — 1 µs doubling
to a 2 s cap, at most 20 retries.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random


@dataclasses.dataclass
class ExponentialBackoff:
    initial: float = 1e-6
    cap: float = 2.0
    factor: float = 2.0
    max_retries: int = 20

    def delays(self):
        """Yield ``max_retries`` sleep durations."""
        d = self.initial
        for _ in range(self.max_retries):
            yield min(d, self.cap)
            d *= self.factor

    async def sleep(self, attempt: int) -> None:
        await asyncio.sleep(min(self.initial * (self.factor**attempt), self.cap))


class DecorrelatedJitter:
    """Decorrelated-jitter delays (the AWS architecture-blog variant).

    ``next() = min(cap, uniform(base, prev * 3))`` — successive delays are
    randomized against the PREVIOUS draw, so a thundering herd that shed at
    the same instant decorrelates after one round instead of re-colliding
    on every doubling the way pure exponential backoff does. One instance
    per request (the draw sequence is the per-request state).
    """

    def __init__(self, base: float = 1e-3, cap: float = 2.0) -> None:
        self.base = max(1e-9, base)
        self.cap = cap
        self._prev = self.base

    def next(self) -> float:
        self._prev = min(self.cap, random.uniform(self.base, self._prev * 3))
        return self._prev
