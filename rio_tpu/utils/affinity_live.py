"""Measured payoff of communication-aware placement on a live cluster.

The affinity subsystem (``rio_tpu/affinity`` + the graph term in
:class:`~rio_tpu.object_placement.jax_placement.JaxObjectPlacement`)
promises one operational headline: feeding the sampled edge graph back
into the solver moves chatty actor pairs onto the same node, so the bytes
those pairs used to push over TCP disappear from the sockets. This module
*measures* that claim end to end — no simulation, every byte counted
crossed a real loopback socket:

* **multi-hop workload** — a producer actor publishes padded records into
  a durable stream; one cursor per partition delivers to one consumer per
  partition. The placement directory is pre-seated ADVERSARIALLY before
  the first request: every cursor on node 0, every consumer on node 1, so
  each delivery is a cross-node hop (the cursor's local-first send
  redirects and falls back to the cluster client).
* **blind phase** — traffic runs with the placement exactly as seated;
  the per-server ``EdgeSampler`` TCP byte counters (fed by both
  transports) price the phase.
* **feedback** — the per-node edge graphs are scraped OVER THE WIRE with
  the admin ``DumpEdges`` command, merged cluster-wide
  (:func:`rio_tpu.admin.cluster_edges`), installed via
  ``set_edge_graph``, and a full re-solve runs. The alternating
  linearized-OT refine co-locates each cursor with its consumer.
* **affinity phase** — identical traffic again; deliveries now resolve
  local-first in-process. The bytes-over-TCP ratio (blind / affinity) is
  the headline; the acceptance bar is >= 2x.

The waterfall proof rides along: servers boot with an aggressive span
tail SLO, so strided delivery requests are retained by the span rings.
In the blind phase the consumer-side delivery hops show up as wire
``request`` spans; in the affinity phase the same logical hops run
through the in-server dispatch queue and VANISH from the wire span
rings — the "formerly cross-node hop now served process-locally"
evidence, counted per phase.

``measure_sampler_overhead`` prices the other acceptance bar: the
dispatch-path cost of the sampler itself (`affinity_sampler` off vs on),
with the ``series_live`` discipline — coexisting clusters, interleaved
gc-disabled batches, MEDIAN of per-batch paired ratios.
"""

from __future__ import annotations

import asyncio
import dataclasses
import gc
import time

from .. import (
    AppData,
    Client,
    LocalReminderStorage,
    LocalStorage,
    ObjectId,
    ObjectPlacementItem,
    ReminderDaemonConfig,
    ReminderStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from ..cluster.membership_protocol import LocalClusterProvider
from ..object_placement.jax_placement import JaxObjectPlacement
from ..registry import type_id
from ..reminders.daemon import SHARD_TYPE as REMINDER_SHARD_TYPE
from ..state import LocalState, StateProvider
from ..streams import LocalStreamStorage, StreamStorage, partition_for
from ..streams.cursor import CURSOR_TYPE, cursor_id, publish
from .routing_live import Echo, EchoActor, boot_echo_cluster

STREAM = "affinity-orders"
GROUP = "affinity-sink"


@message(name="affinity_live.Fill")
class Fill:
    """One padded stream record — the payload whose bytes the A/B counts."""

    value: int = 0
    pad: bytes = b""


@message(name="affinity_live.Produce")
class Produce:
    """Trigger: publish ``n`` records in-server (client sends ONE small
    frame; the append path is in-process, so delivery hops dominate the
    measured TCP traffic)."""

    n: int = 0
    pad_bytes: int = 0
    keys: list = dataclasses.field(default_factory=list)


class ProducerActor(ServiceObject):
    """In-cluster record source: publishes through the ctx-based producer
    API, so the publish leg never touches TCP and the wake → cursor →
    consumer chain is the traffic under test."""

    @handler
    async def produce(self, msg: Produce, ctx: AppData) -> Echo:
        pad = b"\x00" * msg.pad_bytes
        for i in range(msg.n):
            await publish(
                ctx, STREAM, Fill(value=i, pad=pad), key=msg.keys[i % len(msg.keys)]
            )
        return Echo(value=msg.n)


def _build_registry() -> Registry:
    return Registry().add_type(EchoActor).add_type(ProducerActor)


def _partition_keys(stream: str, n_partitions: int) -> list[str]:
    """One key per partition (crc32 search), so the workload is exactly
    ``n_partitions`` disjoint cursor→consumer pairs — the cleanest
    possible co-location target for the refine."""
    found: dict[int, str] = {}
    i = 0
    while len(found) < n_partitions:
        key = f"k{i}"
        found.setdefault(partition_for(stream, key, n_partitions), key)
        i += 1
    return [found[p] for p in range(n_partitions)]


async def measure_affinity_payoff(
    *,
    n_records: int = 256,
    pad_bytes: int = 4096,
    redelivery_period: float = 0.25,
    transport: str = "asyncio",
    affinity_weight: float = 2.0,
    affinity_host_factor: float = 0.05,
    drain_timeout: float = 60.0,
) -> dict:
    """Blind vs affinity-fed placement on identical multi-hop traffic.

    Returns the per-phase TCP byte deltas, their ratio (the >= 2x
    acceptance headline), the per-phase count of consumer-side delivery
    spans on the wire rings (the waterfall proof: the cross-node hop
    disappears), the merged-edge/move counts of the feedback step, and
    the refine's per-pass history. Raises ``RuntimeError`` on delivery
    loss — the byte win must never come from dropped records.
    """
    # Both "nodes" share this host, but the loopback sockets between them
    # still carry every byte the A/B counts — so the same-host discount is
    # nearly zeroed here (the shipping 0.5 default is for real multi-host
    # topologies where same-host means shared memory, not TCP). With the
    # heaviest edge normalized to 1.0, the attraction differential must
    # clear the stay-put move_cost (0.5) for a pair to co-locate at all:
    # at host_factor 0.5 the differential TIES it and the refine strands
    # most pairs; at 0.05 it is ~2x with affinity_weight 2.0 giving margin.
    placement = JaxObjectPlacement(
        node_axis_size=4,
        mode="greedy",
        affinity_weight=affinity_weight,
        affinity_host_factor=affinity_host_factor,
    )
    storage = LocalStreamStorage()
    state = LocalState()
    members = LocalStorage()
    reminders = LocalReminderStorage()
    servers: list[Server] = []
    tasks: list[asyncio.Task] = []
    client: Client | None = None
    try:
        for _ in range(2):
            ad = AppData().set(storage, as_type=StreamStorage)
            ad.set(state, as_type=StateProvider)
            ad.set(reminders, as_type=ReminderStorage)
            s = Server(
                address="127.0.0.1:0",
                registry=_build_registry(),
                cluster_provider=LocalClusterProvider(members),
                object_placement_provider=placement,
                transport=transport,
                app_data=ad,
                reminder_daemon=True,
                reminder_daemon_config=ReminderDaemonConfig(
                    poll_interval=0.05, lease_ttl=2.0
                ),
                # Full-fidelity edge capture: the shipping 1-in-8 stride
                # needs thousands of dispatches per edge to stabilize; a
                # short A/B phase leaves most pairs unsampled and the
                # refine can only co-locate edges it can see. Overhead is
                # measure_sampler_overhead's problem, not this harness's.
                affinity_stride=1,
                # Tail-capture everything the span stride clocks: delivery
                # requests are fast, and only an aggressive SLO keeps the
                # wire hops visible on the rings for the waterfall proof.
                spans_slo_ms=0.001,
            )
            await s.prepare()
            await s.bind()
            servers.append(s)
        tasks = [asyncio.create_task(s.run()) for s in servers]
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if len(await members.active_members()) >= 2:
                break
            await asyncio.sleep(0.02)
        client = Client(members, transport=transport)

        n_parts = storage.num_partitions
        keys = _partition_keys(STREAM, n_parts)
        node0, node1 = servers[0].local_address, servers[1].local_address
        for addr in (node0, node1):
            placement.register_node(addr)

        # Adversarial pre-seat BEFORE any traffic (activation follows the
        # directory): every cursor on node 0, every consumer on node 1 —
        # a balanced seating a load-only solver has no reason to change,
        # and the worst one for bytes-over-TCP.
        echo_t, prod_t = type_id(EchoActor), type_id(ProducerActor)
        await placement.update(ObjectPlacementItem(ObjectId(prod_t, "prod"), node0))
        for p in range(n_parts):
            await placement.update(
                ObjectPlacementItem(
                    ObjectId(CURSOR_TYPE, cursor_id(STREAM, GROUP, p)), node0
                )
            )
        for key in keys:
            await placement.update(ObjectPlacementItem(ObjectId(echo_t, key), node1))
        # Seat the reminder shards evenly too. The daemons auto-place all
        # of them on whichever node looks them up first, which skews the
        # directory so hard that a plain LOAD re-solve evicts the cursors
        # off node 0 — and with only two nodes, any eviction lands them
        # beside their consumers "for free". Balancing the bystanders
        # keeps the blind seating load-optimal, so the greedy keep-phase
        # is a no-op and only the affinity refine can justify the moves:
        # the measured byte drop is attributable to the edge graph, not
        # to load-balancing luck.
        for i in range(reminders.num_shards):
            await placement.update(
                ObjectPlacementItem(
                    ObjectId(REMINDER_SHARD_TYPE, str(i)),
                    node0 if i % 2 == 0 else node1,
                )
            )
        await client.subscribe_stream(
            STREAM, GROUP, EchoActor, redelivery_period=redelivery_period
        )

        published = 0

        async def produce_and_drain(n: int) -> None:
            nonlocal published
            await client.send(
                ProducerActor,
                "prod",
                Produce(n=n, pad_bytes=pad_bytes, keys=keys),
                returns=Echo,
            )
            published += n
            deadline = time.monotonic() + drain_timeout
            while sum((await storage.cursors(STREAM, GROUP)).values()) < published:
                if time.monotonic() > deadline:
                    done = sum((await storage.cursors(STREAM, GROUP)).values())
                    raise RuntimeError(
                        f"delivery stalled: {done}/{published} committed"
                    )
                await asyncio.sleep(0.005)

        def tcp_total() -> int:
            return sum(
                s.affinity.tcp_in_bytes + s.affinity.tcp_out_bytes for s in servers
            )

        from ..admin import cluster_edges, scrape_spans

        async def span_marks() -> dict[str, int]:
            snaps = await scrape_spans(client, members, limit=1)
            return {s.address: s.node_seq for s in snaps}

        delivery_prefix = f"{echo_t}/"

        async def delivery_spans_since(marks: dict[str, int]) -> int:
            """Wire ``request`` spans for consumer-side delivery hops
            retained after ``marks`` — each one is a delivery that
            crossed TCP (local-first in-process sends never hit the
            transport span path)."""
            snaps = await scrape_spans(client, members, limit=4096)
            count = 0
            for snap in snaps:
                base = marks.get(snap.address, 0)
                for rec in snap.spans():
                    if rec.seq <= base or rec.name != "request":
                        continue
                    if str(rec.attrs.get("handler", "")).startswith(delivery_prefix):
                        count += 1
            return count

        # Warm phase: activate the whole chain (and the span stride) so
        # neither measured phase pays first-touch costs.
        await produce_and_drain(max(16, n_records // 8))

        # -- blind phase --------------------------------------------------
        marks = await span_marks()
        t0 = tcp_total()
        await produce_and_drain(n_records)
        blind_bytes = tcp_total() - t0
        blind_spans = await delivery_spans_since(marks)

        # -- feedback: scrape (over the wire) → merge → solve -------------
        rows = await cluster_edges(client, members)
        installed = placement.set_edge_graph(rows)
        moves = await placement.rebalance(delta=False)
        # Capture the refine trajectory NOW: later daemon full solves
        # re-run the refine against the already-co-located directory
        # (cut 0 at pass 0, nothing to accept) and overwrite it.
        refine_history = list(placement._affinity_history)
        # `stats` races with concurrent daemon-driven solves two ways: a
        # sibling attempt discarded by OUR epoch bump records itself as
        # the latest event, and a sibling that snapshotted `prior` before
        # our solve published drops our entry from the archive entirely.
        # Scan the archive first, then fall back to the refine history —
        # an accepted pass > 0 is the refine hook's own record that this
        # feedback cycle's solve took the affinity term.
        solved_as = placement.stats.mode
        if "+affinity" not in str(solved_as):
            for s in reversed(placement.stats.history):
                if "+affinity" in str(s.mode):
                    solved_as = s.mode
                    break
        if "+affinity" not in str(solved_as) and any(
            h["accepted"] and h["pass"] > 0 for h in refine_history
        ):
            solved_as = f"{solved_as}+affinity"

        # Settle: let cursors re-pump once against the new directory so
        # the affinity phase measures steady state, not the cutover.
        await produce_and_drain(max(16, n_records // 8))

        # -- affinity phase -----------------------------------------------
        marks = await span_marks()
        t0 = tcp_total()
        await produce_and_drain(n_records)
        affinity_bytes = tcp_total() - t0
        affinity_spans = await delivery_spans_since(marks)

        done = sum((await storage.cursors(STREAM, GROUP)).values())
        if done != published:
            raise RuntimeError(f"record loss: {done}/{published} committed")

        pairs_local = 0
        for p, key in enumerate(keys):
            c = await placement.lookup(
                ObjectId(CURSOR_TYPE, cursor_id(STREAM, GROUP, p))
            )
            e = await placement.lookup(ObjectId(echo_t, key))
            pairs_local += int(c == e)
        return {
            "n_records": n_records,
            "pad_bytes": pad_bytes,
            "partitions": n_parts,
            "edges_scraped": len(rows),
            "edges_installed": installed,
            "moves": moves,
            "solved_as": solved_as,
            "pairs_colocated": pairs_local,
            "tcp_bytes": {"blind": blind_bytes, "affinity": affinity_bytes},
            "bytes_ratio": round(blind_bytes / max(affinity_bytes, 1), 2),
            "delivery_wire_spans": {
                "blind": blind_spans,
                "affinity": affinity_spans,
            },
            "refine_history": refine_history,
            "delivered": published,
        }
    finally:
        if client is not None:
            client.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


async def measure_sampler_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 128,
    n_objects: int = 256,
    cycles: int = 16,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with the edge sampler off vs on (stride 8).

    Batches are deliberately longer than the other ``*_live`` overhead
    A/Bs (4096 requests each): per-batch paired ratios on this workload
    swing far wider than the effect under test, and the median only
    resolves a percent-level overhead once each batch spans a few
    hundred milliseconds of box weather. Two symmetries cancel the two
    biases this harness actually exhibited:

    * **measurement order** — each cycle runs off→on→on→off and averages
      the two ratios (ABBA), so within-pair speed drift cancels;
    * **boot order** — the whole measurement runs twice, once with the
      off cluster booted first and once with the on cluster booted
      first, and the per-order medians are averaged. The SECOND-booted
      pair of servers on a shared loop is consistently a few percent
      slower (an off-vs-off control under ABBA read +4.5% on a quiet
      box — pure boot-order artifact), which a fixed boot order aliases
      straight into the "overhead".

    Returns best-of msgs/sec per mode plus ``sampler_overhead_pct``
    (positive = sampler slower) and the on-clusters' sample counters —
    asserted > 0 so the priced clusters actually observed edges, with
    the off clusters asserted sampler-free.
    """
    import statistics

    rates: dict[str, list[float]] = {"off": [], "on": []}
    sampled_total = 0
    edges_total = 0
    order_medians: list[float] = []
    for boot_order in (("off", "on"), ("on", "off")):
        clusters: dict[str, tuple] = {}  # name -> (client, tasks, servers)
        try:
            for name in boot_order:
                members, placement, tasks, servers = await boot_echo_cluster(
                    n_servers,
                    transport=transport,
                    server_kwargs={"affinity_sampler": name == "on"},
                )
                tname = type_id(EchoActor)
                for i in range(n_objects):
                    await placement.update(
                        ObjectPlacementItem(
                            ObjectId(tname, f"w{i}"),
                            servers[i % n_servers].local_address,
                        )
                    )
                client = Client(members, transport=transport)
                clusters[name] = (client, tasks, servers)
                for i in range(n_objects):
                    await client.send(
                        EchoActor, f"w{i}", Echo(value=i), returns=Echo
                    )

            async def batch(name: str) -> float:
                client = clusters[name][0]
                total = n_workers * requests_per_batch

                async def worker(w: int) -> None:
                    for r in range(requests_per_batch):
                        oid = f"w{(w * requests_per_batch + r) % n_objects}"
                        await client.send(
                            EchoActor, oid, Echo(value=r), returns=Echo
                        )

                gc.collect()
                gc.disable()
                try:
                    t0 = time.perf_counter()
                    await asyncio.gather(*[worker(w) for w in range(n_workers)])
                    elapsed = time.perf_counter() - t0
                finally:
                    gc.enable()
                return total / elapsed

            for name in clusters:  # discarded warm batch per mode
                await batch(name)
            ratios: list[float] = []
            for _ in range(max(1, cycles // 2)):
                off_a = await batch("off")
                on_a = await batch("on")
                on_b = await batch("on")
                off_b = await batch("off")
                rates["off"] += [off_a, off_b]
                rates["on"] += [on_a, on_b]
                ratios.append((off_a / on_a + off_b / on_b) / 2.0 - 1.0)
            order_medians.append(statistics.median(ratios))

            on_servers = clusters["on"][2]
            sampled = sum(s.affinity.sampled for s in on_servers)
            assert sampled > 0, "on-cluster sampler observed nothing"
            sampled_total += sampled
            edges_total += sum(len(s.affinity._edges) for s in on_servers)
            for s in clusters["off"][2]:
                assert s.affinity is None, "off-cluster is not a real control"
        finally:
            for client, tasks, _servers in clusters.values():
                client.close()
                for t in tasks:
                    t.cancel()
            for _client, tasks, _servers in clusters.values():
                await asyncio.gather(*tasks, return_exceptions=True)

    overhead = sum(order_medians) / len(order_medians)
    return {
        "msgs_per_sec": {m: round(max(rates[m]), 1) for m in rates},
        "sampler_overhead_pct": round(overhead * 100.0, 2),
        "overhead_pct_by_boot_order": [
            round(m * 100.0, 2) for m in order_medians
        ],
        "sampled_on": sampled_total,
        "edges_on": edges_total,
        "batches": max(1, cycles // 2) * 4 * 2,
        "n_requests_per_batch": n_workers * requests_per_batch,
    }
