"""Measured throughput of the durable-stream data path on the live loop.

The streams subsystem (``rio_tpu/streams/``) promises two things worth
pricing on a real cluster: a publish is acked only after the append hit
:class:`~rio_tpu.streams.StreamStorage` (durability is on the request
path), and delivery is at-least-once with the reminder subsystem as the
redelivery backstop (missed wakes are caught by reminder fires). This
module measures both the same way ``faults_live`` prices its wrappers:
two cluster configurations, identical traffic, one process —

* **off** — the backstop idle: no :class:`ReminderStorage` in AppData, no
  reminder daemon; delivery rides the publish-time cursor wake alone;
* **on** — the backstop ticking hard: the reminder daemon polls at
  0.05 s and every partition's redelivery reminder fires at 0.05 s (a
  40x harder cadence than the shipping 2 s default), so each timed batch
  pays the full at-least-once machinery while the same publishes flow.

The measurement discipline is inherited from ``tracing_live``: both
clusters boot once and coexist, GC is collected before and disabled
during each timed batch, batches interleave in alternating order, and
the headline is the MEDIAN of per-batch paired off/on ratios on the
end-to-end (publish → every record committed-after-delivery) rate. The
acked-publish rate is reported per mode too — that is the producer-facing
durability cost, independent of consumption.
"""

from __future__ import annotations

import asyncio
import gc
import time

from .. import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalReminderStorage,
    LocalStorage,
    ReminderDaemonConfig,
    ReminderStorage,
    Server,
)
from ..cluster.membership_protocol import LocalClusterProvider
from ..state import LocalState, StateProvider
from ..streams import LocalStreamStorage, StreamStorage
from .routing_live import Echo, EchoActor, build_echo_registry

STREAM = "bench-orders"
GROUP = "bench-sink"


async def measure_streams_overhead(
    *,
    n_servers: int = 2,
    publishes_per_batch: int = 96,
    batches: int = 12,
    n_keys: int = 16,
    transport: str = "asyncio",
) -> dict:
    """A/B the stream data path with the redelivery backstop idle vs ticking.

    Returns best-of acked-publish and end-to-end deliver rates per mode
    plus ``redelivery_overhead_pct`` (median per-batch paired off/on
    ratio on the end-to-end rate, positive = the ticking backstop is
    slower). Both modes must deliver every acked publish — the zero-loss
    check rides along with the throughput number.
    """
    import statistics

    modes = {
        "off": {"daemon": False, "period": 3600.0},
        "on": {"daemon": True, "period": 0.05},
    }
    # name -> (client, tasks, storage)
    clusters: dict[str, tuple] = {}
    pub_rates: dict[str, list[float]] = {m: [] for m in modes}
    e2e_rates: dict[str, list[float]] = {m: [] for m in modes}
    published: dict[str, int] = {m: 0 for m in modes}
    all_tasks: list[asyncio.Task] = []
    try:
        for name, cfg in modes.items():
            storage = LocalStreamStorage()
            state = LocalState()
            members = LocalStorage()
            placement = LocalObjectPlacement()
            reminders = LocalReminderStorage() if cfg["daemon"] else None
            tasks: list[asyncio.Task] = []
            for _ in range(n_servers):
                ad = AppData().set(storage, as_type=StreamStorage)
                ad.set(state, as_type=StateProvider)
                server_kwargs: dict = {}
                if reminders is not None:
                    ad.set(reminders, as_type=ReminderStorage)
                    server_kwargs = {
                        "reminder_daemon": True,
                        "reminder_daemon_config": ReminderDaemonConfig(
                            poll_interval=0.05, lease_ttl=2.0
                        ),
                    }
                s = Server(
                    address="127.0.0.1:0",
                    registry=build_echo_registry(),
                    cluster_provider=LocalClusterProvider(members),
                    object_placement_provider=placement,
                    transport=transport,
                    app_data=ad,
                    **server_kwargs,
                )
                await s.prepare()
                await s.bind()
                tasks.append(asyncio.create_task(s.run()))
            all_tasks.extend(tasks)
            deadline = asyncio.get_event_loop().time() + 10.0
            while asyncio.get_event_loop().time() < deadline:
                if len(await members.active_members()) >= n_servers:
                    break
                await asyncio.sleep(0.02)
            client = Client(members, transport=transport)
            await client.subscribe_stream(
                STREAM, GROUP, EchoActor, redelivery_period=cfg["period"]
            )
            clusters[name] = (client, tasks, storage)

        async def batch(name: str) -> tuple[float, float]:
            client, _, storage = clusters[name]
            n = publishes_per_batch
            target = published[name] + n
            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                for i in range(n):
                    await client.publish_stream(
                        STREAM, Echo(value=i), key=f"k{i % n_keys}"
                    )
                t_acked = time.perf_counter()
                while sum((await storage.cursors(STREAM, GROUP)).values()) < target:
                    await asyncio.sleep(0.001)
                t_done = time.perf_counter()
            finally:
                gc.enable()
            published[name] = target
            return n / (t_acked - t0), n / (t_done - t0)

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                po, eo = await batch("off")
                pr, er = await batch("on")
            else:
                pr, er = await batch("on")
                po, eo = await batch("off")
            pub_rates["off"].append(po)
            pub_rates["on"].append(pr)
            e2e_rates["off"].append(eo)
            e2e_rates["on"].append(er)
            ratios.append(eo / er - 1.0)

        # Zero-loss contract per mode: every acked publish is committed
        # behind a delivery (cursor sums count delivered-then-committed
        # records only).
        delivered: dict[str, int] = {}
        partitions: dict[str, int] = {}
        for name, (_, _, storage) in clusters.items():
            cur = await storage.cursors(STREAM, GROUP)
            delivered[name] = sum(cur.values())
            partitions[name] = len(cur)
            if delivered[name] != published[name]:
                raise RuntimeError(
                    f"{name}: {published[name]} acked publishes but only "
                    f"{delivered[name]} delivered+committed"
                )
    finally:
        for client, _, _ in clusters.values():
            client.close()
        for t in all_tasks:
            t.cancel()
        await asyncio.gather(*all_tasks, return_exceptions=True)

    return {
        "publish_acks_per_sec": {k: round(max(v), 1) for k, v in pub_rates.items()},
        "deliver_msgs_per_sec": {k: round(max(v), 1) for k, v in e2e_rates.items()},
        "redelivery_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "delivered": delivered,
        "partitions_active": partitions,
        "publishes_per_batch": publishes_per_batch,
        "batches": batches,
    }
