"""Measured request-waterfall span-retention overhead on the live RPC loop.

The span ring (``rio_tpu/spans.py``) promises the request path pays
~nothing for waterfall retention when nothing upstream traces: the null
fast path is untouched, phase clocks attach only on a 1-in-8 stride of
untraced requests (plus every traced one), and retention itself is a few
attribute stores into a preallocated ring. This module *measures* that
promise with the ``series_live`` discipline — two cluster configurations,
identical traffic, one process:

* **off** — servers booted with ``spans=False``: no ring, no phase
  stamping, the transports' pre-waterfall paths byte-for-byte.
* **on** — retention enabled with head sampling OFF and tail capture
  ARMED at an aggressive SLO (default 1 ms — far below the shipping
  250 ms default), so the priced configuration actually exercises the
  stride, the phase stamps, AND the retention write, not just the
  disabled check.

Both clusters boot once and coexist, placement is pre-seated identically,
GC is collected before and disabled during each timed batch, and the
artifact is the MEDIAN of per-batch paired off/on ratios (batch k's two
runs share the same seconds of box weather).
"""

from __future__ import annotations

import asyncio
import gc
import time

from .. import Client
from .routing_live import Echo, EchoActor, boot_echo_cluster


async def measure_spans_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 64,
    n_objects: int = 256,
    batches: int = 24,
    slo_ms: float = 1.0,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with span retention off vs on (tail capture armed).

    Returns best-of msgs/sec per mode plus ``spans_overhead_pct`` (the
    median per-batch paired ratio of off/on, positive = slower) and the
    on-cluster's retention counters — ``tail_captured_on`` asserted > 0 so
    the A/B priced a cluster whose stride/SLO path actually retained
    spans, and the off-cluster is asserted ring-free so it is a real
    control.
    """
    import statistics

    modes = {"off": False, "on": True}
    clusters: dict[str, tuple] = {}  # name -> (client, tasks, servers)
    rates: dict[str, list[float]] = {name: [] for name in modes}
    try:
        for name, spans_on in modes.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                server_kwargs={
                    "spans": spans_on,
                    # A tight SLO keeps tail capture genuinely firing under
                    # batch concurrency (queueing alone crosses 1 ms), so
                    # the measured bar includes real retention writes.
                    "spans_slo_ms": slo_ms,
                },
            )
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks, servers)
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        async def batch(name: str) -> float:
            client = clusters[name][0]
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
            return total / elapsed

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                o = await batch("off")
                r = await batch("on")
            else:
                r = await batch("on")
                o = await batch("off")
            rates["off"].append(o)
            rates["on"].append(r)
            ratios.append(o / r - 1.0)
        on_servers = clusters["on"][2]
        retained = sum(s.spans.retained for s in on_servers)
        tail_captured = sum(s.spans.tail_captured for s in on_servers)
        if tail_captured <= 0:
            raise RuntimeError(
                "spans=True cluster tail-captured nothing — the A/B priced "
                "only the disabled check (SLO too high for this box?)"
            )
        off_servers = clusters["off"][2]
        if any(s.spans is not None for s in off_servers):
            raise RuntimeError("spans=False cluster still built a ring")
    finally:
        for client, tasks, _ in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks, _ in clusters.values() for t in tasks],
            return_exceptions=True,
        )

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "spans_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "retained_on": int(retained),
        "tail_captured_on": int(tail_captured),
        "slo_ms": slo_ms,
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }
