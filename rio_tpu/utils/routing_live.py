"""Measured route hops on a live in-process cluster.

The numpy model in :mod:`rio_tpu.utils.routing_sim` *estimates* the
BASELINE route-hop headline; this module *measures* it: boot N real
servers on ephemeral loopback ports inside one event loop (the reference's
integration harness shape, ``rio-rs/tests/client_server_integration_test.rs:
153-180`` / ``tests/server_utils.rs:49-139``), pre-allocate a population of
objects, then drive one cold-cache request per object under each routing
policy and count actual network round trips via :class:`rio_tpu.client.
ClientStats`:

* **reference policy** — random active server on placement-cache miss
  (``client/mod.rs:255-262``); a wrong pick costs a real ``Redirect``
  response plus a second round trip.
* **rio-tpu policy** — ``placement_resolver`` pointed at the shared
  placement directory (the :class:`JaxObjectPlacement` host mirror in
  production); the owner is dialed directly.

Every hop counted here crossed a real TCP socket and the full
encode/dispatch/decode path — no simulation.
"""

from __future__ import annotations

import asyncio
import random as _random
from dataclasses import dataclass

from .. import AppData, Client, LocalObjectPlacement, LocalStorage, Registry, Server
from .. import ServiceObject, handler, message
from ..cluster.membership_protocol import LocalClusterProvider
from ..registry import ObjectId, type_id


@message(name="routing_live.Echo")
class Echo:
    value: int = 0


class EchoActor(ServiceObject):
    """Minimal actor: the request path is the thing under test."""

    @handler
    async def echo(self, msg: Echo, ctx: AppData) -> Echo:
        return msg


def build_echo_registry() -> Registry:
    """Factory spec target for sharded workers / bench children
    (``rio_tpu.utils.routing_live:build_echo_registry``)."""
    return Registry().add_type(EchoActor)


@dataclass
class LiveHopStats:
    mean: float
    p50: float
    p99: float
    n_requests: int

    def as_dict(self) -> dict:
        return {
            "mean": round(self.mean, 3),
            "p50": self.p50,
            "p99": self.p99,
            "n": self.n_requests,
        }


def _stats(hops: list[int]) -> LiveHopStats:
    s = sorted(hops)
    n = len(s)
    return LiveHopStats(
        mean=sum(s) / n,
        p50=float(s[n // 2]),
        p99=float(s[min(n - 1, (n * 99) // 100)]),
        n_requests=n,
    )


async def boot_echo_cluster(
    n_servers: int,
    *,
    transport: str = "asyncio",
    members=None,
    placement=None,
    server_kwargs: dict | None = None,
):
    """Boot N echo servers on loopback.

    Returns ``(members, placement, tasks, servers)``. Shared helper for the
    measured benchmarks (route hops, RPC throughput). Callers cancel the
    returned tasks to tear the cluster down. ``server_kwargs`` are forwarded
    to every :class:`Server` (the tracing A/B boots with ``metrics=False``
    to reconstruct the pre-metrics hot path); ``members``/``placement``
    substitute the storage backends (the faults A/B boots over idle
    fault-injection wrappers).
    """
    members = members if members is not None else LocalStorage()
    placement = placement if placement is not None else LocalObjectPlacement()
    servers: list[Server] = []
    tasks: list[asyncio.Task] = []
    try:
        for _ in range(n_servers):
            s = Server(
                address="127.0.0.1:0",
                registry=build_echo_registry(),
                cluster_provider=LocalClusterProvider(members),
                object_placement_provider=placement,
                transport=transport,
                **(server_kwargs or {}),
            )
            await s.prepare()
            await s.bind()
            servers.append(s)
        tasks = [asyncio.create_task(s.run()) for s in servers]
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if len(await members.active_members()) >= n_servers:
                break
            await asyncio.sleep(0.02)
    except BaseException:
        # Boot failed or was cancelled mid-wait: never leak running
        # server tasks (the caller's finally hasn't been entered yet).
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    return members, placement, tasks, servers


async def measure_route_hops_live(
    *,
    n_servers: int = 8,
    n_objects: int = 1024,
    seed: int = 0,
    transport: str = "asyncio",
    placement=None,
    sample_size: int | None = None,
) -> dict[str, LiveHopStats]:
    """Boot a cluster, measure per-request hops under both client policies.

    Returns ``{"reference": LiveHopStats, "rio_tpu": LiveHopStats}``. Each
    sampled object is requested once per policy with a cold placement LRU,
    so every request exercises the cache-miss routing decision — the case
    the policies differ on. Pass ``placement`` (e.g. a JaxObjectPlacement)
    to run the cluster on a specific provider; allocation is concurrent,
    hop measurement sequential over ``sample_size`` (default: all) ids.
    """
    members, placement, tasks, _servers = await boot_echo_cluster(
        n_servers, transport=transport, placement=placement
    )
    try:
        ids = [f"obj-{i}" for i in range(n_objects)]
        # Warm-up pass: allocate every object somewhere (random landing →
        # near-uniform spread, like organic traffic would produce).
        setup = Client(members)
        for base in range(0, n_objects, 512):
            await asyncio.gather(
                *[
                    setup.send(EchoActor, oid, Echo(value=1), returns=Echo)
                    for oid in ids[base : base + 512]
                ]
            )
        setup.close()

        async def directory_resolver(handler_type: str, handler_id: str) -> str | None:
            return await placement.lookup(ObjectId(handler_type, handler_id))

        sample = list(ids)
        _random.Random(seed).shuffle(sample)
        if sample_size is not None:
            sample = sample[:sample_size]

        async def run_policy(resolver) -> LiveHopStats:
            client = Client(members, placement_resolver=resolver)
            hops: list[int] = []
            for oid in sample:
                before = client.stats.roundtrips
                await client.send(EchoActor, oid, Echo(value=2), returns=Echo)
                hops.append(client.stats.roundtrips - before)
            client.close()
            return _stats(hops)

        reference = await run_policy(None)
        ours = await run_policy(directory_resolver)
        return {"reference": reference, "rio_tpu": ours}
    finally:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


async def measure_route_hops_scaled(
    *,
    n_servers: int = 64,
    n_objects: int = 50_000,
    wrong_fraction: float = 0.08,
    dead_servers: int = 4,
    seed: int = 0,
    sample_size: int = 8_000,
) -> dict:
    """Large-scale live routing evidence, including graceful degradation.

    Boots ``n_servers`` real servers, allocates ``n_objects`` actors, then
    measures per-request roundtrips (exact, sequential, over a shuffled
    ``sample_size`` sample of the live population) under three policies:

    * ``reference`` — random pick on cache miss (the reference policy,
      ``client/mod.rs:255-262``);
    * ``directory`` — fresh shared-directory resolver (rio-tpu policy);
    * ``stale``     — the SAME directory policy fed a frozen snapshot
      poisoned two ways: ``wrong_fraction`` of entries point at the wrong
      (live) node, and every object owned by ``dead_servers`` killed nodes
      still points at its dead address. This is the claim BASELINE rows
      1-2 actually make: a stale directory must degrade to redirects and
      dial-failure fallback (bounded extra hops), never to failed requests.

    Returns ``{"reference"|"directory"|"stale": LiveHopStats-as-dict,
    "stale_failures": int, "n_servers": int, "n_objects": int,
    "displaced": int, "wrong": int}``.
    """
    members, placement, tasks, servers = await boot_echo_cluster(n_servers)
    rng = _random.Random(seed)
    try:
        ids = [f"obj-{i}" for i in range(n_objects)]
        setup = Client(members)
        # Allocate the population concurrently (placement + activation out
        # of the measured region).
        for base in range(0, n_objects, 512):
            await asyncio.gather(
                *[
                    setup.send(EchoActor, oid, Echo(value=1), returns=Echo)
                    for oid in ids[base : base + 512]
                ]
            )
        setup.close()

        tname = type_id(EchoActor)
        addresses = [await placement.lookup(ObjectId(tname, oid)) for oid in ids]
        snapshot = {o: a for o, a in zip(ids, addresses) if a is not None}

        async def measure_seq(resolver, sample: list[str]) -> tuple[LiveHopStats, int]:
            client = Client(members, placement_resolver=resolver)
            hops: list[int] = []
            failures = 0
            for oid in sample:
                # A "hop" is any network attempt: completed roundtrips plus
                # dials that died on a dead address (the stale-directory
                # cost would be invisible without them).
                before = client.stats.roundtrips + client.stats.dial_failures
                try:
                    await client.send(EchoActor, oid, Echo(value=2), returns=Echo)
                    hops.append(
                        client.stats.roundtrips + client.stats.dial_failures - before
                    )
                except Exception:
                    failures += 1
            client.close()
            return _stats(hops) if hops else _stats([0]), failures

        sample = list(ids)
        rng.shuffle(sample)
        sample = sample[: min(n_objects, sample_size)]

        reference, _ = await measure_seq(None, sample)

        async def fresh_resolver(handler_type: str, handler_id: str) -> str | None:
            return await placement.lookup(ObjectId(handler_type, handler_id))

        directory, _ = await measure_seq(fresh_resolver, sample)

        # ---- staleness: kill nodes + poison the frozen snapshot ---------
        live_addrs = sorted(snapshot.values())
        victims = {s.local_address for s in servers[:dead_servers]}
        displaced = [o for o, a in snapshot.items() if a in victims]
        pool = sorted(set(live_addrs) - victims)
        n_wrong = int(len(snapshot) * wrong_fraction)
        wrong = 0
        for oid in rng.sample(ids, n_wrong):
            cur = snapshot.get(oid)
            others = [a for a in pool if a != cur]
            if cur is not None and cur not in victims and others:
                snapshot[oid] = rng.choice(others)
                wrong += 1

        # Kill the victims for real; mark them dead in membership (the
        # LocalClusterProvider has no failure detector) and let the REACTIVE
        # path re-materialize their objects on first touch — the stale run
        # below is that first touch for most of them.
        for srv, task in zip(servers, tasks):
            if srv.local_address in victims:
                task.cancel()
        await asyncio.gather(
            *[t for s, t in zip(servers, tasks) if s.local_address in victims],
            return_exceptions=True,
        )
        for v in victims:
            host, _, port = v.rpartition(":")
            await members.set_inactive(host, int(port))

        async def stale_resolver(handler_type: str, handler_id: str) -> str | None:
            return snapshot.get(handler_id)

        stale, stale_failures = await measure_seq(stale_resolver, sample)

        return {
            "reference": reference.as_dict(),
            "directory": directory.as_dict(),
            "stale": stale.as_dict(),
            "stale_failures": stale_failures,
            "n_servers": n_servers,
            "n_objects": n_objects,
            "dead_servers": dead_servers,
            "displaced": len(displaced),
            "wrong": wrong,
        }
    finally:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


async def measure_rpc_throughput(
    *,
    n_servers: int = 2,
    n_workers: int = 64,
    requests_per_worker: int = 400,
    n_objects: int = 1024,
    transport: str = "asyncio",
) -> float:
    """Messages/sec through the full actor data plane (real TCP loopback).

    ``n_workers`` concurrent senders share one client (per-address
    connection pool) and round-robin over ``n_objects`` actors — the shape
    of the reference's only load artifact, the metric-aggregator 20k-send
    driver (``metric_aggregator_loadall.rs:26-37``), but concurrent.
    ``transport`` selects the asyncio or the native (C++ epoll) data plane
    on both servers and client.
    """
    members, _placement, tasks, _servers = await boot_echo_cluster(
        n_servers, transport=transport
    )
    client = Client(members, transport=transport)
    try:
        return await _drive_echo_load(
            client, n_workers, requests_per_worker, n_objects
        )
    finally:
        client.close()
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


async def _drive_echo_load(
    client, n_workers: int, requests_per_worker: int, n_objects: int
) -> float:
    """Warm the echo population, then one timed concurrent window."""
    import time

    for i in range(n_objects):
        await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)
    total = n_workers * requests_per_worker

    async def worker(w: int) -> None:
        for r in range(requests_per_worker):
            oid = f"w{(w * requests_per_worker + r) % n_objects}"
            await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

    t0 = time.perf_counter()
    await asyncio.gather(*[worker(w) for w in range(n_workers)])
    return total / (time.perf_counter() - t0)


async def measure_rpc_external(
    members,
    *,
    n_workers: int = 64,
    requests_per_worker: int = 400,
    n_objects: int = 512,
    transport: str = "asyncio",
) -> float:
    """Messages/sec against an EXTERNAL cluster (servers in other
    processes, e.g. a :class:`rio_tpu.sharded.ShardedServer`): same load
    shape as :func:`measure_rpc_throughput`, but this process runs only
    the client side. ``members`` is the shared membership view (e.g. the
    sharded node's sqlite storage)."""
    client = Client(members, transport=transport)
    try:
        return await _drive_echo_load(
            client, n_workers, requests_per_worker, n_objects
        )
    finally:
        client.close()
