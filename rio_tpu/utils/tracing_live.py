"""Measured tracing/metrics overhead on the live RPC loop.

The observability layer promises a near-free default: with sampling at 0
the only per-request additions are one histogram record (O(1), no locks,
no allocations on the steady state) and a contextvar check. This module
*measures* that promise on the same real-TCP echo loop as
``measure_rpc_throughput``: three cluster configurations, identical
traffic, one process —

* **disabled** — servers booted with ``metrics=False``, sample rate 0:
  the spans-disabled null path (no registry in AppData, the null trace
  object on every request) — this is the pre-observability hot path.
* **record** — the shipping default: per-handler RED histograms on,
  sampling still 0 (counts exact every request, durations stride-sampled
  1-in-8). The acceptance bar lives here: ``record`` vs ``disabled`` is
  the overhead every deployment pays.
* **sampled** — sample rate 1.0 with a live (counting) sink: every
  request roots a span, carries trace_ctx on the wire, adopts it
  server-side and stashes exemplars. The worst case, priced explicitly.

Measuring a 1-2% effect under ±10% box drift takes design, not repeats
(the first cut — one cluster per mode per round — read anywhere from -1%
to +8% across invocations):

* all three clusters boot ONCE and coexist; the benchmark alternates
  sub-second timed batches between them, so each paired ratio compares
  the same seconds of box weather;
* tracing globals (sample rate, sinks) are switched per batch — a sink
  registered for the sampled cluster would otherwise turn every span in
  the process live and contaminate the disabled/record batches;
* GC is collected before and disabled during each timed batch: cyclic
  collections over the live three-cluster heap land as multi-ms pauses on
  whichever batch they hit;
* the artifact is the MEDIAN of per-batch paired ratios (batch k's
  disabled/record share a time window), with best-of throughput reported
  only for eyeballing absolute rates.
"""

from __future__ import annotations

import asyncio
import gc
import time

from .. import Client, tracing
from .routing_live import Echo, EchoActor, boot_echo_cluster


async def measure_tracing_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 64,
    n_objects: int = 256,
    batches: int = 24,
    transport: str = "asyncio",
) -> dict:
    """A/B/C the RPC loop across the three observability configurations.

    Returns best-of msgs/sec per mode plus overheads vs ``disabled``
    (positive = slower) as median per-batch paired ratios, in percent.
    """
    import statistics

    modes = {
        "disabled": dict(metrics=False, sample_rate=0.0, sink=False),
        "record": dict(metrics=True, sample_rate=0.0, sink=False),
        "sampled": dict(metrics=True, sample_rate=1.0, sink=True),
    }
    sunk = [0]
    sink_fn = lambda s: sunk.__setitem__(0, sunk[0] + 1)  # noqa: E731

    tracing.clear_sinks()
    tracing.set_sample_rate(0.0)
    clusters: dict[str, tuple] = {}  # name -> (client, tasks)
    rates: dict[str, list[float]] = {name: [] for name in modes}
    try:
        for name, cfg in modes.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                server_kwargs={"metrics": cfg["metrics"]},
            )
            # Seat object i on server i%N in EVERY cluster before first
            # touch: the provider's own (random) choice gives each boot a
            # different split across servers, and a skewed split shifts
            # per-connection pipelining enough to read as a durable
            # few-percent throughput difference between the clusters.
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks)
            # Warm untimed: placement, activation, connection pools, codec
            # caches — and one full-traffic pass per tracing config so
            # first-touch costs (span plumbing, histogram seating) never
            # land inside a timed batch.
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        async def batch(name: str) -> float:
            cfg = modes[name]
            client = clusters[name][0]
            tracing.set_sample_rate(cfg["sample_rate"])
            tracing.clear_sinks()
            if cfg["sink"]:
                tracing.add_sink(sink_fn)
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
                tracing.clear_sinks()
                tracing.set_sample_rate(0.0)
            return total / elapsed

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        # Each enabled mode is paired against its OWN immediately-adjacent
        # disabled batch (sub-second apart, order alternating): box regimes
        # drift on a seconds timescale, so a ratio across two back-to-back
        # batches cancels what a round-robin over all modes would not.
        ratios: dict[str, list[float]] = {"record": [], "sampled": []}
        for k in range(batches):
            for name in ("record", "sampled"):
                if k % 2 == 0:
                    o = await batch("disabled")
                    r = await batch(name)
                else:
                    r = await batch(name)
                    o = await batch("disabled")
                rates["disabled"].append(o)
                rates[name].append(r)
                ratios[name].append(o / r - 1.0)
        if sunk[0] < batches * n_workers * requests_per_batch:
            raise RuntimeError(
                f"sink saw {sunk[0]} spans for "
                f"{batches * n_workers * requests_per_batch} sampled requests"
            )
    finally:
        tracing.clear_sinks()
        tracing.set_sample_rate(0.0)
        for client, tasks in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks in clusters.values() for t in tasks],
            return_exceptions=True,
        )

    def overhead_pct(mode: str) -> float:
        return round(statistics.median(ratios[mode]) * 100.0, 2)

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "record_overhead_pct": overhead_pct("record"),
        "sampled_overhead_pct": overhead_pct("sampled"),
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }
