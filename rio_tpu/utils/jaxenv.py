"""Pin the current process's jax to CPU, axon-proof.

One shared implementation of the wedge-defense dance used by the test
conftest, the bench orchestrator, and the driver entry's multichip dryrun
(previously three hand-maintained copies of the same jax-internal poke):

1. set ``JAX_PLATFORMS=cpu`` (+ optionally the virtual device count) in the
   environment BEFORE jax initializes a backend;
2. mirror it into live jax config (the env alone is ignored once jax is
   imported);
3. deregister the axon PJRT plugin factory — even under
   ``jax_platforms=cpu`` its discovery hook can run, and against a wedged
   TPU relay that hangs the process indefinitely (observed r1 and r3).

Importing jax here is safe: the hang is in backend *initialization*, not
import.
"""

from __future__ import annotations

import os
import re


def force_cpu(n_devices: int | None = None) -> None:
    """Pin this process to the CPU backend; never touches the TPU relay.

    ``n_devices`` additionally forces that many virtual CPU devices (the
    multichip-dryrun / sharded-test mesh), raising an existing
    ``xla_force_host_platform_device_count`` flag when it is lower.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None:
            flags = (
                flags + f" --xla_force_host_platform_device_count={n_devices}"
            ).strip()
        elif int(m.group(1)) < n_devices:
            flags = flags.replace(
                m.group(0), f"--xla_force_host_platform_device_count={n_devices}"
            )
        os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb

        for reg in ("_backend_factories", "backend_factories"):
            factories = getattr(_xb, reg, None)
            if isinstance(factories, dict):
                factories.pop("axon", None)
    except Exception:  # pragma: no cover - jax-internal surface
        pass
