"""Measured gauge time-series sampling overhead on the live RPC loop.

The series ring (``rio_tpu/timeseries.py``) promises the data path pays
~nothing for trend history: sampling rides the LoadMonitor's existing
cadence (no new task), each tick is one ``server_gauges`` scrape plus a
dict copy, and the request path itself is untouched. This module
*measures* that promise with the ``journal_live`` discipline — two
cluster configurations, identical traffic, one process:

* **off** — servers booted with ``timeseries=False``: no ring, no
  sampler tick, no HealthWatch.
* **on** — sampling at an AGGRESSIVE cadence (default 0.05 s — 20x the
  shipping 1 s default) plus HealthWatch rule evaluation per sample, so
  the measured bar (ISSUE 11: ≤ ~1% at the shipping cadence) is priced
  under far more sampling pressure than production ever sees.

Both clusters boot once and coexist, placement is pre-seated identically,
GC is collected before and disabled during each timed batch, and the
artifact is the MEDIAN of per-batch paired off/on ratios (batch k's two
runs share the same seconds of box weather).
"""

from __future__ import annotations

import asyncio
import gc
import time

from .. import Client
from .routing_live import Echo, EchoActor, boot_echo_cluster


async def measure_series_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 64,
    n_objects: int = 256,
    batches: int = 24,
    sample_interval: float = 0.05,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with gauge time-series sampling off vs on.

    Returns best-of msgs/sec per mode plus ``series_overhead_pct`` (the
    median per-batch paired ratio of off/on, positive = slower) and the
    on-cluster's total sample count — asserted > 0 so the A/B measured a
    cluster that was actually sampling, and the off-cluster is asserted
    ring-free so it is a real control.
    """
    import statistics

    modes = {"off": False, "on": True}
    clusters: dict[str, tuple] = {}  # name -> (client, tasks, servers)
    rates: dict[str, list[float]] = {name: [] for name in modes}
    try:
        for name, series_on in modes.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                server_kwargs={
                    "timeseries": series_on,
                    # The sampler rides the load loop: tick the loop at the
                    # sampling cadence so "on" really samples this fast.
                    "load_interval": sample_interval,
                    "timeseries_interval": sample_interval,
                },
            )
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks, servers)
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        async def batch(name: str) -> float:
            client = clusters[name][0]
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
            return total / elapsed

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                o = await batch("off")
                r = await batch("on")
            else:
                r = await batch("on")
                o = await batch("off")
            rates["off"].append(o)
            rates["on"].append(r)
            ratios.append(o / r - 1.0)
        on_servers = clusters["on"][2]
        sampled = sum(s.timeseries.sampled for s in on_servers)
        if sampled <= 0:
            raise RuntimeError(
                "timeseries=True cluster took no samples — the A/B measured "
                "nothing (load loop not ticking?)"
            )
        alerts_fired = sum(
            s.health_watch.fired_total
            for s in on_servers
            if s.health_watch is not None
        )
        off_servers = clusters["off"][2]
        if any(s.timeseries is not None for s in off_servers):
            raise RuntimeError("timeseries=False cluster still built a ring")
    finally:
        for client, tasks, _ in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks, _ in clusters.values() for t in tasks],
            return_exceptions=True,
        )

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "series_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "samples_on": int(sampled),
        "health_alerts_fired_on": int(alerts_fired),
        "sample_interval_s": sample_interval,
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }
