"""Route-hop simulation for the BASELINE headline metric.

``BASELINE.md``'s target is "placements/sec + p99 route hops @1M objects /
1k nodes". Hops are a *client routing* property, so they are evaluated by
simulating the two client strategies over the same request stream:

* **reference policy** (``rio-rs``): on a placement-cache miss the client
  sends to a *random active server* (``client/mod.rs:255-262``); a wrong
  pick costs a ``Redirect`` round trip (``tower_services.rs:158-209``) —
  2 hops. A request that lands on a dead owner costs redirect +
  ``DeallocateServiceObject`` + retry — 3 hops (``service.rs:261-298``).
* **rio-tpu policy**: the placement directory is a host-mirrored table fed
  by the device solve (``JaxObjectPlacement.lookup`` is an O(1) dict hit,
  no SQL round trip), so clients resolve the owner *before* dialing:
  1 hop, 2 when the snapshot is stale (bounded by churn between refreshes).

The simulation is deterministic (seeded), pure numpy, and intentionally
charges rio-tpu a staleness penalty so the comparison is not a freebie.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class HopStats:
    mean: float
    p50: float
    p99: float

    def as_dict(self) -> dict:
        return {"mean": round(self.mean, 3), "p50": self.p50, "p99": self.p99}


def _percentile(hops: np.ndarray, q: float) -> float:
    return float(np.percentile(hops, q, method="lower"))


def simulate_route_hops(
    *,
    n_objects: int = 1_000_000,
    n_nodes: int = 1_000,
    n_requests: int = 200_000,
    cache_size: int = 1_000,
    zipf_a: float = 1.1,
    dead_owner_rate: float = 0.002,
    stale_directory_rate: float = 0.003,
    seed: int = 0,
) -> dict[str, HopStats]:
    """Simulate both routing policies over one zipf request stream.

    ``cache_size`` models the reference client's 1,000-entry placement LRU
    (``client/mod.rs:137``): with vastly more objects than cache slots the
    hit rate is what the popularity skew gives — everything else is a
    random pick. ``dead_owner_rate`` is the fraction of requests whose
    cached/true owner died since last contact; ``stale_directory_rate`` is
    the chance rio-tpu's host mirror hasn't absorbed a move yet. Defaults
    model gossip-scale churn (nodes die over 10-60 s windows,
    ``peer_to_peer.rs:28-37``) against a request stream that is orders of
    magnitude faster — a fraction of a percent of requests race a death.
    """
    rng = np.random.default_rng(seed)
    # Zipf-ish popularity over object ids (clip the tail into range).
    objects = rng.zipf(zipf_a, size=n_requests) % n_objects

    # Reference: LRU hit => 1 hop (cached owner; may be dead). Miss =>
    # random server: right with p=1/n_nodes, else redirect (2 hops).
    # Simulate the LRU by tracking recency over the stream (exact LRU).
    from collections import OrderedDict

    lru: OrderedDict[int, None] = OrderedDict()
    ref_hops = np.empty(n_requests, np.int32)
    dead = rng.random(n_requests) < dead_owner_rate
    lucky = rng.random(n_requests) < (1.0 / n_nodes)
    for i, obj in enumerate(objects):
        hit = obj in lru
        if hit:
            lru.move_to_end(obj)
        else:
            lru[int(obj)] = None
            if len(lru) > cache_size:
                lru.popitem(last=False)
        if dead[i]:
            # redirect (or cached stale owner) -> deallocate -> retry
            ref_hops[i] = 3
        elif hit or lucky[i]:
            ref_hops[i] = 1
        else:
            ref_hops[i] = 2

    # rio-tpu: directory-resolved dial. Stale entry => one redirect.
    ours_hops = np.where(
        rng.random(n_requests) < (stale_directory_rate + dead_owner_rate), 2, 1
    ).astype(np.int32)

    return {
        "reference": HopStats(
            mean=float(ref_hops.mean()),
            p50=_percentile(ref_hops, 50),
            p99=_percentile(ref_hops, 99),
        ),
        "rio_tpu": HopStats(
            mean=float(ours_hops.mean()),
            p50=_percentile(ours_hops, 50),
            p99=_percentile(ours_hops, 99),
        ),
    }
