"""Measured disabled-overhead of the fault-injection layer on the live loop.

The fault subsystem (``rio_tpu/faults.py``) promises that a DISABLED
schedule prices the data path at exactly zero: flipping
``schedule.enabled = False`` re-arms every attached wrapper into a pure
passthrough (the inner backend's bound methods are swapped onto the
wrapper instance — no extra coroutine, no counters), so the per-request
directory lookup the service layer does is byte-for-byte the bare
backend's call. This module *measures* that promise the same way
``journal_live`` prices the flight recorder: two cluster configurations,
identical traffic, one process —

* **off** — servers booted over bare ``LocalStorage``/``LocalObjectPlacement``;
* **on** — the same backends wrapped in ``FaultyMembershipStorage`` /
  ``FaultyObjectPlacement`` around a DISABLED :class:`~rio_tpu.faults.FaultSchedule`
  (the production posture if the chaos layer ships installed).

The measurement discipline is inherited wholesale from ``tracing_live``:
both clusters boot once and coexist, placement is pre-seated identically,
GC is collected before and disabled during each timed batch, and the
artifact is the MEDIAN of per-batch paired ratios where batch k's off/on
share the same seconds of box weather. A direct-trait lookup micro prices
all three wrapper states — bare, disabled (swap active), and armed-idle
(enabled, zero rules: the gated delegation path with health accounting) —
so the cost ladder is explicit rather than implied.
"""

from __future__ import annotations

import asyncio
import gc
import time

from .. import Client
from ..cluster.storage import LocalStorage
from ..faults import (
    FaultSchedule,
    FaultyMembershipStorage,
    FaultyObjectPlacement,
    StorageHealth,
)
from ..object_placement import LocalObjectPlacement
from .routing_live import Echo, EchoActor, boot_echo_cluster


async def _lookup_rate(placement, n_ops: int) -> float:
    from ..registry import ObjectId, type_id

    oid = ObjectId(type_id(EchoActor), "w0")
    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        for _ in range(n_ops):
            await placement.lookup(oid)
        elapsed = time.perf_counter() - t0
    finally:
        gc.enable()
    return n_ops / elapsed


async def measure_faults_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 64,
    n_objects: int = 256,
    batches: int = 24,
    lookup_ops: int = 20_000,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with the fault wrappers absent vs installed-but-disabled.

    Returns best-of msgs/sec per mode plus ``faults_overhead_pct`` (the
    median per-batch paired ratio of off/on, positive = slower) and the
    direct-trait ``lookup_ops_per_sec`` ladder for bare / disabled /
    armed-idle wrappers. The disabled wrapper is asserted to be in
    passthrough (swap active), and the schedule to have injected NOTHING —
    so the headline number is a pure parity measurement.
    """
    import statistics

    schedule = FaultSchedule(seed=0)
    schedule.enabled = False
    health = StorageHealth()
    storages = {
        "off": (LocalStorage(), LocalObjectPlacement()),
        "on": (
            FaultyMembershipStorage(LocalStorage(), schedule, health),
            FaultyObjectPlacement(LocalObjectPlacement(), schedule, health),
        ),
    }
    clusters: dict[str, tuple] = {}  # name -> (client, tasks, servers)
    rates: dict[str, list[float]] = {name: [] for name in storages}
    lookup_rates: dict[str, float] = {}
    try:
        for name, (members, placement) in storages.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                members=members,
                placement=placement,
            )
            # Identical pre-seating in both clusters (see tracing_live: a
            # skewed provider split reads as a durable throughput delta).
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks, servers)
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        on_placement = storages["on"][1]
        if "lookup" not in on_placement.__dict__:
            raise RuntimeError("disabled wrapper is not in passthrough mode")

        async def batch(name: str) -> float:
            client = clusters[name][0]
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
            return total / elapsed

        for name in storages:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                o = await batch("off")
                r = await batch("on")
            else:
                r = await batch("on")
                o = await batch("off")
            rates["off"].append(o)
            rates["on"].append(r)
            ratios.append(o / r - 1.0)

        if schedule.injected_errors or schedule.injected_hangs:
            raise RuntimeError("disabled schedule injected faults during the A/B")

        # Cost ladder at the trait: bare dict-get, disabled passthrough,
        # armed-idle gated delegation (this is what a chaos soak pays while
        # no fault is actually firing).
        bare = storages["off"][1]
        lookup_rates["bare"] = await _lookup_rate(bare, lookup_ops)
        lookup_rates["disabled"] = await _lookup_rate(on_placement, lookup_ops)
        armed = FaultyObjectPlacement(
            LocalObjectPlacement(), FaultSchedule(seed=0), StorageHealth()
        )
        from ..object_placement import ObjectPlacementItem
        from ..registry import ObjectId, type_id

        await armed.update(
            ObjectPlacementItem(ObjectId(type_id(EchoActor), "w0"), "127.0.0.1:1")
        )
        lookup_rates["armed_idle"] = await _lookup_rate(armed, lookup_ops)
    finally:
        for client, tasks, _ in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks, _ in clusters.values() for t in tasks],
            return_exceptions=True,
        )

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "faults_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "lookup_ops_per_sec": {k: round(v, 1) for k, v in lookup_rates.items()},
        "lookup_overhead_disabled_pct": round(
            (lookup_rates["bare"] / lookup_rates["disabled"] - 1.0) * 100.0, 2
        ),
        "lookup_overhead_armed_idle_pct": round(
            (lookup_rates["bare"] / lookup_rates["armed_idle"] - 1.0) * 100.0, 2
        ),
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }
