"""Measured migration drain on a live in-process cluster.

The A/B evidence for the batched actuation pipeline (`bench.py` host
stage): boot two real servers on loopback — the :mod:`.routing_live`
harness shape — seat N stateful actors on one of them, each carrying a
volatile payload, then drain every seat to the other node through
``MigrationManager.apply_moves`` and report migrations/sec plus the
pinned-window distribution.

``measure_migration_drain`` runs the drain twice in the same process —
once with per-key actuation (burst size 1, no prefetch, no overlap: the
shape of the engine before batching) and once with the batched+prefetch
defaults — so the speedup ratio is anchored to one session's clock, the
same anchoring discipline as the rpc stage's in-session sqlite baseline.
A small throwaway drain warms codecs and the transport first so neither
measured mode pays first-use costs.

Every handoff here crossed a real TCP socket: pin, snapshot, install RPC,
directory flip — no simulation.
"""

from __future__ import annotations

import asyncio
import time

from .. import AppData, Client, LocalObjectPlacement, LocalStorage, Registry, Server
from .. import ServiceObject, handler, message
from ..cluster.membership_protocol import LocalClusterProvider
from ..commands import ServerInfo
from ..migration import MigrationConfig
from ..object_placement import ObjectPlacementItem
from ..registry import ObjectId, type_id


@message(name="migration_live.Warm")
class Warm:
    size: int = 0


@message(name="migration_live.Seen")
class Seen:
    address: str = ""


class DrainActor(ServiceObject):
    """Stateful actor whose volatile payload is the thing being moved."""

    def __init__(self):
        self.blob = b""

    def __migrate_state__(self):
        return {"blob": self.blob}

    def __restore_state__(self, value):
        self.blob = value["blob"]

    @handler
    async def warm(self, msg: Warm, ctx: AppData) -> Seen:
        # Per-object payload bytes: a cross-wired install would not
        # byte-compare equal against another object's snapshot.
        seed = self.id.encode() + b"\xa5"
        self.blob = (seed * (-(-msg.size // len(seed))))[: msg.size]
        return Seen(address=ctx.get(ServerInfo).address)


def per_key_config() -> MigrationConfig:
    """The pre-batching engine's shape: one key at a time, no prefetch."""
    return MigrationConfig(
        batch_size=1,
        per_node_inflight=1,
        global_inflight=1,
        handoff_concurrency=1,
        prefetch=False,
    )


async def _drain_once(
    n_objects: int,
    payload_bytes: int,
    config: MigrationConfig,
    *,
    transport: str = "asyncio",
) -> dict:
    """Boot a fresh 2-server cluster, seat+warm N actors on node 0, drain
    them all to node 1 under ``config``, and return the measured numbers.

    A fresh cluster per mode keeps the stats deltas and the directory
    state of the two measured drains independent.
    """
    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers: list[Server] = []
    tasks: list[asyncio.Task] = []
    try:
        for _ in range(2):
            s = Server(
                address="127.0.0.1:0",
                registry=Registry().add_type(DrainActor),
                cluster_provider=LocalClusterProvider(members),
                object_placement_provider=placement,
                transport=transport,
                migration_config=config,
            )
            await s.prepare()
            await s.bind()
            servers.append(s)
        tasks = [asyncio.create_task(s.run()) for s in servers]
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if len(await members.active_members()) >= 2:
                break
            await asyncio.sleep(0.02)
        src, dst = servers[0], servers[1]
        tname = type_id(DrainActor)
        keys = [f"d{i}" for i in range(n_objects)]
        # Seat every key on the source up front (the directory is
        # authoritative: first touch activates there), then warm them all
        # so each carries a live volatile payload worth migrating.
        for k in keys:
            await placement.update(
                ObjectPlacementItem(ObjectId(tname, k), src.local_address)
            )
        client = Client(members)
        try:
            gate = asyncio.Semaphore(64)

            async def warm(k: str) -> None:
                async with gate:
                    out = await client.send(
                        DrainActor, k, Warm(size=payload_bytes), returns=Seen
                    )
                    assert out.address == src.local_address, (k, out.address)

            await asyncio.gather(*(warm(k) for k in keys))

            stats = src.migration_manager.stats
            before_ms, before_windows = stats.pinned_ms_total, stats.pinned_windows
            moves = [(f"{tname}.{k}", src.local_address, dst.local_address) for k in keys]
            t0 = time.perf_counter()
            moved = await src.migration_manager.apply_moves(moves)
            dt = time.perf_counter() - t0
            windows = stats.pinned_windows - before_windows
            pinned_ms = stats.pinned_ms_total - before_ms
            return {
                "moved": moved,
                "seconds": round(dt, 3),
                "migrations_per_sec": round(moved / dt, 1) if dt > 0 else 0.0,
                "pinned_ms_mean": round(pinned_ms / windows, 4) if windows else None,
                "pinned_ms_max": round(stats.pinned_ms_max, 3),
                "bursts": stats.batches,
                "prefetch_hits": stats.prefetch_hits,
                "prefetch_misses": stats.prefetch_misses,
                "state_bytes": stats.state_bytes,
            }
        finally:
            client.close()
    finally:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


async def measure_migration_drain(
    n_objects: int = 1000,
    payload_bytes: int = 1024,
    *,
    transport: str = "asyncio",
) -> dict:
    """Per-key vs batched+prefetch drain of ``n_objects``, same session."""
    # Throwaway warm-up: codec schema caches, transport pools, first-GC.
    await _drain_once(16, payload_bytes, MigrationConfig(), transport=transport)
    per_key = await _drain_once(
        n_objects, payload_bytes, per_key_config(), transport=transport
    )
    batched = await _drain_once(
        n_objects, payload_bytes, MigrationConfig(), transport=transport
    )
    out: dict = {
        "n_objects": n_objects,
        "payload_bytes": payload_bytes,
        "per_key": per_key,
        "batched": batched,
    }
    if per_key["migrations_per_sec"]:
        out["speedup"] = round(
            batched["migrations_per_sec"] / per_key["migrations_per_sec"], 2
        )
    if per_key["pinned_ms_mean"] and batched["pinned_ms_mean"]:
        out["pinned_window_ratio"] = round(
            batched["pinned_ms_mean"] / per_key["pinned_ms_mean"], 3
        )
    return out
