"""Measured QoS scheduler cost and benefit on live clusters (ISSUE 20).

The QoS subsystem (``rio_tpu/qos``) makes two promises that only a paired
A/B on real sockets can price:

* **Uniform traffic is ~free** — unclassified requests ride a
  zero-wrapper fast path (admission is one branch chain; 7 of 8
  dispatches hand the transport the bare handler coroutine);
  ``qos_overhead_pct`` is the median per-batch paired off/on ratio
  under identical echo traffic (the ``journal_live`` discipline: both
  clusters coexist in one process, batch k's two runs alternate order
  and share the same seconds of box weather). Bar: ≤ 2%.
* **Overload protection is real** — a bulk tenant floods one hot object
  while an interactive tenant sends strict-priority probes at it.
  Per-object serialized execution is the contention: every request to
  the hot object queues FIFO at the object's lock for its service time.
  OFF, all bulk requests become ready handler tasks instantly and the
  probe parks behind the whole flood at the lock; ON, concurrent starts
  are capped and the probe's tier overtakes every parked bulk request —
  it waits behind at most the in-flight few. Bars: interactive p99 ≥ 3x
  better with QoS on, and ZERO interactive sheds (the flood never
  causes the scheduler to refuse the tenant it exists to protect).

Both halves bank into ``BENCH_DETAIL.cpu.json`` as a host stage: absolute
rates drift with box weather between sessions, only the paired ratios
mean anything — the stage never carries into a TPU bank
(``tests/test_bench_detail.py``).
"""

from __future__ import annotations

import asyncio
import gc
import statistics
import time

from .. import (
    AppData,
    Client,
    LocalObjectPlacement,
    LocalStorage,
    Registry,
    Server,
    ServiceObject,
    handler,
    message,
)
from ..cluster.membership_protocol import LocalClusterProvider
from ..qos import QosConfig
from .routing_live import Echo, EchoActor, boot_echo_cluster


@message(name="qos_live.Burn")
class Burn:
    """One request worth ``spin_s`` seconds of actor service time."""

    spin_s: float = 0.0005


class BurnActor(ServiceObject):
    """Overload-model actor: each request holds the object's serialized-
    execution lock for ``spin_s``, so a flood of them at one object is a
    FIFO queue every later arrival waits through. An ``asyncio.sleep``
    models the hold (I/O-bound service time) without burning loop CPU —
    in a one-process A/B, CPU burn would slow OFF and ON clusters alike
    and measure nothing."""

    @handler
    async def burn(self, msg: Burn, ctx: AppData) -> Burn:
        if msg.spin_s > 0:
            await asyncio.sleep(msg.spin_s)
        return msg


def build_burn_registry() -> Registry:
    return Registry().add_type(BurnActor)


async def _boot_burn_cluster(
    n_servers: int,
    *,
    transport: str = "asyncio",
    server_kwargs: dict | None = None,
):
    """``boot_echo_cluster`` with the burn registry (same teardown shape)."""
    members = LocalStorage()
    placement = LocalObjectPlacement()
    servers: list[Server] = []
    tasks: list[asyncio.Task] = []
    try:
        for _ in range(n_servers):
            s = Server(
                address="127.0.0.1:0",
                registry=build_burn_registry(),
                cluster_provider=LocalClusterProvider(members),
                object_placement_provider=placement,
                transport=transport,
                **(server_kwargs or {}),
            )
            await s.prepare()
            await s.bind()
            servers.append(s)
        tasks = [asyncio.create_task(s.run()) for s in servers]
        deadline = asyncio.get_event_loop().time() + 10.0
        while asyncio.get_event_loop().time() < deadline:
            if len(await members.active_members()) >= n_servers:
                break
            await asyncio.sleep(0.02)
    except BaseException:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)
        raise
    return members, placement, tasks, servers


async def measure_qos_overhead(
    *,
    n_servers: int = 2,
    n_workers: int = 32,
    requests_per_batch: int = 16,
    n_objects: int = 256,
    batches: int = 48,
    transport: str = "asyncio",
) -> dict:
    """A/B the RPC loop with the QoS scheduler off vs on, uniform traffic.

    The ON cluster runs the DEFAULT :class:`QosConfig` — the shipping
    configuration every request crosses once a node opts in. Uniform
    unclassified traffic stays on the zero-wrapper fast path (no queuing,
    no token buckets, no slot accounting), so the measured delta is the
    per-request cost of the admission branch chain plus the 1-in-8 timed
    RED sample. Batches are SHORT (~50 ms) and alternate off/on order:
    box weather is autocorrelated over seconds, so fine-grained pairs
    cancel it far better than a few long batches, and the median over
    many pairs shrugs off the bursts that straddle one.
    """
    modes = {"off": None, "on": QosConfig()}
    clusters: dict[str, tuple] = {}
    rates: dict[str, list[float]] = {name: [] for name in modes}
    try:
        for name, qos_config in modes.items():
            members, placement, tasks, servers = await boot_echo_cluster(
                n_servers,
                transport=transport,
                server_kwargs=(
                    {"qos_config": qos_config} if qos_config is not None else {}
                ),
            )
            from ..object_placement import ObjectPlacementItem
            from ..registry import ObjectId, type_id

            tname = type_id(EchoActor)
            for i in range(n_objects):
                await placement.update(
                    ObjectPlacementItem(
                        ObjectId(tname, f"w{i}"),
                        servers[i % n_servers].local_address,
                    )
                )
            client = Client(members, transport=transport)
            clusters[name] = (client, tasks, servers)
            for i in range(n_objects):
                await client.send(EchoActor, f"w{i}", Echo(value=i), returns=Echo)

        async def batch(name: str) -> float:
            client = clusters[name][0]
            total = n_workers * requests_per_batch

            async def worker(w: int) -> None:
                for r in range(requests_per_batch):
                    oid = f"w{(w * requests_per_batch + r) % n_objects}"
                    await client.send(EchoActor, oid, Echo(value=r), returns=Echo)

            gc.collect()
            gc.disable()
            try:
                t0 = time.perf_counter()
                await asyncio.gather(*[worker(w) for w in range(n_workers)])
                elapsed = time.perf_counter() - t0
            finally:
                gc.enable()
            return total / elapsed

        for name in modes:  # discarded warm batch per mode
            await batch(name)
        ratios: list[float] = []
        for k in range(batches):
            if k % 2 == 0:
                o = await batch("off")
                r = await batch("on")
            else:
                r = await batch("on")
                o = await batch("off")
            rates["off"].append(o)
            rates["on"].append(r)
            ratios.append(o / r - 1.0)
        on_servers = clusters["on"][2]
        admitted = sum(s.qos.stats.admitted for s in on_servers)
        if admitted <= 0:
            raise RuntimeError(
                "qos_config cluster admitted nothing — the A/B measured "
                "a scheduler that never saw the traffic"
            )
        if any(s.qos is not None for s in clusters["off"][2]):
            raise RuntimeError("qos-off cluster still built a scheduler")
    finally:
        for client, tasks, _ in clusters.values():
            client.close()
            for t in tasks:
                t.cancel()
        await asyncio.gather(
            *[t for _, tasks, _ in clusters.values() for t in tasks],
            return_exceptions=True,
        )

    return {
        "msgs_per_sec": {k: round(max(v), 1) for k, v in rates.items()},
        "qos_overhead_pct": round(statistics.median(ratios) * 100.0, 2),
        "admitted_on": int(admitted),
        "n_requests_per_batch": n_workers * requests_per_batch,
        "batches": batches,
    }


async def measure_qos_flood(
    *,
    n_servers: int = 2,
    bulk_workers: int = 48,
    interactive_probes: int = 80,
    spin_s: float = 0.002,
    max_concurrent: int = 4,
    transport: str = "asyncio",
) -> dict:
    """A/B interactive latency under a bulk flood of one hot object.

    Everything targets the SAME object, so per-object serialized
    execution is the contention: each request holds the object lock for
    ``spin_s``. OFF, every one of ``bulk_workers`` pipelined bulk
    requests becomes a handler task parked at that lock, and the probe
    joins the FIFO at position ~``bulk_workers`` (≈ ``bulk_workers *
    spin_s`` of wait). ON, the scheduler caps handler starts at
    ``max_concurrent`` — the rest of the flood parks in the fair ring —
    and the probe's strict-priority tier takes the next grant, so it
    waits behind at most the in-flight few. Returns per-mode interactive
    p50/p99 (ms), the paired p99 ratio, and the ON cluster's interactive
    shed count (contract: 0).
    """
    modes = {
        "off": None,
        "on": QosConfig(max_concurrent=max_concurrent),
    }
    out: dict[str, dict] = {}
    interactive_sheds = 0
    for name, qos_config in modes.items():
        members, placement, tasks, servers = await _boot_burn_cluster(
            n_servers,
            transport=transport,
            server_kwargs=(
                {"qos_config": qos_config} if qos_config is not None else {}
            ),
        )
        bulk_client = Client(members, transport=transport, tenant="bulk")
        inter_client = Client(
            members, transport=transport, tenant="frontend", priority=2
        )
        stop = asyncio.Event()
        bulk_done = 0
        try:
            # Seat the hot object before the flood: placement is not the
            # contention under test.
            await inter_client.send(
                BurnActor, "hot", Burn(spin_s=0.0), returns=Burn
            )

            async def flood(w: int) -> None:
                nonlocal bulk_done
                while not stop.is_set():
                    try:
                        await bulk_client.send(
                            BurnActor, "hot", Burn(spin_s=spin_s),
                            returns=Burn,
                        )
                        bulk_done += 1
                    except Exception:
                        if stop.is_set():
                            return
                        # A shed (retry exhausted) is legal under flood;
                        # keep the pressure on.
                        await asyncio.sleep(spin_s)

            flood_tasks = [
                asyncio.create_task(flood(w)) for w in range(bulk_workers)
            ]
            # Let the flood reach steady state before measuring.
            await asyncio.sleep(0.3)
            lat_ms: list[float] = []
            for _ in range(interactive_probes):
                t0 = time.perf_counter()
                await inter_client.send(
                    BurnActor, "hot", Burn(spin_s=spin_s), returns=Burn
                )
                lat_ms.append((time.perf_counter() - t0) * 1000.0)
            stop.set()
            await asyncio.gather(*flood_tasks, return_exceptions=True)
            lat_ms.sort()
            n = len(lat_ms)
            out[name] = {
                "interactive_p50_ms": round(lat_ms[n // 2], 3),
                "interactive_p99_ms": round(lat_ms[min(n - 1, (n * 99) // 100)], 3),
                "bulk_requests": int(bulk_done),
            }
            if name == "on":
                interactive_sheds = sum(
                    s.qos.stats.interactive_sheds for s in servers
                )
        finally:
            stop.set()
            bulk_client.close()
            inter_client.close()
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)

    off_p99 = out["off"]["interactive_p99_ms"]
    on_p99 = out["on"]["interactive_p99_ms"]
    return {
        "off": out["off"],
        "on": out["on"],
        "interactive_p99_improvement": round(off_p99 / max(on_p99, 1e-9), 2),
        "interactive_sheds_on": int(interactive_sheds),
        "bulk_workers": bulk_workers,
        "spin_s": spin_s,
        "max_concurrent_on": max_concurrent,
    }


async def measure_qos(*, transport: str = "asyncio", fast: bool = False) -> dict:
    """Both halves of the ``bench.py --qos`` stage, paired in-session."""
    overhead = await measure_qos_overhead(
        transport=transport, batches=16 if fast else 48
    )
    flood = await measure_qos_flood(
        transport=transport,
        interactive_probes=40 if fast else 80,
    )
    return {"uniform": overhead, "flood": flood}
